"""Per-replica keep-alive connection pool for the fleet gateway.

PR 3's ``gateway_overhead_bench`` put the gateway's added latency at
+6.2 ms median per request, and nearly all of it was connection churn:
every proxied request dialed a fresh TCP connection and tore it down
(dial + slow-start + TIME_WAIT on every hop). With ``utils/http.py``
serving HTTP/1.1 keep-alive, the gateway can instead hold a small
stack of warm connections per replica and reuse them:

- **LIFO reuse.** Idle connections are a per-replica stack; the most
  recently used connection is handed out first, so under light load
  one connection stays hot (warm TCP window, warm kernel path) while
  the rest age out.
- **Bounded.** At most ``max_idle`` idle connections per replica;
  each connection is retired after ``max_uses`` requests; idle
  connections older than ``idle_ttl`` are dropped at the next acquire
  rather than reused (the server's own idle reaper has a similar
  clock, and racing it is what the stale-redial path is for).
- **Health-aware.** The gateway evicts a replica's idle connections
  when the replica leaves the healthy set (drain/deregister/TTL
  expiry) and when any request to it raises ``UpstreamError`` — a
  replica that just failed one request cannot be trusted to honor the
  others' pooled connections either.
- **Stale detection.** A pooled connection can die between uses
  (server idle reap, replica restart). When a REUSED connection fails
  before yielding a single response byte, ``StaleConnection`` tells
  the caller a transparent redial is safe: the server cannot have
  processed a request it never answered a byte of, and generation
  requests are idempotent under a fixed seed besides.

``max_idle=0`` disables reuse entirely: every acquire dials and every
release closes — the per-dial baseline ``gateway_overhead_bench``
measures against.
"""
from __future__ import annotations

import asyncio
import time
from typing import Callable, Dict, List, Optional

__all__ = [
    "ConnectionPool",
    "PooledConnection",
    "StaleConnection",
    "UpstreamError",
]


class UpstreamError(RuntimeError):
    """Transport-level failure talking to one replica."""


class StaleConnection(UpstreamError):
    """A pooled connection died between uses (server idle reap,
    replica restart): raised only for REUSED connections that failed
    before any response byte arrived, so one transparent redial is
    always safe."""


class PooledConnection:
    """One upstream connection plus the bookkeeping reuse needs."""

    __slots__ = (
        "reader", "writer", "replica_id", "authority",
        "reused", "uses", "idle_since",
    )

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        replica_id: str,
        authority: str,
    ) -> None:
        self.reader = reader
        self.writer = writer
        self.replica_id = replica_id
        self.authority = authority
        self.reused = False  # True when handed out from the idle pool
        self.uses = 0
        self.idle_since = 0.0

    def close(self) -> None:
        self.writer.close()


# pool events the gateway mirrors into its prometheus counters
POOL_HIT = "hit"
POOL_MISS = "miss"
POOL_EVICTED = "evicted"


class ConnectionPool:
    """Bounded LIFO pool of idle keep-alive connections per replica."""

    def __init__(
        self,
        max_idle: int = 8,
        idle_ttl: float = 30.0,
        max_uses: int = 1000,
        on_event: Optional[Callable[[str, str], None]] = None,
    ) -> None:
        self.max_idle = max_idle
        self.idle_ttl = idle_ttl
        self.max_uses = max_uses
        self._on_event = on_event
        self._idle: Dict[str, List[PooledConnection]] = {}
        # plain counters for the /fleet JSON snapshot; the gateway's
        # prometheus counters are fed through on_event
        self.hits: Dict[str, int] = {}
        self.misses: Dict[str, int] = {}
        self.evicted: Dict[str, int] = {}

    def _event(self, table: Dict[str, int], event: str, rid: str) -> None:
        table[rid] = table.get(rid, 0) + 1
        if self._on_event is not None:
            self._on_event(event, rid)

    async def acquire(
        self, replica, connect_timeout: float
    ) -> PooledConnection:
        """Pop the freshest usable idle connection to ``replica``, or
        dial a new one. Raises UpstreamError when the dial fails.
        Concurrent acquires (retry legs, hedge legs) can never share a
        connection: an idle connection is handed to exactly one caller
        by the pop, and a dial is private to its caller."""
        stack = self._idle.get(replica.id)
        now = time.monotonic()
        while stack:
            conn = stack.pop()
            if (
                conn.writer.is_closing()
                or conn.reader.at_eof()
                or now - conn.idle_since > self.idle_ttl
            ):
                # already dead (server FIN arrived while idle) or aged
                # out: drop it rather than hand out a known-bad socket
                self._event(self.evicted, POOL_EVICTED, replica.id)
                conn.close()
                continue
            conn.reused = True
            self._event(self.hits, POOL_HIT, replica.id)
            return conn
        self._event(self.misses, POOL_MISS, replica.id)
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(replica.address, replica.port),
                connect_timeout,
            )
        except (OSError, asyncio.TimeoutError) as exc:
            raise UpstreamError(
                f"connect {replica.authority}: {exc}"
            ) from None
        return PooledConnection(reader, writer, replica.id, replica.authority)

    def release(self, conn: PooledConnection) -> None:
        """Return a connection whose response was FULLY read (and was
        Content-Length-framed with no ``Connection: close``) for
        reuse; retires it instead when the pool is full, reuse is
        disabled, or the connection hit its use cap."""
        conn.uses += 1
        stack = self._idle.setdefault(conn.replica_id, [])
        if (
            self.max_idle <= 0
            or len(stack) >= self.max_idle
            or conn.uses >= self.max_uses
            or conn.writer.is_closing()
        ):
            conn.close()
            return
        conn.reused = False
        conn.idle_since = time.monotonic()
        stack.append(conn)

    def discard(self, conn: PooledConnection) -> None:
        """Close a connection that must never be reused: transport
        failure, streamed (close-delimited) response, or a cancelled
        hedge/retry leg that may have left unread response bytes."""
        conn.close()

    def discard_stale(self, conn: PooledConnection) -> None:
        """Close a reused connection that died between uses; counted
        as an eviction (the reuse attempt was voided)."""
        self._event(self.evicted, POOL_EVICTED, conn.replica_id)
        conn.close()

    def evict(self, replica_id: str) -> int:
        """Drop every idle connection to one replica (it drained,
        deregistered, or just failed a request)."""
        stack = self._idle.pop(replica_id, [])
        for conn in stack:
            self._event(self.evicted, POOL_EVICTED, replica_id)
            conn.close()
        return len(stack)

    def prune(self, keep_ids) -> int:
        """Evict pools for replicas no longer in the healthy set."""
        return sum(
            self.evict(rid)
            for rid in list(self._idle)
            if rid not in keep_ids
        )

    def close_all(self) -> None:
        """Shutdown: close everything idle (not counted as eviction)."""
        for rid in list(self._idle):
            for conn in self._idle.pop(rid):
                conn.close()

    def idle_count(self, replica_id: str) -> int:
        return len(self._idle.get(replica_id, ()))

    def stats(self, replica_id: str) -> Dict[str, int]:
        """Per-replica snapshot for the /fleet JSON."""
        return {
            "idle": self.idle_count(replica_id),
            "hits": self.hits.get(replica_id, 0),
            "misses": self.misses.get(replica_id, 0),
            "evicted": self.evicted.get(replica_id, 0),
        }
