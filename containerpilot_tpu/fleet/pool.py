"""Per-replica keep-alive connection pool for the fleet gateway.

PR 3's ``gateway_overhead_bench`` put the gateway's added latency at
+6.2 ms median per request, and nearly all of it was connection churn:
every proxied request dialed a fresh TCP connection and tore it down
(dial + slow-start + TIME_WAIT on every hop). With ``utils/http.py``
serving HTTP/1.1 keep-alive, the gateway can instead hold a small
stack of warm connections per replica and reuse them:

- **LIFO reuse.** Idle connections are a per-replica stack; the most
  recently used connection is handed out first, so under light load
  one connection stays hot (warm TCP window, warm kernel path) while
  the rest age out.
- **Bounded.** At most ``max_idle`` idle connections per replica;
  each connection is retired after ``max_uses`` requests; idle
  connections older than ``idle_ttl`` are dropped at the next acquire
  rather than reused (the server's own idle reaper has a similar
  clock, and racing it is what the stale-redial path is for).
- **Health-aware.** The gateway evicts a replica's idle connections
  when the replica leaves the healthy set (drain/deregister/TTL
  expiry) and when any request to it raises ``UpstreamError`` — a
  replica that just failed one request cannot be trusted to honor the
  others' pooled connections either.
- **Stale detection.** A pooled connection can die between uses
  (server idle reap, replica restart). When a REUSED connection fails
  before yielding a single response byte, ``StaleConnection`` tells
  the caller a transparent redial is safe: the server cannot have
  processed a request it never answered a byte of, and generation
  requests are idempotent under a fixed seed besides.

``max_idle=0`` disables reuse entirely: every acquire dials and every
release closes — the per-dial baseline ``gateway_overhead_bench``
measures against.

**cp-mux/1 multiplexing** (PR 8) collapses the pool further: with
``mux=True`` (the default) the pool keeps ONE warm upgraded
connection per replica and carries every concurrent request to that
replica as an interleaved stream on it — gateway concurrency stops
being bounded by socket count, an SSE stream no longer pins a
connection for its lifetime, and a cancelled hedge leg or abandoned
client costs a CANCEL frame instead of a teardown. The upgrade is
negotiated per connection (``MuxConnection`` speaks the
``utils.http`` frame codec); a replica that declines it is remembered
as mux-unsupported and its traffic takes the classic pooled path
above — including the very socket the probe dialed, which is drained
and pooled rather than wasted. A mux connection that dies fails every
in-flight stream **exactly once** (each failure arms the caller's
retry/hedge exactly like a classic transport error — no stream is
ever silently redispatched), and the next acquire redials.
"""
from __future__ import annotations

import asyncio
import json
import logging
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Set, Tuple

from ..telemetry import tracing
from ..utils.http import (
    FRAME_CANCEL,
    FRAME_DATA,
    FRAME_END,
    FRAME_HEAD,
    FRAME_HEADERS,
    FRAME_PING,
    FRAME_PONG,
    FRAME_TYPES,
    FRAME_WINDOW,
    MUX_MAX_FRAME,
    MUX_PROTOCOL,
    MUX_UPGRADE_PATH,
    encode_frame,
)

__all__ = [
    "ConnectionPool",
    "MuxConnection",
    "MuxStream",
    "MuxStreamError",
    "PooledConnection",
    "StaleConnection",
    "StaleMuxConnection",
    "UpstreamError",
]


log = logging.getLogger("containerpilot.fleet")


class UpstreamError(RuntimeError):
    """Transport-level failure talking to one replica."""


class StaleConnection(UpstreamError):
    """A pooled connection died between uses (server idle reap,
    replica restart): raised only for REUSED connections that failed
    before any response byte arrived, so one transparent redial is
    always safe."""


class StaleMuxConnection(UpstreamError):
    """The shared mux connection died between the acquire and this
    stream's open (idle reap, replica restart): the server saw none
    of this request, so one transparent redial is safe — the mux
    analog of StaleConnection. Never raised by a freshly dialed
    connection, which bounds the redial loop at one."""


class MuxStreamError(UpstreamError):
    """One stream failed on a connection that is still healthy
    (per-stream deadline, server-side stream abort): the co-resident
    streams are fine, so the caller must NOT evict the replica's
    connections — cancel this stream and move on."""


class PooledConnection:
    """One upstream connection plus the bookkeeping reuse needs."""

    __slots__ = (
        "reader", "writer", "replica_id", "authority",
        "reused", "uses", "idle_since",
    )

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        replica_id: str,
        authority: str,
    ) -> None:
        self.reader = reader
        self.writer = writer
        self.replica_id = replica_id
        self.authority = authority
        self.reused = False  # True when handed out from the idle pool
        self.uses = 0
        self.idle_since = 0.0

    def close(self) -> None:
        self.writer.close()


class MuxStream:
    """Client-side handle for one in-flight stream: a deque of events
    the connection's read loop pushes (response head, DATA chunks,
    END, errors) drained by the request's own task. Waits use a plain
    Event plus a timer handle — no Task-per-read, the same economy
    ``utils.http.timed_read`` buys the HTTP/1.1 hot path."""

    __slots__ = (
        "conn", "sid", "status", "headers", "ended",
        "_buf", "_event", "_expired",
    )

    def __init__(self, conn: "MuxConnection", sid: int) -> None:
        self.conn = conn
        self.sid = sid
        self.status: Optional[int] = None
        self.headers: Dict[str, str] = {}
        self.ended = False
        self._buf: Deque[Tuple] = deque()
        self._event = asyncio.Event()
        self._expired = False

    # -- read-loop side ----------------------------------------------

    def push(self, item: Tuple) -> None:
        self._buf.append(item)
        self._event.set()

    # -- consumer side -----------------------------------------------

    def _expire(self) -> None:
        self._expired = True
        self._event.set()

    async def _next(self, timeout: float) -> Tuple:
        while not self._buf:
            self._event.clear()
            self._expired = False
            handle = asyncio.get_event_loop().call_later(
                timeout, self._expire
            )
            try:
                await self._event.wait()
            finally:
                handle.cancel()
            if self._expired and not self._buf:
                raise MuxStreamError(
                    f"{self.conn.authority}: stream {self.sid} timed "
                    f"out after {timeout}s"
                )
        return self._buf.popleft()

    async def response_head(
        self, timeout: float
    ) -> Tuple[int, Dict[str, str]]:
        kind, payload = await self._next(timeout)
        if kind == "err":
            self.ended = True
            raise payload
        if kind != "head":
            self.ended = True
            raise MuxStreamError(
                f"{self.conn.authority}: stream {self.sid} got "
                f"{kind!r} before the response head"
            )
        self.status, self.headers = payload
        return self.status, self.headers

    async def read_chunk(self, timeout: float) -> bytes:
        """The next DATA chunk, or b"" once the stream ended. Credit
        is granted back only as chunks are CONSUMED here, so a relay
        whose downstream stalls stops refilling the sender's window —
        that is the whole per-stream backpressure loop."""
        if self.ended:
            return b""
        kind, payload = await self._next(timeout)
        if kind == "data":
            if not (self._buf and self._buf[0][0] == "end"):
                # skip the refill when END is already buffered: a
                # buffered response would otherwise pay a whole extra
                # socket send (and the server an extra wakeup) per
                # request for credit nobody will ever spend
                self.conn.grant(self.sid, len(payload))
            return payload
        self.ended = True
        if kind == "end":
            return b""
        if kind == "err":
            raise payload
        raise MuxStreamError(
            f"{self.conn.authority}: stream {self.sid} got "
            f"unexpected {kind!r} mid-body"
        )

    async def read_body(self, timeout: float, cap: int) -> bytes:
        chunks: List[bytes] = []
        total = 0
        while True:
            chunk = await self.read_chunk(timeout)
            if not chunk:
                return b"".join(chunks)
            total += len(chunk)
            if total > cap:
                self.cancel()
                raise MuxStreamError(
                    f"{self.conn.authority}: stream {self.sid} body "
                    f"exceeds {cap}-byte cap"
                )
            chunks.append(chunk)

    def cancel(self) -> bool:
        """Abort this stream with a CANCEL frame, leaving the shared
        connection in service. Returns True when a live stream was
        actually cancelled (the caller's 'a teardown was saved'
        signal); a stream that already ended, or whose connection is
        already dead, has nothing to cancel."""
        if self.ended:
            return False
        self.ended = True
        return self.conn.cancel_stream(self.sid)


class _MuxClientProtocol(asyncio.Protocol):
    """Client frame parser living AT the transport-protocol layer:
    complete frames are parsed and routed to stream handles
    synchronously inside ``data_received``, so a response wakes the
    awaiting request task DIRECTLY — no intermediate reader task, no
    per-read future machinery. This is what keeps mux's per-request
    cost at parity with the classic keep-alive path at concurrency 1
    (a reader-task design pays one extra task switch per response)."""

    def __init__(self, conn: "MuxConnection") -> None:
        self.conn = conn
        self.buf = bytearray()
        self.paused = False
        self.drained = asyncio.Event()
        self.drained.set()

    def connection_made(self, transport) -> None:  # pragma: no cover
        pass  # the transport was adopted mid-life; conn holds it

    def data_received(self, data: bytes) -> None:
        buf = self.buf
        buf += data
        head_size = FRAME_HEAD.size
        pos = 0
        end = len(buf)
        conn = self.conn
        while end - pos >= head_size:
            length, ftype, sid = FRAME_HEAD.unpack_from(buf, pos)
            if ftype not in FRAME_TYPES or length > MUX_MAX_FRAME:
                conn.protocol_error(f"bad frame ({ftype}, {length})")
                return
            if end - pos < head_size + length:
                break
            payload = bytes(buf[pos + head_size:pos + head_size + length])
            pos += head_size + length
            if not conn.on_frame(ftype, sid, payload):
                return  # protocol error already handled
        del buf[:pos]

    def connection_lost(self, exc: Optional[Exception]) -> None:
        self.drained.set()  # never leave a drain waiter hanging
        self.conn._die(UpstreamError(
            f"{self.conn.authority}: mux connection died: "
            f"{exc or 'EOF'}"
        ))

    def pause_writing(self) -> None:
        self.paused = True
        self.drained.clear()

    def resume_writing(self) -> None:
        self.paused = False
        self.drained.set()


class MuxConnection:
    """One upgraded cp-mux/1 connection carrying many interleaved
    streams to a single replica. Frames are parsed at the protocol
    layer (_MuxClientProtocol) and routed to per-stream handles;
    death (EOF, reset, protocol violation) fails every in-flight
    stream exactly once and marks the connection for replacement at
    the next acquire."""

    def __init__(self, replica_id: str, authority: str) -> None:
        self.replica_id = replica_id
        self.authority = authority
        self.dead = False
        self.dead_exc: Optional[UpstreamError] = None
        #: False only between the dial and the first acquire-reuse:
        #: the stale-redial discipline keys off it
        self.reused = False
        self.streams: Dict[int, MuxStream] = {}
        self.streams_opened = 0
        self._next_id = 1
        self._transport = None
        self._protocol: Optional[_MuxClientProtocol] = None
        self._pongs: Dict[bytes, asyncio.Event] = {}
        #: (method, path) -> encoded head; (method, path, True) ->
        #: (prefix, suffix) template the trace id splices between
        self._head_cache: Dict[Tuple, object] = {}

    @property
    def active_streams(self) -> int:
        return len(self.streams)

    def adopt(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        """Take over the freshly upgraded socket from its stream pair:
        swap the transport's protocol for the frame parser. Any bytes
        the server raced onto the wire after its 101 are replayed out
        of the StreamReader's buffer first."""
        transport = writer.transport
        protocol = _MuxClientProtocol(self)
        leftover = b""
        buffered = getattr(reader, "_buffer", None)
        if buffered:
            leftover = bytes(buffered)
            buffered.clear()
        transport.set_protocol(protocol)
        self._transport = transport
        self._protocol = protocol
        try:
            if not transport.is_reading():
                transport.resume_reading()
        except (RuntimeError, AttributeError):
            log.debug("mux: transport resume after adopt not needed")
        if leftover:
            protocol.data_received(leftover)

    async def open_stream(
        self,
        method: str,
        path: str,
        body: bytes = b"",
        headers: Optional[Dict[str, str]] = None,
        trace_id: Optional[str] = None,
    ) -> MuxStream:
        """Send HEADERS(+DATA)+END for a new stream in one write and
        return its handle. ``trace_id`` rides the HEADERS frame as
        ``x-cp-trace`` — the mux half of cross-hop trace propagation
        — spliced into a cached head template so the (every-request)
        traced path pays no per-request JSON encode. A send that
        bounces off a dead connection raises StaleMuxConnection when
        the connection came warm from the pool (redial-safe: the
        server answered nothing for this stream) and plain
        UpstreamError for a fresh dial."""
        if self.dead:
            raise self._send_failure("connection already dead")
        sid = self._next_id
        self._next_id += 1
        if self._next_id >= 1 << 32:
            self._next_id = 1
        if headers:
            merged = {"content-type": "application/json", **headers}
            if trace_id:
                merged.setdefault("x-cp-trace", trace_id)
            head = json.dumps({
                "method": method,
                "path": path,
                "headers": merged,
            }).encode()
        else:
            # the hot path sends the same few heads over and over
            # (generate/completions/score); cache their encoding. The
            # traced variant caches a (prefix, suffix) template the
            # splice-safe trace id splices between — minted ids are
            # hex by construction and adopted ids pass
            # tracing.safe_id at the gateway, but re-check here: an
            # unsafe id through the template is a JSON injection
            # into the upstream HEADERS frame
            if trace_id and tracing.safe_id(trace_id) is None:
                head = json.dumps({
                    "method": method,
                    "path": path,
                    "headers": {
                        "content-type": "application/json",
                        "x-cp-trace": trace_id,
                    },
                }).encode()
            elif trace_id:
                parts = self._head_cache.get((method, path, True))
                if parts is None:
                    template = json.dumps({
                        "method": method,
                        "path": path,
                        "headers": {
                            "content-type": "application/json",
                            "x-cp-trace": "@TRACE-ID@",
                        },
                    }).encode().split(b'"@TRACE-ID@"')
                    # a method/path containing the placeholder would
                    # tear the template; no API path does, but fall
                    # back to a plain encode rather than mis-splice
                    parts = (
                        (template[0] + b'"', b'"' + template[1])
                        if len(template) == 2 else None
                    )
                    self._head_cache[(method, path, True)] = parts
                if parts is not None:
                    head = parts[0] + trace_id.encode() + parts[1]
                else:
                    head = json.dumps({
                        "method": method,
                        "path": path,
                        "headers": {
                            "content-type": "application/json",
                            "x-cp-trace": trace_id,
                        },
                    }).encode()
            else:
                head = self._head_cache.get((method, path))
                if head is None:
                    head = json.dumps({
                        "method": method,
                        "path": path,
                        "headers": {
                            "content-type": "application/json"
                        },
                    }).encode()
                    self._head_cache[(method, path)] = head
        frames = encode_frame(FRAME_HEADERS, sid, head)
        if body:
            frames += encode_frame(FRAME_DATA, sid, body)
        frames += encode_frame(FRAME_END, sid)
        stream = MuxStream(self, sid)
        self.streams[sid] = stream
        self.streams_opened += 1
        try:
            self._transport.write(frames)
        except (ConnectionError, OSError) as exc:
            self.streams.pop(sid, None)
            self._die(UpstreamError(f"{self.authority}: {exc}"))
            raise self._send_failure(str(exc)) from None
        if self._protocol.paused:
            # transport backpressure (rare: the socket buffer filled);
            # wait it out so opens can't pile unbounded bytes
            await self._protocol.drained.wait()
            if self.dead:
                self.streams.pop(sid, None)
                raise self._send_failure("connection died during drain")
        return stream

    def _send_failure(self, msg: str) -> UpstreamError:
        if self.reused:
            return StaleMuxConnection(
                f"{self.authority}: mux connection died between "
                f"uses ({msg})"
            )
        return UpstreamError(f"{self.authority}: {msg}")

    def grant(self, sid: int, n: int) -> None:
        """Refill the server's send window for one stream; fire-and-
        forget (tiny frame — a dead transport surfaces through
        connection_lost, not here)."""
        if self.dead or n <= 0:
            return
        try:
            self._transport.write(
                encode_frame(FRAME_WINDOW, sid, n.to_bytes(4, "big"))
            )
        except (ConnectionError, OSError):
            log.debug("mux: WINDOW write found %s gone", self.authority)

    def cancel_stream(self, sid: int) -> bool:
        stream = self.streams.pop(sid, None)
        if self.dead:
            return False
        try:
            self._transport.write(encode_frame(FRAME_CANCEL, sid))
        except (ConnectionError, OSError):
            return False
        return stream is not None

    async def ping(self, timeout: float = 5.0) -> bool:
        """Round-trip liveness probe (tests, warmup)."""
        if self.dead:
            return False
        nonce = str(self.streams_opened).encode() + b":" + str(
            id(self)
        ).encode()
        event = asyncio.Event()
        self._pongs[nonce] = event
        try:
            self._transport.write(encode_frame(FRAME_PING, 0, nonce))
            await asyncio.wait_for(event.wait(), timeout)
            return True
        except (ConnectionError, OSError, asyncio.TimeoutError):
            return False
        finally:
            self._pongs.pop(nonce, None)

    def on_frame(self, ftype: int, sid: int, payload: bytes) -> bool:
        """Route one parsed frame; called synchronously from the
        protocol's data_received. Returns False when the frame killed
        the connection (protocol violation)."""
        if ftype == FRAME_HEADERS:
            stream = self.streams.get(sid)
            if stream is None:
                return True  # cancelled: late frames are noise
            try:
                head = json.loads(payload.decode())
                status = int(head["status"])
                headers = {
                    str(k).lower(): str(v)
                    for k, v in (head.get("headers") or {}).items()
                }
            except (ValueError, KeyError, TypeError,
                    UnicodeDecodeError) as exc:
                self.protocol_error(f"malformed response head: {exc}")
                return False
            stream.push(("head", (status, headers)))
        elif ftype == FRAME_DATA:
            stream = self.streams.get(sid)
            if stream is not None:
                stream.push(("data", payload))
        elif ftype == FRAME_END:
            stream = self.streams.pop(sid, None)
            if stream is not None:
                stream.push(("end", None))
        elif ftype == FRAME_CANCEL:
            stream = self.streams.pop(sid, None)
            if stream is not None:
                stream.push((
                    "err",
                    MuxStreamError(
                        f"{self.authority}: stream {sid} cancelled "
                        f"by the server"
                    ),
                ))
        elif ftype == FRAME_PONG:
            event = self._pongs.get(bytes(payload))
            if event is not None:
                event.set()
        elif ftype == FRAME_PING:
            self._transport.write(encode_frame(FRAME_PONG, sid, payload))
        # FRAME_WINDOW: request bodies aren't windowed; ignore
        return True

    def protocol_error(self, msg: str) -> None:
        self._die(UpstreamError(
            f"{self.authority}: mux protocol error: {msg}"
        ))

    def _die(self, exc: UpstreamError) -> None:
        """Fail every in-flight stream EXACTLY once: the stream table
        is drained here, so neither a late frame nor a second close
        can deliver a second error — each in-flight request surfaces
        one UpstreamError, arming one retry/hedge, and none is ever
        silently redispatched."""
        if self.dead:
            return
        self.dead = True
        self.dead_exc = exc
        failed = list(self.streams.values())
        self.streams.clear()
        for stream in failed:
            if stream.status is None and self.reused:
                # this stream got ZERO response bytes on a warm
                # connection that just died — the classic keep-alive
                # stale heuristic applies (overwhelmingly the idle
                # reaper racing the send), so the caller may redial
                # and resend ONCE. A stream whose head already
                # arrived gets the plain error: response bytes prove
                # the server took it, resending could double-apply.
                stream.push(("err", StaleMuxConnection(
                    f"{self.authority}: connection died before "
                    f"stream {stream.sid} got any response ({exc})"
                )))
            else:
                stream.push(("err", exc))
        if self._transport is not None:
            self._transport.close()

    def close(self, reason: str = "connection closed") -> None:
        """Tear down (eviction, shutdown): in-flight streams fail
        once and the transport closes."""
        self._die(UpstreamError(f"{self.authority}: {reason}"))


def _parse_head(
    head_blob: bytes, authority: str
) -> Tuple[int, Dict[str, str]]:
    """Status + lowercased headers from one response head blob;
    raises UpstreamError on garbage (the upgrade probe's only
    parser — the request path proper parses in gateway.py)."""
    lines = head_blob.split(b"\r\n")
    parts = lines[0].decode("latin-1", "replace").split(None, 2)
    if len(parts) < 2 or not parts[1].isascii() or not parts[1].isdigit():
        raise UpstreamError(
            f"{authority}: malformed status line {lines[0]!r}"
        )
    headers: Dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        key, _, value = line.decode("latin-1", "replace").partition(":")
        headers[key.strip().lower()] = value.strip()
    return int(parts[1]), headers


# pool events the gateway mirrors into its prometheus counters
POOL_HIT = "hit"
POOL_MISS = "miss"
POOL_EVICTED = "evicted"


class ConnectionPool:
    """Bounded LIFO pool of idle keep-alive connections per replica."""

    def __init__(
        self,
        max_idle: int = 8,
        idle_ttl: float = 30.0,
        max_uses: int = 1000,
        on_event: Optional[Callable[[str, str], None]] = None,
        mux: bool = True,
    ) -> None:
        self.max_idle = max_idle
        self.idle_ttl = idle_ttl
        self.max_uses = max_uses
        self.mux = mux
        self._on_event = on_event
        self._idle: Dict[str, List[PooledConnection]] = {}
        # cp-mux/1: ONE warm multiplexed connection per replica; the
        # classic idle stacks above become the fallback for replicas
        # that declined the upgrade (and the per-dial baseline)
        self._mux_conns: Dict[str, MuxConnection] = {}
        self._mux_unsupported: Set[str] = set()
        # in-flight upgrade dials, so a cold burst of N concurrent
        # acquires shares ONE dial instead of stampeding N sockets
        self._mux_dialing: Dict[str, "asyncio.Task"] = {}
        # plain counters for the /fleet JSON snapshot; the gateway's
        # prometheus counters are fed through on_event
        self.hits: Dict[str, int] = {}
        self.misses: Dict[str, int] = {}
        self.evicted: Dict[str, int] = {}

    def _event(self, table: Dict[str, int], event: str, rid: str) -> None:
        table[rid] = table.get(rid, 0) + 1
        if self._on_event is not None:
            self._on_event(event, rid)

    async def acquire(
        self, replica, connect_timeout: float
    ) -> PooledConnection:
        """Pop the freshest usable idle connection to ``replica``, or
        dial a new one. Raises UpstreamError when the dial fails.
        Concurrent acquires (retry legs, hedge legs) can never share a
        connection: an idle connection is handed to exactly one caller
        by the pop, and a dial is private to its caller."""
        stack = self._idle.get(replica.id)
        now = time.monotonic()
        while stack:
            conn = stack.pop()
            if (
                conn.writer.is_closing()
                or conn.reader.at_eof()
                or now - conn.idle_since > self.idle_ttl
            ):
                # already dead (server FIN arrived while idle) or aged
                # out: drop it rather than hand out a known-bad socket
                self._event(self.evicted, POOL_EVICTED, replica.id)
                conn.close()
                continue
            conn.reused = True
            self._event(self.hits, POOL_HIT, replica.id)
            return conn
        self._event(self.misses, POOL_MISS, replica.id)
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(replica.address, replica.port),
                connect_timeout,
            )
        except (OSError, asyncio.TimeoutError) as exc:
            raise UpstreamError(
                f"connect {replica.authority}: {exc}"
            ) from None
        return PooledConnection(reader, writer, replica.id, replica.authority)

    async def acquire_mux(
        self, replica, connect_timeout: float
    ) -> Optional[MuxConnection]:
        """The replica's shared mux connection, dialing and upgrading
        on first use. Returns None when mux is off or the replica
        declined the upgrade — the caller's signal to take the
        classic pooled path. Raises UpstreamError when the dial or
        the upgrade exchange transport-fails.

        Unlike ``acquire``, the returned connection is SHARED: any
        number of concurrent callers may hold it, each opening their
        own streams on it."""
        if not self.mux:
            return None
        conn = self._mux_conns.get(replica.id)
        if conn is not None:
            if not conn.dead:
                conn.reused = True
                return conn
            self._mux_conns.pop(replica.id, None)
        if replica.id in self._mux_unsupported:
            return None
        dial = self._mux_dialing.get(replica.id)
        if dial is None:
            dial = asyncio.ensure_future(
                self._dial_mux(replica, connect_timeout)
            )
            self._mux_dialing[replica.id] = dial
            dial.add_done_callback(
                lambda _t, rid=replica.id: self._mux_dialing.pop(rid, None)
            )
        # shield: a caller cancelled mid-dial (losing hedge leg) must
        # not kill the dial its co-acquirers are waiting on
        return await asyncio.shield(dial)

    async def _dial_mux(
        self, replica, connect_timeout: float
    ) -> Optional[MuxConnection]:
        """Dial + upgrade one mux connection (the single shared dial
        behind acquire_mux). Returns None when the replica declined
        the upgrade; raises UpstreamError on transport failure —
        every waiter sees the same outcome."""
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(replica.address, replica.port),
                connect_timeout,
            )
        except (OSError, asyncio.TimeoutError) as exc:
            raise UpstreamError(
                f"connect {replica.authority}: {exc}"
            ) from None
        try:
            writer.write(
                (
                    f"GET {MUX_UPGRADE_PATH} HTTP/1.1\r\n"
                    f"Host: {replica.authority}\r\n"
                    f"Connection: Upgrade\r\n"
                    f"Upgrade: {MUX_PROTOCOL}\r\n\r\n"
                ).encode()
            )
            await writer.drain()
            head_blob = await asyncio.wait_for(
                reader.readuntil(b"\r\n\r\n"), connect_timeout
            )
        except (
            OSError, ConnectionError, asyncio.TimeoutError,
            asyncio.IncompleteReadError, asyncio.LimitOverrunError,
        ) as exc:
            writer.close()
            raise UpstreamError(
                f"mux upgrade {replica.authority}: {exc}"
            ) from None
        try:
            status, headers = _parse_head(head_blob, replica.authority)
        except UpstreamError:
            writer.close()
            raise
        if status != 101:
            # the replica speaks plain HTTP/1.1 only (older build or
            # --no-mux): remember that, drain the declined answer, and
            # pool the already-dialed socket for the classic path so
            # the probe costs nothing
            self._mux_unsupported.add(replica.id)
            if not await self._drain_decline(reader, headers):
                writer.close()
                return None
            self.release(
                PooledConnection(
                    reader, writer, replica.id, replica.authority
                )
            )
            return None
        conn = MuxConnection(replica.id, replica.authority)
        conn.adopt(reader, writer)
        self._mux_conns[replica.id] = conn
        return conn

    @staticmethod
    async def _drain_decline(reader, headers: Dict[str, str]) -> bool:
        """Read the declined upgrade's body off the socket so it can
        be pooled; False when the response isn't cleanly framed."""
        raw = headers.get("content-length", "")
        if not raw.isascii() or not raw.isdigit():
            return False
        if "close" in headers.get("connection", "").lower():
            return False
        try:
            await reader.readexactly(int(raw))
        except (OSError, asyncio.IncompleteReadError):
            return False
        return True

    def mux_stats(self, replica_id: str) -> Dict[str, object]:
        """Per-replica mux snapshot for the /fleet JSON."""
        conn = self._mux_conns.get(replica_id)
        return {
            "enabled": self.mux,
            "connected": conn is not None and not conn.dead,
            "active_streams": conn.active_streams if conn else 0,
            "streams_opened": conn.streams_opened if conn else 0,
            "unsupported": replica_id in self._mux_unsupported,
        }

    def release(self, conn: PooledConnection) -> None:
        """Return a connection whose response was FULLY read (and was
        Content-Length-framed with no ``Connection: close``) for
        reuse; retires it instead when the pool is full, reuse is
        disabled, or the connection hit its use cap."""
        conn.uses += 1
        stack = self._idle.setdefault(conn.replica_id, [])
        if (
            self.max_idle <= 0
            or len(stack) >= self.max_idle
            or conn.uses >= self.max_uses
            or conn.writer.is_closing()
        ):
            conn.close()
            return
        conn.reused = False
        conn.idle_since = time.monotonic()
        stack.append(conn)

    def discard(self, conn: PooledConnection) -> None:
        """Close a connection that must never be reused: transport
        failure, streamed (close-delimited) response, or a cancelled
        hedge/retry leg that may have left unread response bytes."""
        conn.close()

    def discard_stale(self, conn: PooledConnection) -> None:
        """Close a reused connection that died between uses; counted
        as an eviction (the reuse attempt was voided)."""
        self._event(self.evicted, POOL_EVICTED, conn.replica_id)
        conn.close()

    def evict(self, replica_id: str) -> int:
        """Drop every idle connection to one replica (it drained,
        deregistered, or just failed a request). The replica's mux
        connection goes too — its in-flight streams fail exactly once
        (idempotent when the failure that triggered this eviction
        already killed it) — and the mux-unsupported memory is
        cleared, so a restarted replica gets a fresh upgrade probe."""
        stack = self._idle.pop(replica_id, [])
        for conn in stack:
            self._event(self.evicted, POOL_EVICTED, replica_id)
            conn.close()
        evicted = len(stack)
        mux = self._mux_conns.pop(replica_id, None)
        if mux is not None:
            if not mux.dead:
                self._event(self.evicted, POOL_EVICTED, replica_id)
                evicted += 1
            mux.close("replica evicted from the pool")
        self._mux_unsupported.discard(replica_id)
        return evicted

    def prune(self, keep_ids) -> int:
        """Evict pools for replicas no longer in the healthy set —
        including bare mux-unsupported memory with no live
        connections, so a replica that re-registers under the same id
        after an upgrade gets a fresh probe."""
        gone = (
            set(self._idle) | set(self._mux_conns) | self._mux_unsupported
        ) - set(keep_ids)
        return sum(self.evict(rid) for rid in gone)

    def close_all(self) -> None:
        """Shutdown: close everything idle (not counted as eviction)."""
        for rid in list(self._idle):
            for conn in self._idle.pop(rid):
                conn.close()
        for rid in list(self._mux_conns):
            self._mux_conns.pop(rid).close("pool shutdown")

    def idle_count(self, replica_id: str) -> int:
        return len(self._idle.get(replica_id, ()))

    def stats(self, replica_id: str) -> Dict[str, int]:
        """Per-replica snapshot for the /fleet JSON."""
        return {
            "idle": self.idle_count(replica_id),
            "hits": self.hits.get(replica_id, 0),
            "misses": self.misses.get(replica_id, 0),
            "evicted": self.evicted.get(replica_id, 0),
        }
