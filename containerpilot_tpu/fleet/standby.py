"""Warm-standby pool + peer weight transfer: scale-up as *promotion*.

PR 12's ledger put numbers on the cold-start tax: a scale event pays
process boot + weight init + XLA compile (measured 0.4-5.4s
time-to-first-routed-token on the lab box) before the new replica
serves its first token — which is why ``burst_10x`` survives by
shedding, not by growing. This module collapses that tax from three
directions, composed:

- **Warm-standby pool** (``StandbyLauncher``): the autoscaler keeps
  ``standby_count`` replicas fully booted — weights loaded,
  warmup-compiled, registered in the catalog under the ``standby``
  role (heartbeating, never routed to; the gateway excludes them from
  ``_pick`` and admission capacity). A scale event *promotes* one
  (``POST /v3/standby/promote`` flips the role and ``/health``
  semantics in one assignment) instead of launching, and the pool is
  refilled in the background with equal-jitter backoff. Kill-repair
  rides the same path: the autoscaler's below-min relaunch goes
  through ``launch()``, which promotes when a standby is warm.
- **Peer weight transfer over cp-mux/1** (``fetch_params``): a fresh
  standby fetches model weights from an already-warm peer replica as
  a framed mux stream (``GET /v1/weights``) — digest-verified chunks,
  resume-at-chunk-boundary with ONE transparent redial per the pool's
  stale-connection discipline — instead of re-reading a checkpoint or
  re-initializing. ANY failure (declined upgrade, digest mismatch,
  second connection death, shape mismatch) returns None and the
  caller falls back to its disk/init load: transfer is an
  accelerator, never a new failure mode.
- **Shared compile cache** (workload/modelcfg.py): replicas advertise
  their XLA compile-cache dir through heartbeat notes (``cc=``);
  launches on the same host adopt it and skip already-marked warmup
  buckets, so ``compile_warmup`` seconds collapse release-over-
  release. The marker helpers live in modelcfg next to
  ``enable_compile_cache``; this module only defines the roles and
  the transfer wire.

Wire format for ``GET /v1/weights`` (one close-delimited stream,
preferably carried as a cp-mux/1 stream so the transfer interleaves
with the peer's live traffic):

    u64 manifest_len | manifest JSON | chunk bytes back-to-back

The manifest names every leaf (flattened in ``jax.tree_util`` order:
path, dtype, shape, byte length) and every chunk (owning leaf, offset,
length, blake2b-8 digest). ``?chunk=K`` re-serves from flat chunk
index K — the resume point after a connection death is simply the
number of fully verified chunks already received. Serialization is
deterministic (numpy ``tobytes`` of the device-fetched leaf), so a
resumed stream's digests match the first attempt's manifest.
"""
from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import random
from typing import Any, Dict, List, Optional, Set, Tuple

from ..utils.tasks import spawn
from .pool import ConnectionPool, UpstreamError

log = logging.getLogger("containerpilot.fleet")

#: replica roles as they ride catalog heartbeat notes (``role=``);
#: an absent field means active, so promotion is visible the moment
#: the first post-promote beat lands
ROLE_ACTIVE = "active"
ROLE_STANDBY = "standby"
#: phase-specialized roles for a disaggregated fleet (docs/60):
#: routing ADVICE, not a serving restriction — a prefill replica
#: takes fresh prompts and ships the KV prefix to a decode peer
#: (kvtier/handoff.py), a decode replica generates off handed-off
#: prefixes, and either serves anything when the other pool is empty
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"

#: path a peer serves its weights on (and the standby fetches from)
WEIGHTS_PATH = "/v1/weights"

#: bytes per manifest chunk: big enough to amortize per-chunk digest
#: and frame overhead, small enough that a resume never re-ships much
WEIGHT_CHUNK = 256 * 1024

_MANIFEST_LEN_BYTES = 8


class WeightTransferError(RuntimeError):
    """The peer transfer failed in a way a redial cannot fix (digest
    mismatch, manifest drift, shape/dtype disagreement): fall back to
    the disk/init load, do not retry the peer."""


def equal_jitter(
    backoff: float, rng: random.Random, fraction: float = 0.5
) -> float:
    """The fleet's ONE retry-delay shape (the gateway's request
    retries, the autoscaler's launch retries, and the standby
    refill all call this): a deterministic floor plus a uniform
    random slice of ``fraction`` of the backoff — failures retried
    by many actors at once spread out instead of re-arriving as one
    synchronized wave."""
    spread = backoff * fraction
    return backoff - spread + rng.random() * spread


# -- serialization (pure helpers; callers executor-wrap them) ---------


def _chunk_digest(data: bytes) -> str:
    return hashlib.blake2b(data, digest_size=8).hexdigest()


def leaf_bytes(leaf: Any) -> bytes:
    """One leaf's deterministic host-side byte image (numpy
    ``tobytes`` of the device-fetched array). Blocking (device_get):
    call it from an executor, never on the loop."""
    import jax
    import numpy as np

    return np.asarray(jax.device_get(leaf)).tobytes()


def weights_manifest(
    params: Any, chunk_bytes: int = WEIGHT_CHUNK
) -> Dict[str, Any]:
    """The transfer manifest: every leaf (name/dtype/shape/bytes) and
    every chunk (leaf index, offset, length, digest) in flat
    ``tree_util`` order. Blocking (device_get per leaf): executor-wrap
    it. Built once per server and cached — the manifest is small; the
    chunk bytes themselves are re-derived lazily at serve time so the
    server never holds a second full copy of the params."""
    import jax
    import numpy as np

    flat, _treedef = jax.tree_util.tree_flatten_with_path(params)
    leaves: List[Dict[str, Any]] = []
    chunks: List[Dict[str, Any]] = []
    for index, (path, leaf) in enumerate(flat):
        arr = np.asarray(jax.device_get(leaf))
        data = arr.tobytes()
        leaves.append(
            {
                "name": jax.tree_util.keystr(path),
                "dtype": arr.dtype.name,
                "shape": list(arr.shape),
                "bytes": len(data),
            }
        )
        for offset in range(0, len(data) or 1, chunk_bytes):
            piece = data[offset:offset + chunk_bytes]
            chunks.append(
                {
                    "leaf": index,
                    "offset": offset,
                    "len": len(piece),
                    "digest": _chunk_digest(piece),
                }
            )
    return {
        "version": 1,
        "total_bytes": sum(entry["bytes"] for entry in leaves),
        "leaves": leaves,
        "chunks": chunks,
    }


def encode_manifest(manifest: Dict[str, Any]) -> bytes:
    """Length-prefixed manifest blob — the stream's first bytes."""
    body = json.dumps(manifest, sort_keys=True).encode()
    return len(body).to_bytes(_MANIFEST_LEN_BYTES, "big") + body


def rebuild_params(
    manifest: Dict[str, Any], chunks: List[bytes], like: Any
) -> Any:
    """Reassemble a host-side params tree from verified chunks,
    shaped like ``like`` (the fetcher's own freshly-initialized or
    restored tree — it provides the treedef the wire cannot carry).
    Raises WeightTransferError on any structural disagreement; the
    caller falls back. Blocking-ish (numpy assembly): executor-wrap
    for big models."""
    import jax
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(like)
    specs = manifest["leaves"]
    if len(specs) != len(leaves):
        raise WeightTransferError(
            f"peer serves {len(specs)} leaves, local model has "
            f"{len(leaves)} — config mismatch"
        )
    if len(chunks) != len(manifest["chunks"]):
        raise WeightTransferError(
            f"{len(chunks)} chunks received, manifest names "
            f"{len(manifest['chunks'])}"
        )
    by_leaf: List[List[bytes]] = [[] for _ in specs]
    for chunk_spec, data in zip(manifest["chunks"], chunks):
        by_leaf[chunk_spec["leaf"]].append(data)
    rebuilt: List[Any] = []
    for spec, pieces, local in zip(specs, by_leaf, leaves):
        arr = np.frombuffer(
            b"".join(pieces), dtype=np.dtype(spec["dtype"])
        ).reshape(spec["shape"])
        local_shape = tuple(getattr(local, "shape", arr.shape))
        if local_shape != tuple(arr.shape):
            raise WeightTransferError(
                f"leaf {spec['name']}: peer shape {tuple(arr.shape)} "
                f"!= local {local_shape} — config mismatch"
            )
        rebuilt.append(arr)
    return jax.tree_util.tree_unflatten(treedef, rebuilt)


# -- the fetch client (standby side) ----------------------------------


class _Peer:
    """The minimal replica shape ConnectionPool.acquire_mux needs."""

    def __init__(self, address: str, port: int) -> None:
        self.id = f"peer@{address}:{port}"
        self.address = address
        self.port = port
        self.authority = f"{address}:{port}"


class _ChunkedReader:
    """Reassemble exact-length reads off a mux stream's arbitrary
    DATA-frame boundaries."""

    def __init__(self, stream: Any, timeout: float) -> None:
        self._stream = stream
        self._timeout = timeout
        self._buf = bytearray()

    async def read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            piece = await self._stream.read_chunk(self._timeout)
            if not piece:
                raise UpstreamError(
                    "peer weight stream ended "
                    f"{n - len(self._buf)} bytes early"
                )
            self._buf += piece
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out


async def _read_manifest(
    reader: _ChunkedReader,
) -> Dict[str, Any]:
    raw_len = await reader.read_exact(_MANIFEST_LEN_BYTES)
    length = int.from_bytes(raw_len, "big")
    if not 0 < length <= 64 * 1024 * 1024:
        raise UpstreamError(f"implausible manifest length {length}")
    try:
        manifest = json.loads((await reader.read_exact(length)).decode())
    except (ValueError, UnicodeDecodeError) as exc:
        raise UpstreamError(f"malformed weight manifest: {exc}") from None
    if not isinstance(manifest, dict) or not isinstance(
        manifest.get("chunks"), list
    ):
        raise UpstreamError("weight manifest missing its chunk table")
    return manifest


async def fetch_weight_chunks(
    address: str,
    port: int,
    *,
    connect_timeout: float = 5.0,
    read_timeout: float = 120.0,
) -> Tuple[Dict[str, Any], List[bytes]]:
    """Fetch a peer's full weight stream over cp-mux/1: returns
    (manifest, verified chunks). ONE transparent redial on connection
    death, resuming at the first unverified chunk boundary — mirroring
    the pool's stale-connection discipline (the peer served none of
    the missing bytes, so re-requesting them cannot double-apply
    anything). Digest mismatches and manifest drift raise
    WeightTransferError immediately (corruption is not a connection
    problem; a redial cannot fix it)."""
    pool = ConnectionPool(mux=True)
    peer = _Peer(address, port)
    got: List[bytes] = []
    manifest: Optional[Dict[str, Any]] = None
    redialed = False
    try:
        while True:
            try:
                conn = await pool.acquire_mux(peer, connect_timeout)
                if conn is None:
                    raise UpstreamError(
                        f"{peer.authority} declined the cp-mux/1 "
                        f"upgrade"
                    )
                stream = await conn.open_stream(
                    "GET", f"{WEIGHTS_PATH}?chunk={len(got)}"
                )
                status, _headers = await stream.response_head(
                    read_timeout
                )
                if status != 200:
                    raise UpstreamError(
                        f"weights fetch answered {status}"
                    )
                reader = _ChunkedReader(stream, read_timeout)
                fresh = await _read_manifest(reader)
                if manifest is None:
                    manifest = fresh
                elif fresh != manifest:
                    # the peer's params changed between attempts (it
                    # reloaded): the already-verified prefix belongs
                    # to a different tree
                    raise WeightTransferError(
                        "peer manifest changed across the redial"
                    )
                specs = manifest["chunks"]
                while len(got) < len(specs):
                    spec = specs[len(got)]
                    data = await reader.read_exact(int(spec["len"]))
                    if _chunk_digest(data) != spec["digest"]:
                        raise WeightTransferError(
                            f"chunk {len(got)} digest mismatch"
                        )
                    got.append(data)
                return manifest, got
            except WeightTransferError:
                raise
            except UpstreamError:
                if redialed:
                    raise
                redialed = True
                # drop the dead shared connection so the next acquire
                # dials fresh; fully-verified chunks stay counted
                pool.close_all()
                log.warning(
                    "standby: peer weight stream died at chunk %d; "
                    "redialing once to resume", len(got),
                )
    finally:
        pool.close_all()


async def fetch_params(
    address: str,
    port: int,
    like: Any,
    *,
    connect_timeout: float = 5.0,
    read_timeout: float = 120.0,
) -> Optional[Any]:
    """Fetch a warm peer's weights and return them as a device-put
    tree shaped like ``like``, or None on ANY failure — the caller
    falls back to its disk/init load (the transfer is an accelerator,
    never a new way to fail a boot)."""
    try:
        manifest, chunks = await fetch_weight_chunks(
            address, port,
            connect_timeout=connect_timeout,
            read_timeout=read_timeout,
        )
    except (WeightTransferError, UpstreamError, OSError) as exc:
        log.warning(
            "standby: peer weight transfer from %s:%d failed (%s); "
            "falling back to local load", address, port, exc,
        )
        return None

    def assemble() -> Any:
        import jax

        host_tree = rebuild_params(manifest, chunks, like)
        # land each leaf HOW ``like``'s leaf lives — but only when
        # that placement is a real multi-device mesh sharding: a
        # tp/cp server's load path sharded ``like`` onto its mesh,
        # and the fetched replacements must follow or the ring/decode
        # programs see a params/mesh mismatch. Single-device likes
        # take the plain default placement (an explicit
        # SingleDeviceSharding would commit the arrays and fork the
        # jit cache a warm process already holds).
        host_leaves, treedef = jax.tree_util.tree_flatten(host_tree)

        def put(arr, ref):
            sharding = getattr(ref, "sharding", None)
            mesh = getattr(sharding, "mesh", None)
            if mesh is not None and getattr(mesh, "size", 1) > 1:
                return jax.device_put(arr, sharding)
            return jax.device_put(arr)

        placed = [
            put(arr, ref)
            for arr, ref in zip(
                host_leaves, jax.tree_util.tree_leaves(like)
            )
        ]
        return jax.tree_util.tree_unflatten(treedef, placed)

    loop = asyncio.get_event_loop()
    try:
        return await loop.run_in_executor(None, assemble)
    except (WeightTransferError, ValueError, TypeError) as exc:
        log.warning(
            "standby: fetched weights did not match the local model "
            "(%s); falling back to local load", exc,
        )
        return None


# -- the pool (autoscaler side) ---------------------------------------


class StandbyLauncher:
    """Autoscaler launcher that turns scale-up into PROMOTION.

    Wraps an inner launcher speaking the plain duck type plus three
    standby verbs::

        count() -> int / ids() -> list[str]   ACTIVE replicas only
        async launch() -> str                 cold active launch
        async retire(id)                      drain + stop
        async launch_standby() -> str         boot one standby replica
        async promote(id) -> bool             standby -> active; False
                                              when the standby is gone
                                              or already promoted

    ``launch()`` claims a warm standby (popped BEFORE any await, so
    two concurrent launches can never promote the same one — the
    promotion-race invariant) and promotes it; a dead/contended
    standby is dropped and the next tried; an empty pool falls back
    to the inner cold launch. Every launch — promoted or cold —
    schedules a background refill that boots standbys until the pool
    holds ``standby_count`` again, retrying failures with the
    fleet's equal-jitter backoff discipline. The autoscaler's
    kill-repair path calls the same ``launch()``, so crash recovery
    promotes too."""

    def __init__(
        self,
        inner: Any,
        standby_count: int = 1,
        *,
        refill_backoff: float = 0.25,
        refill_backoff_cap: float = 4.0,
        jitter_seed: Optional[int] = None,
    ) -> None:
        if standby_count < 0:
            raise ValueError("standby_count must be >= 0")
        self.inner = inner
        self.standby_count = standby_count
        self.refill_backoff = refill_backoff
        self.refill_backoff_cap = refill_backoff_cap
        self._rng = random.Random(jitter_seed)
        self._pool: List[str] = []
        self.promotions = 0
        self.promote_failures = 0
        self.cold_launches = 0
        self.refill_failures = 0
        #: how the LAST successful launch happened ("promoted"/"cold")
        #: — the autoscaler stamps it into its scale log so the TTFRT
        #: report can separate the promoted path from the cold one
        self.last_launch: Dict[str, str] = {}
        self._refill_task: Optional["asyncio.Task[None]"] = None
        self._tasks: Set["asyncio.Task"] = set()

    # -- the autoscaler duck type -------------------------------------

    def count(self) -> int:
        return self.inner.count()

    def ids(self) -> List[str]:
        return self.inner.ids()

    def standby_ids(self) -> List[str]:
        return list(self._pool)

    async def launch(self) -> str:
        """Promote a warm standby when one exists; cold-launch
        otherwise. Either way the pool refills in the background."""
        while self._pool:
            # claim BEFORE the await: concurrent launches pop
            # different standbys, so exactly one promoter ever
            # targets each — the loser of a pool race simply gets
            # the next standby (or the cold path), never a 409
            standby_id = self._pool.pop(0)
            try:
                promoted = await self.inner.promote(standby_id)
            except Exception as exc:
                log.warning(
                    "standby: promote %s raised (%s); trying next",
                    standby_id, exc,
                )
                promoted = False
            if promoted:
                self.promotions += 1
                self.last_launch = {
                    "mode": "promoted", "replica": standby_id,
                }
                self._ensure_refill()
                return standby_id
            # the standby died (or someone else promoted it) between
            # joining the pool and now: drop it and keep going
            self.promote_failures += 1
        self.last_launch = {"mode": "cold"}
        self._ensure_refill()
        replica_id = await self.inner.launch()
        # counted AFTER the await: a raising launcher is the
        # autoscaler's launch_failures, not a cold launch that never
        # happened skewing the promoted-vs-cold split
        self.cold_launches += 1
        return replica_id

    async def retire(self, replica_id: str) -> None:
        await self.inner.retire(replica_id)

    # -- pool maintenance ---------------------------------------------

    async def prefill(self) -> None:
        """Boot the initial standby set synchronously (the fleet-boot
        path; refills after that are background)."""
        while len(self._pool) < self.standby_count:
            self._pool.append(await self.inner.launch_standby())

    def _ensure_refill(self) -> None:
        if self.standby_count <= 0:
            return
        if self._refill_task is not None and not self._refill_task.done():
            return
        self._refill_task = spawn(
            self._refill_loop(), name="standby-refill",
            owner=self._tasks,
        )

    async def _refill_loop(self) -> None:
        """Boot standbys until the pool is full again. A standby that
        crashes mid-boot counts a failure and retries after an
        equal-jitter backoff (doubling, capped) — the same discipline
        the gateway's retry path uses, so a broken launcher can't
        storm the host with boot attempts."""
        backoff = self.refill_backoff
        while len(self._pool) < self.standby_count:
            try:
                standby_id = await self.inner.launch_standby()
            except Exception as exc:
                self.refill_failures += 1
                delay = equal_jitter(backoff, self._rng)
                log.warning(
                    "standby: refill launch failed (%s); retrying "
                    "in %.2fs", exc, delay,
                )
                await asyncio.sleep(delay)
                backoff = min(backoff * 2, self.refill_backoff_cap)
                continue
            self._pool.append(standby_id)
            backoff = self.refill_backoff
        log.info(
            "standby: pool refilled to %d (%s)",
            len(self._pool), self._pool,
        )

    async def stop(self) -> None:
        """Cancel the background refill (shutdown path)."""
        task = self._refill_task
        self._refill_task = None
        if task is not None and not task.done():
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                log.debug("standby: refill task cancelled at stop")

    def standby_stats(self) -> Dict[str, Any]:
        """The pool's surface on /fleet (via the autoscaler stats)."""
        return {
            "standby_count": self.standby_count,
            "pool": list(self._pool),
            "promotions": self.promotions,
            "promote_failures": self.promote_failures,
            "cold_launches": self.cold_launches,
            "refill_failures": self.refill_failures,
        }
