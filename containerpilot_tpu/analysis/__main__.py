"""CLI: ``python -m containerpilot_tpu.analysis`` — the lint gate.

Exit status:
    0  byte-compile clean AND no findings beyond the baseline
    1  new findings (or --write-baseline wrote nothing because the
       scan itself failed)
    2  a module failed to byte-compile / parse

Modes:
    (default)          scan the whole package against the baseline
    --files F [F ...]  report findings for those files only, still
                       filtered through the baseline. The call graph
                       is always built over the FULL package (plus
                       any listed out-of-package files), so the
                       interprocedural rules see every edge — only
                       the findings are filtered to the diff
                       (scripts/cpcheck_diff.sh / `make lint-diff`)
    --write-baseline   regenerate analysis/baseline.json from a fresh
                       full scan (the `make lint-baseline` body),
                       reporting which entries were added or removed
    --list-rules       print the rule catalog (id + first doc line)
"""
from __future__ import annotations

import argparse
import compileall
import os
import sys
from typing import List, Optional

from .callgraph import PROJECT_RULES, build_project_from_paths
from .cpcheck import (
    ALL_RULES,
    Finding,
    baseline_path,
    diff_against_baseline,
    explain_stale,
    iter_package_files,
    load_baseline,
    scan_package,
    scan_project,
    write_baseline,
)


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m containerpilot_tpu.analysis",
        description="cpcheck: repo-specific AST invariant analysis",
    )
    parser.add_argument(
        "--files", nargs="+", metavar="FILE",
        help="report findings for these files only (the call graph "
             "still spans the whole package)",
    )
    parser.add_argument(
        "--baseline", default=None,
        help=f"baseline path (default: {baseline_path()})",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="regenerate the baseline from a fresh full scan and exit",
    )
    parser.add_argument(
        "--no-compileall", action="store_true",
        help="skip the byte-compile pass (cpcheck rules only)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    args = parser.parse_args(argv)

    if args.write_baseline and args.files:
        # a partial scan must never replace the full ledger (it would
        # silently delete every other file's justified entries)
        parser.error("--write-baseline requires a full package scan; "
                     "drop --files")

    if args.list_rules:
        for rule in list(ALL_RULES) + list(PROJECT_RULES):
            doc = (rule.__doc__ or "").strip().splitlines()
            first = doc[0] if doc else ""
            print(f"{rule.rule_id}: {first}")
        return 0

    root = _package_root()
    repo = os.path.dirname(root)

    if not args.no_compileall and not args.files:
        # the old `make lint` body, kept: parse errors beat style errors
        if not compileall.compile_dir(root, quiet=1):
            print("cpcheck: byte-compilation failed", file=sys.stderr)
            return 2

    try:
        if args.files:
            # full-package forest + the listed files: the diff mode
            # must see every call edge (a changed helper can create a
            # reachability finding whose witness spans unchanged
            # files), then report only on the files asked about
            listed = [
                os.path.normpath(os.path.abspath(p))
                for p in args.files
            ]
            paths = list(dict.fromkeys(
                [
                    os.path.normpath(p)
                    for p in iter_package_files(root)
                ] + listed
            ))
            project = build_project_from_paths(paths, repo)
            rel_listed = {
                os.path.relpath(p, repo).replace(os.sep, "/")
                for p in listed
            }
            findings: List[Finding] = [
                f for f in scan_project(project)
                if f.file in rel_listed
            ]
        else:
            findings = scan_package(root, relative_to=repo)
    except SyntaxError as exc:
        print(f"cpcheck: parse failure: {exc}", file=sys.stderr)
        return 2

    entries = load_baseline(args.baseline)
    new, stale = diff_against_baseline(findings, entries)

    if args.write_baseline:
        path = write_baseline(findings, args.baseline)
        print(
            f"cpcheck: wrote {len(findings)} baseline entr"
            f"{'y' if len(findings) == 1 else 'ies'} to {path}"
        )
        if new:
            print(f"cpcheck: {len(new)} entr"
                  f"{'y' if len(new) == 1 else 'ies'} added:")
            for f in new:
                print(f"    {f.file} [{f.scope}] {f.rule}")
        if stale:
            print(f"cpcheck: {len(stale)} stale entr"
                  f"{'y' if len(stale) == 1 else 'ies'} removed:")
            for line in explain_stale(new, stale):
                print(f"    {line}")
        return 0

    scanned = (
        f"{len(args.files)} file(s)" if args.files else "package"
    )
    if new:
        print(
            f"cpcheck: {len(new)} new finding(s) over {scanned} "
            f"(baseline: {len(entries)} known):"
        )
        for f in new:
            print(f.render())
        if stale and not args.files:
            # a 'new' finding paired with a stale entry usually means
            # an edit moved a baselined line, not fresh debt — say so
            print(f"\ncpcheck: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'}:")
            for line in explain_stale(new, stale):
                print(f"    {line}")
        print(
            "\ncpcheck: fix the finding, add an inline "
            "`# cpcheck: disable=<RULE>` with a justification, or — "
            "for genuinely pre-existing debt — `make lint-baseline`.",
        )
        return 1
    if stale and not args.files:
        # full scans know an entry is truly gone; partial scans don't
        print(
            f"cpcheck: warning: {len(stale)} stale baseline entr"
            f"{'y' if len(stale) == 1 else 'ies'}:"
        )
        for line in explain_stale(new, stale):
            print(f"    {line}")
    print(
        f"cpcheck: clean ({scanned}; {len(findings)} finding(s), "
        f"all baselined)"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
