"""cpcheck: repo-specific AST invariant analysis.

The reference supervisor is Go and keeps its concurrency honest with
``go vet`` and the race detector; this Python reproduction gets the
same discipline from a stdlib-``ast`` analyzer whose rules encode the
invariants earlier PRs paid real debugging time to establish:

- **CP-HOTSYNC** — no host synchronization (``block_until_ready``,
  ``.item()``, ``np.asarray``, ``jax.device_get``, ``time.sleep``,
  blocking I/O) inside decode-round hot paths. Hot paths are marked
  with a ``# cpcheck: hotpath`` pragma or an ``@hotpath`` decorator;
  the ONE deliberate per-round token fetch carries an inline
  ``# cpcheck: disable=CP-HOTSYNC`` so it is explicit and auditable.
- **CP-DONATE** — a buffer donated to a jitted call must not be read
  again after the call unless the call's own assignment rebinds it
  (donation deletes the operand; a later read dies on a deleted
  array, or silently reads garbage on backends that alias).
- **CP-LOCKPUB** — no ``bus.publish(...)`` / subscriber ``.receive``
  fan-out lexically inside a ``with <lock>:`` block (ContainerPilot's
  classic deadlock: a subscriber that takes the same lock wedges the
  bus).
- **CP-SWALLOW** — no ``except``/``except Exception`` with a bare
  ``pass`` body: a supervisor thread that swallows its own death
  keeps ``/health`` green while doing nothing.
- **CP-THREAD** — every ``threading.Thread(...)`` must pass
  ``daemon=`` explicitly, forcing a decision about how the thread
  meets process shutdown.
- **CP-TOPIC** — event codes come from the ``events.events`` registry
  (``EventCode.X`` / the well-known ``GLOBAL_*`` constants), never
  inline string literals.

PRs 5-10 grew a second concurrency regime — the asyncio event loop
under the gateway, admission, autoscaler, mux transport, and every
replica HTTP surface — and these rules keep THAT half honest the same
way the thread-and-JAX rules above keep the first:

- **CP-ASYNCBLOCK** — no blocking call (``time.sleep``, sync
  socket/file I/O, ``subprocess.run``, ``future.result()`` /
  ``thread.join()``, ``jax.device_get``/``device_put``/
  ``block_until_ready``) lexically inside an ``async def`` body:
  one blocking call on the gateway loop stalls every multiplexed
  stream on the box. Wrapping the work in ``run_in_executor`` /
  ``asyncio.to_thread`` heals it.
- **CP-TASKLEAK** — ``asyncio.create_task(...)`` /
  ``ensure_future(...)`` whose return value is discarded: an
  unreferenced task is garbage-collectable mid-flight and its
  exception vanishes with it. Storing the task, awaiting it, or
  chaining a done-callback heals it (``utils/tasks.spawn`` does all
  three).
- **CP-AWAITHOLD** — ``await`` lexically inside a held
  ``threading.Lock``/``RLock`` ``with``-block: the task parks with
  the lock held, and any other task (or executor thread) that wants
  it wedges the whole loop. ``asyncio.Lock`` (``async with``) is
  exempt — that is the primitive to use here.
- **CP-RETRACE** — a locally-jitted callable invoked in a
  ``# cpcheck: hotpath`` region with arguments derived from
  Python-varying values (``len(...)``, f-strings, dynamic
  subscripts): every distinct value is a silent recompile, and a
  recompile storm is a stall no profiler names.

The runtime analog of these rules is ``analysis/loopcheck.py`` (an
event-loop lag probe + leaked-task watchdog), the way ``racecheck.py``
is the runtime analog of CP-LOCKPUB.

Each rule is a small visitor class with a ``rule_id`` and a docstring;
``scan_source``/``scan_file``/``scan_package`` drive them and return
``Finding`` records. Findings are fingerprinted by (rule, file, scope,
source-line text) — stable across unrelated edits — and compared
against ``analysis/baseline.json`` so pre-existing debt is enumerated
while anything NEW fails ``make lint`` and the tier-1 gate.

Escape hatches (use sparingly, with a justification comment):

    # cpcheck: hotpath                    -> marks the next/same-line def hot
    # cpcheck: disable=CP-XXXX[,CP-YYYY]  -> suppress on this line
    # cpcheck: disable                    -> suppress every rule on this line
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

PRAGMA = "cpcheck:"
DISABLE_ALL = "*"
_RULE_ID_RE = re.compile(r"^CP-[A-Z0-9]+$", re.IGNORECASE)


def hotpath(fn):
    """No-op marker decorator: ``@hotpath`` puts the function under
    CP-HOTSYNC's scrutiny, same as a ``# cpcheck: hotpath`` pragma
    (the rule matches the decorator NAME, so any import path works)."""
    return fn

# -- pragma + source bookkeeping -------------------------------------------


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    file: str
    line: int
    scope: str
    text: str
    message: str

    @property
    def key(self) -> Tuple[str, str, str, str]:
        """Baseline fingerprint: line numbers drift, these rarely do."""
        return (self.rule, self.file, self.scope, self.text)

    def render(self) -> str:
        return (
            f"{self.file}:{self.line}: {self.rule} [{self.scope}] "
            f"{self.message}\n    {self.text}"
        )


class _Pragmas:
    """Per-file pragma index: hotpath markers and line suppressions."""

    def __init__(self, source: str) -> None:
        self.hotpath_lines: Set[int] = set()
        self.disabled: Dict[int, Set[str]] = {}
        for lineno, line in enumerate(source.splitlines(), start=1):
            hash_idx = line.find("#")
            if hash_idx < 0:
                continue
            idx = line.find(PRAGMA, hash_idx)
            if idx < 0:
                continue
            body = line[idx + len(PRAGMA):].strip()
            directive, _, arg = body.partition("=")
            # trailing free text after the directive is a justification
            directive = directive.strip().lower().split()[0] if directive.strip() else ""
            if directive == "hotpath":
                self.hotpath_lines.add(lineno)
            elif directive == "disable":
                # `disable=CP-X,CP-Y free-text justification` — each
                # comma part's first word is a rule id; collection
                # stops at the first token NOT shaped like one, so a
                # comma inside the prose justification cannot
                # silently widen the suppression
                rules = set()
                for part in arg.split(","):
                    words = part.split()
                    if not words or not _RULE_ID_RE.match(words[0]):
                        break
                    rules.add(words[0].upper())
                self.disabled.setdefault(lineno, set()).update(
                    rules or {DISABLE_ALL}
                )

    def is_disabled(self, rule: str, line: int) -> bool:
        rules = self.disabled.get(line)
        if not rules:
            return False
        return DISABLE_ALL in rules or rule in rules


@dataclass
class ModuleContext:
    """Everything a rule needs to scan one module."""

    path: str
    tree: ast.Module
    lines: List[str]
    pragmas: _Pragmas
    scopes: Dict[ast.AST, str] = field(default_factory=dict)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def scope_of(self, node: ast.AST) -> str:
        return self.scopes.get(node, "<module>")


def _index_scopes(ctx: ModuleContext) -> None:
    """Annotate every node with its enclosing function qualname."""

    def walk(node: ast.AST, scope: str) -> None:
        for child in ast.iter_child_nodes(node):
            child_scope = scope
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                child_scope = (
                    f"{scope}.{child.name}"
                    if scope != "<module>"
                    else child.name
                )
            ctx.scopes[child] = child_scope
            walk(child, child_scope)

    ctx.scopes[ctx.tree] = "<module>"
    walk(ctx.tree, "<module>")


def dotted_name(node: ast.AST) -> str:
    """'np.asarray' for Attribute chains, 'open' for Names, '' else.

    Subscripted/called bases collapse to their tail attribute, so
    ``self._bufs[i].block_until_ready()`` still ends with the method
    name the rules match on.
    """
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif parts:
        parts.append("")  # call/subscript base: keep the attr tail
    return ".".join(reversed(parts)).lstrip(".")


def _expr_path(node: ast.AST) -> Optional[str]:
    """A stable string for Name / self.attr chains, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _expr_path(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _body_nodes(nodes: Iterable[ast.AST], *, skip_defs: bool) -> Iterable[ast.AST]:
    """Walk statements recursively, optionally not descending into
    nested function/class definitions (whose bodies run later, not
    lexically here)."""
    stack = list(nodes)
    while stack:
        node = stack.pop()
        yield node
        if skip_defs and isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda),
        ):
            continue  # its body runs later, not lexically here
        stack.extend(ast.iter_child_nodes(node))


# -- rule framework --------------------------------------------------------


class Rule:
    """Base class: subclasses set ``rule_id`` and implement ``run``."""

    rule_id = "CP-NONE"

    def run(self, ctx: ModuleContext) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(
        self, ctx: ModuleContext, node: ast.AST, message: str
    ) -> Optional[Finding]:
        lineno = getattr(node, "lineno", 1)
        if ctx.pragmas.is_disabled(self.rule_id, lineno):
            return None
        return Finding(
            rule=self.rule_id,
            file=ctx.path,
            line=lineno,
            scope=ctx.scope_of(node),
            text=ctx.line_text(lineno),
            message=message,
        )


def _is_hotpath(
    fn: ast.AST, ctx: ModuleContext
) -> bool:
    """Hot iff decorated @hotpath (any dotted tail) or carrying a
    ``# cpcheck: hotpath`` pragma on the def line, a decorator line,
    or the contiguous comment block directly above the def."""
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(target)
        if name.rpartition(".")[2] == "hotpath":
            return True
    first = min(
        [fn.lineno]
        + [d.lineno for d in getattr(fn, "decorator_list", [])]
    )
    # def/decorator lines up to (excluding) the first body statement
    candidates = set(range(first, getattr(fn, "body")[0].lineno))
    if candidates & ctx.pragmas.hotpath_lines:
        return True
    # the comment block immediately above the def
    lineno = first - 1
    while lineno >= 1 and ctx.line_text(lineno).startswith("#"):
        if lineno in ctx.pragmas.hotpath_lines:
            return True
        lineno -= 1
    return False


class HotSyncRule(Rule):
    """CP-HOTSYNC: host synchronization inside a decode-round hot path.

    Flags, inside functions marked hot: ``*.block_until_ready``,
    ``*.item()``, ``np.asarray``/``np.array``/``numpy.asarray``,
    ``jax.device_get``, ``time.sleep``, ``print``, ``open`` and
    ``input``. PR 2's host-overhead work established that a steady
    decode round should ship zero host->device transfers and exactly
    one token fetch; that fetch carries an inline disable pragma so
    every sync point in a hot path is visible in review.
    """

    rule_id = "CP-HOTSYNC"

    BLOCKED_NAMES = {
        "np.asarray", "np.array", "numpy.asarray", "numpy.array",
        "jax.device_get", "device_get", "time.sleep",
        "print", "open", "input",
    }
    BLOCKED_ATTRS = {"block_until_ready", "item"}

    def run(self, ctx: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if not _is_hotpath(node, ctx):
                continue
            for sub in _body_nodes(node.body, skip_defs=False):
                if not isinstance(sub, ast.Call):
                    continue
                name = dotted_name(sub.func)
                tail = name.rpartition(".")[2]
                hit = (
                    name in self.BLOCKED_NAMES
                    or tail in self.BLOCKED_ATTRS
                )
                if hit:
                    f = self.finding(
                        ctx, sub,
                        f"host sync `{name or tail}` in hot path "
                        "(mark the one deliberate fetch with "
                        "`# cpcheck: disable=CP-HOTSYNC`)",
                    )
                    if f:
                        findings.append(f)
        return findings


class DonateRule(Rule):
    """CP-DONATE: reading a buffer after donating it to a jitted call.

    Donation sources: local ``x = jax.jit(f, donate_argnums=...)``
    bindings discovered in the module, plus this repo's known donating
    entry points (models/slots.py): ``insert_row``,
    ``admit_slot_state`` and ``retire_slot`` donate argument 0,
    ``decode_slots_chunk`` and ``decode_slots_window`` donate
    arguments 1 and 2. A donated operand is cleared by being a
    target of the same call's assignment (``state = step(state, x)``);
    any later *read* of a still-donated name in the same function body
    is flagged, any later rebind heals it.
    """

    rule_id = "CP-DONATE"

    KNOWN_DONATORS: Dict[str, Tuple[int, ...]] = {
        "insert_row": (0,),
        "admit_slot_state": (0,),
        "retire_slot": (0,),
        "decode_slots_chunk": (1, 2),
        "decode_slots_window": (1, 2),
    }

    JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}

    def _module_donators(self, ctx: ModuleContext) -> Dict[str, Tuple[int, ...]]:
        """{name: donated positions} for `g = jax.jit(f, donate_argnums=..)`."""
        donators = dict(self.KNOWN_DONATORS)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Assign):
                continue
            call = node.value
            if not isinstance(call, ast.Call):
                continue
            if dotted_name(call.func) not in self.JIT_NAMES:
                continue
            positions: Tuple[int, ...] = ()
            for kw in call.keywords:
                if kw.arg != "donate_argnums":
                    continue
                try:
                    value = ast.literal_eval(kw.value)
                except ValueError:
                    continue
                if isinstance(value, int):
                    positions = (value,)
                elif isinstance(value, (tuple, list)):
                    positions = tuple(
                        v for v in value if isinstance(v, int)
                    )
            if not positions:
                continue
            for target in node.targets:
                if isinstance(target, ast.Name):
                    donators[target.id] = positions
        return donators

    @staticmethod
    def _assign_targets(stmt: ast.AST) -> Set[str]:
        targets: Set[str] = set()
        if isinstance(stmt, ast.Assign):
            nodes: List[ast.AST] = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            nodes = [stmt.target]
        else:
            return targets
        while nodes:
            t = nodes.pop()
            if isinstance(t, (ast.Tuple, ast.List)):
                nodes.extend(t.elts)
                continue
            path = _expr_path(t)
            if path:
                targets.add(path)
        return targets

    def run(self, ctx: ModuleContext) -> List[Finding]:
        donators = self._module_donators(ctx)
        findings: List[Finding] = []
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            findings.extend(self._scan_function(ctx, fn, donators))
        return findings

    @staticmethod
    def _diverges(b1, b2) -> bool:
        """True iff the two branch paths take DIFFERENT arms of the
        same ``if`` — i.e. the code locations are mutually exclusive."""
        for (id1, arm1), (id2, arm2) in zip(b1, b2):
            if id1 != id2:
                return False  # different nesting, not exclusive
            if arm1 != arm2:
                return True
        return False

    def _scan_function(
        self,
        ctx: ModuleContext,
        fn: ast.AST,
        donators: Dict[str, Tuple[int, ...]],
    ) -> List[Finding]:
        # Event positions model execution at line resolution: a
        # donating call taints at its END line (its own argument
        # loads happen before the donation), the enclosing
        # assignment's store heals after the call returns, and a load
        # is flagged only strictly after the donation completed.
        # Sort priority breaks same-position ties: load < donate < store.
        # Every event carries its if/else branch path, so a donation
        # in one arm never taints a read in the sibling arm, and a
        # heal in an arm divergent from the read never absolves it.
        PRIO = {"load": 0, "donate": 1, "store": 2}
        events: List[Tuple[int, int, str, ast.AST, object, tuple]] = []

        def classify(node: ast.AST, branch: tuple) -> None:
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                tail = name.rpartition(".")[2]
                positions = donators.get(name) or donators.get(tail)
                if positions:
                    pos = getattr(node, "end_lineno", node.lineno)
                    events.append(
                        (pos, PRIO["donate"], "donate", node, positions,
                         branch)
                    )
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                pos = getattr(node, "end_lineno", node.lineno)
                for path in self._assign_targets(node):
                    events.append(
                        (pos, PRIO["store"], "store", node, path, branch)
                    )
            elif isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                getattr(node, "ctx", None), ast.Load
            ):
                path = _expr_path(node)
                if path:
                    events.append(
                        (node.lineno, PRIO["load"], "load", node, path,
                         branch)
                    )

        def collect(node: ast.AST, branch: tuple) -> None:
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                 ast.Lambda),
            ):
                return  # runs later, not lexically here
            if isinstance(node, ast.If):
                collect(node.test, branch)
                for child in node.body:
                    collect(child, branch + ((id(node), 0),))
                for child in node.orelse:
                    collect(child, branch + ((id(node), 1),))
                return
            classify(node, branch)
            for child in ast.iter_child_nodes(node):
                collect(child, branch)

        for stmt in fn.body:
            collect(stmt, ())
        events.sort(key=lambda e: (e[0], e[1]))

        findings: List[Finding] = []
        donations: Dict[str, List[Tuple[int, tuple]]] = {}
        stores: Dict[str, List[Tuple[int, tuple]]] = {}
        for position, _prio, kind, node, payload, branch in events:
            if kind == "store":
                stores.setdefault(payload, []).append((position, branch))
            elif kind == "donate":
                call: ast.Call = node
                for arg_pos in payload:
                    if arg_pos < len(call.args):
                        path = _expr_path(call.args[arg_pos])
                        if path:
                            donations.setdefault(path, []).append(
                                (position, branch)
                            )
            else:  # load
                live = donations.get(payload)
                if not live:
                    continue
                for i, (d_pos, d_branch) in enumerate(live):
                    if position <= d_pos:
                        continue
                    if self._diverges(d_branch, branch):
                        continue  # sibling arm: can't both execute
                    healed = any(
                        d_pos <= s_pos <= position
                        and not self._diverges(s_branch, branch)
                        for s_pos, s_branch in stores.get(payload, [])
                    )
                    if healed:
                        continue
                    f = self.finding(
                        ctx, node,
                        f"`{payload}` read after being donated at "
                        f"line {d_pos}",
                    )
                    if f:
                        findings.append(f)
                    del live[i]  # one report per donation
                    break
        return findings


class LockPubRule(Rule):
    """CP-LOCKPUB: event fan-out lexically inside a held lock.

    Inside any ``with`` block whose context manager expression names a
    lock (its dotted path contains "lock" or "mutex", or it is an
    ``acquire()`` call), flags calls to ``*.publish`` and subscriber
    ``*.receive``. Fan-out is synchronous here: a subscriber that
    takes the same lock deadlocks the publisher — ContainerPilot's
    classic bus deadlock shape (reference: events/bus.go,
    jobs/jobs.go:23). Nested ``def`` bodies are skipped (they run
    later, not under the lock).
    """

    rule_id = "CP-LOCKPUB"

    FANOUT_TAILS = {"publish", "receive"}

    @staticmethod
    def _is_lockish(expr: ast.AST) -> bool:
        if isinstance(expr, ast.Call):
            name = dotted_name(expr.func)
            if name.rpartition(".")[2] == "acquire":
                return True
            expr_name = name
        else:
            expr_name = dotted_name(expr) or ""
        lowered = expr_name.lower()
        return "lock" in lowered or "mutex" in lowered

    def run(self, ctx: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            if not any(
                self._is_lockish(item.context_expr) for item in node.items
            ):
                continue
            for sub in _body_nodes(node.body, skip_defs=True):
                if not isinstance(sub, ast.Call):
                    continue
                tail = dotted_name(sub.func).rpartition(".")[2]
                if tail in self.FANOUT_TAILS:
                    f = self.finding(
                        ctx, sub,
                        f"`{dotted_name(sub.func)}` fan-out while "
                        "holding a lock: snapshot under the lock, "
                        "deliver outside it",
                    )
                    if f:
                        findings.append(f)
        return findings


class SwallowRule(Rule):
    """CP-SWALLOW: a broad except whose entire body is ``pass``.

    ``except:``, ``except Exception:``, ``except BaseException:`` (or
    a tuple containing either) with a bare ``pass`` body silently eats
    the failure that should have crashed or logged — the supervisor
    keeps reporting healthy while a worker thread is already dead.
    Narrow exception types (``except ValueError: pass``) are allowed:
    they encode an explicit, bounded decision.
    """

    rule_id = "CP-SWALLOW"

    BROAD = {"Exception", "BaseException"}

    def _is_broad(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True
        names: List[ast.AST] = (
            list(t.elts) if isinstance(t, ast.Tuple) else [t]
        )
        return any(
            dotted_name(n).rpartition(".")[2] in self.BROAD for n in names
        )

    def run(self, ctx: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node):
                continue
            if len(node.body) == 1 and isinstance(node.body[0], ast.Pass):
                f = self.finding(
                    ctx, node,
                    "broad except swallows the error: log it, narrow "
                    "the type, or re-raise",
                )
                if f:
                    findings.append(f)
        return findings


class ThreadRule(Rule):
    """CP-THREAD: ``threading.Thread(...)`` without an explicit
    ``daemon=``.

    A thread that defaults to non-daemon silently blocks interpreter
    exit; one that should be joined on shutdown needs an owner. The
    rule forces the decision to be written down: pass ``daemon=True``
    for fire-and-forget monitors, ``daemon=False`` (and join it in the
    shutdown path) for workers holding state.
    """

    rule_id = "CP-THREAD"

    THREAD_NAMES = {"threading.Thread", "Thread"}

    def run(self, ctx: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) not in self.THREAD_NAMES:
                continue
            if any(kw.arg == "daemon" for kw in node.keywords):
                continue
            f = self.finding(
                ctx, node,
                "Thread without explicit daemon=: decide (and write "
                "down) how this thread meets shutdown",
            )
            if f:
                findings.append(f)
        return findings


class TopicRule(Rule):
    """CP-TOPIC: event codes must come from the events registry.

    ``Event("exitSuccess", ...)`` (a string literal where an
    ``EventCode`` belongs) bypasses the registry in
    ``events/events.py`` — a typo'd code silently never matches any
    subscriber's dispatch. Construct events with ``EventCode.X`` or
    the well-known ``GLOBAL_*`` constants; parse config strings
    through ``code_from_string`` (the registry accessor), never
    inline.
    """

    rule_id = "CP-TOPIC"

    EVENT_NAMES = {"Event", "events.Event"}

    def run(self, ctx: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) not in self.EVENT_NAMES:
                continue
            code_arg: Optional[ast.AST] = None
            if node.args:
                code_arg = node.args[0]
            for kw in node.keywords:
                if kw.arg == "code":
                    code_arg = kw.value
            if isinstance(code_arg, ast.Constant) and isinstance(
                code_arg.value, str
            ):
                f = self.finding(
                    ctx, node,
                    f"inline event code {code_arg.value!r}: use "
                    "EventCode.* from the events registry",
                )
                if f:
                    findings.append(f)
        return findings


class AsyncBlockRule(Rule):
    """CP-ASYNCBLOCK: a blocking call lexically inside an ``async
    def`` body.

    The event loop is cooperative: one ``time.sleep``, sync
    socket/file I/O, ``subprocess.run``, ``future.result()`` /
    ``thread.join()``, or host-synchronizing JAX transfer
    (``device_get``/``device_put``/``block_until_ready``) on the
    gateway loop stalls every co-resident request, stream, heartbeat
    and poll on the box — the exact failure the supervisor exists to
    prevent. Nested ``def``/``lambda`` bodies are skipped (they run
    later, usually on an executor thread), and a call lexically
    wrapped in ``loop.run_in_executor(...)`` / ``asyncio.to_thread(...)``
    arguments is healed: that is the sanctioned escape, and the fix
    this rule is pushing toward.

    ``.result()``/``.join()`` are matched by dataflow, not name alone
    (``"".join(...)`` and an awaited asyncio future are innocent):
    only receivers bound from ``executor.submit(...)`` /
    ``threading.Thread(...)`` in the same function — or chained
    directly off them — are flagged.
    """

    rule_id = "CP-ASYNCBLOCK"

    BLOCKED_NAMES = {
        "time.sleep",
        "subprocess.run", "subprocess.call", "subprocess.check_call",
        "subprocess.check_output", "subprocess.getoutput",
        "os.system", "os.waitpid",
        "socket.create_connection", "urllib.request.urlopen",
        "open", "input",
        "jax.device_get", "jax.device_put", "jax.block_until_ready",
    }
    BLOCKED_TAILS = {"block_until_ready", "device_get", "device_put"}
    #: calls whose argument subtrees are the sanctioned escape hatch
    EXECUTOR_TAILS = {"run_in_executor", "to_thread"}
    #: receivers born from these tails make .result()/.join() blocking
    FUTURE_SOURCES = {"submit"}
    THREAD_SOURCES = {"Thread"}

    def _scan_async_fn(
        self, ctx: ModuleContext, fn: ast.AsyncFunctionDef
    ) -> List[Finding]:
        findings: List[Finding] = []
        # names bound from executor.submit(...) / threading.Thread(...)
        future_names: Set[str] = set()
        thread_names: Set[str] = set()

        def source_kind(call: ast.Call) -> Optional[str]:
            tail = dotted_name(call.func).rpartition(".")[2]
            if tail in self.FUTURE_SOURCES:
                return "future"
            if tail in self.THREAD_SOURCES:
                return "thread"
            return None

        for node in _body_nodes(fn.body, skip_defs=True):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                kind = source_kind(node.value)
                if kind:
                    for target in node.targets:
                        path = _expr_path(target)
                        if path:
                            (future_names if kind == "future"
                             else thread_names).add(path)

        def visit(node: ast.AST) -> None:
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                 ast.Lambda),
            ):
                return  # runs later, not on this loop iteration
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                tail = name.rpartition(".")[2]
                if tail in self.EXECUTOR_TAILS:
                    # run_in_executor/to_thread arguments are the
                    # escape hatch; don't descend into them
                    visit(node.func)
                    return
                hit = (
                    name in self.BLOCKED_NAMES
                    or tail in self.BLOCKED_TAILS
                )
                why = f"blocking `{name or tail}`"
                if not hit and tail in ("result", "join"):
                    recv = node.func.value if isinstance(
                        node.func, ast.Attribute
                    ) else None
                    recv_path = _expr_path(recv) if recv is not None else None
                    if recv_path in future_names or (
                        isinstance(recv, ast.Call)
                        and source_kind(recv) == "future"
                    ):
                        hit, why = True, f"`{recv_path or '...'}.result()` blocks on a concurrent future"
                    elif recv_path in thread_names or (
                        isinstance(recv, ast.Call)
                        and source_kind(recv) == "thread"
                    ):
                        hit, why = True, f"`{recv_path or '...'}.join()` blocks on a thread"
                if hit:
                    f = self.finding(
                        ctx, node,
                        f"{why} in async def `{fn.name}` stalls the "
                        "event loop: move it to run_in_executor / "
                        "asyncio.to_thread",
                    )
                    if f:
                        findings.append(f)
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in fn.body:
            visit(stmt)
        return findings

    def run(self, ctx: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                findings.extend(self._scan_async_fn(ctx, node))
        return findings


class TaskLeakRule(Rule):
    """CP-TASKLEAK: ``asyncio.create_task(...)`` (or
    ``ensure_future``) whose return value is discarded.

    The event loop holds only a weak reference to running tasks: a
    task nobody stores can be garbage-collected mid-flight, and an
    exception it raises is silently dropped on the floor — the
    asyncio face of CP-SWALLOW, with the added insult that the
    watchdog/relay the task implemented just stops existing. Storing
    the task (``self._task = ...``, a pending set), awaiting it, or
    chaining ``.add_done_callback(...)`` heals the finding;
    ``utils/tasks.spawn`` packages the full discipline (reference +
    logging done-callback) in one call.
    """

    rule_id = "CP-TASKLEAK"

    SPAWN_TAILS = {"create_task", "ensure_future"}

    def run(self, ctx: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Expr):
                continue
            call = node.value
            if not isinstance(call, ast.Call):
                continue
            name = dotted_name(call.func)
            if name.rpartition(".")[2] not in self.SPAWN_TAILS:
                continue
            f = self.finding(
                ctx, call,
                f"`{name}` result discarded: an unreferenced task is "
                "GC-cancellable and swallows its exception — store "
                "it (utils/tasks.spawn), await it, or chain "
                "add_done_callback",
            )
            if f:
                findings.append(f)
        return findings


class AwaitHoldRule(Rule):
    """CP-AWAITHOLD: ``await`` lexically inside a held
    ``threading.Lock``/``RLock`` ``with``-block.

    A coroutine that awaits while holding a *thread* lock parks with
    the lock held. Any other task that wants the lock then blocks the
    whole event loop when it tries to acquire (thread locks don't
    yield), and an executor thread contending for it can deadlock
    against the loop outright — a loop-wide stall with no stack trace
    pointing at the cause. ``async for`` and ``async with`` suspend
    the same way (at ``__anext__``/``__aenter__``) and are flagged
    too. ``asyncio.Lock`` is exempt by shape: the *outer* lock being
    held must be a sync ``with`` (an ``AsyncWith`` there is exactly
    the primitive to use around awaits). Nested ``def`` bodies are
    skipped (they run later, not under the lock).
    """

    rule_id = "CP-AWAITHOLD"

    #: nodes that suspend the coroutine: an explicit await, or the
    #: implicit ones inside `async for` / `async with`
    SUSPENDS = (ast.Await, ast.AsyncFor, ast.AsyncWith)

    def run(self, ctx: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            # sync `with` only: `async with asyncio.Lock()` is the fix
            if not isinstance(node, ast.With):
                continue
            if not any(
                LockPubRule._is_lockish(item.context_expr)
                for item in node.items
            ):
                continue
            for sub in _body_nodes(node.body, skip_defs=True):
                if isinstance(sub, self.SUSPENDS):
                    f = self.finding(
                        ctx, sub,
                        "await while holding a thread lock: the task "
                        "parks mid-critical-section and wedges the "
                        "loop — narrow the lock or use asyncio.Lock",
                    )
                    if f:
                        findings.append(f)
        return findings


class RetraceRule(Rule):
    """CP-RETRACE: a jitted callable invoked in a hot path with
    Python-varying arguments — the static face of a recompile storm.

    ``jax.jit`` specializes on argument shapes and static values:
    passing ``len(batch)``, an f-string, or a dict lookup keyed on
    request state means every distinct value silently compiles a new
    executable, billing seconds of XLA time to a request that
    expected milliseconds (the exact trap the chaos warmup had to
    pre-compile its way around). Inside ``# cpcheck: hotpath``
    regions, calls to locally-bound ``jax.jit``/``pjit`` objects —
    and direct ``lax.scan``/``lax.while_loop`` calls (the fused
    decode window's shape) — are checked: any argument whose
    expression tree contains ``len(...)``, an f-string
    (``JoinedStr``), or a subscript with a non-constant key is
    flagged. Pad/bucket the value (the warmup's bucket set exists for
    this) or hoist it out of the hot region.
    """

    rule_id = "CP-RETRACE"

    JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
    # direct structured-control-flow entry points: a lax.scan OR a
    # lax.while_loop step program called with Python-varying operands
    # retraces the same way a jit-bound callable does (the fused
    # decode window is a while_loop — its rounds/chunk/slots must be
    # padded/bucketed, never derived from request state)
    SCAN_NAMES = {
        "lax.scan", "jax.lax.scan",
        "lax.while_loop", "jax.lax.while_loop",
    }
    VARYING_CALLS = {"len"}

    def _jit_bound(self, ctx: ModuleContext) -> Set[str]:
        bound: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            if dotted_name(node.value.func) not in self.JIT_NAMES:
                continue
            for target in node.targets:
                path = _expr_path(target)
                if path:
                    bound.add(path)
        return bound

    @staticmethod
    def _static_index(node: ast.AST) -> bool:
        """True when a subscript's index is a compile-time constant:
        ``b[0]``, ``b[-1]``, ``shapes[1, 0]`` — literal_eval folds
        them all; anything it can't fold varies at runtime."""
        try:
            ast.literal_eval(node)
        except (ValueError, TypeError, SyntaxError, MemoryError):
            return False
        return True

    def _varying(self, arg: ast.AST) -> Optional[str]:
        """The first Python-varying subexpression in ``arg``, as a
        human-readable reason, or None when the argument is stable."""
        for node in ast.walk(arg):
            if isinstance(node, ast.Call) and dotted_name(
                node.func
            ) in self.VARYING_CALLS:
                return "len(...)"
            if isinstance(node, ast.JoinedStr):
                return "an f-string"
            if isinstance(node, ast.Subscript) and not self._static_index(
                node.slice
            ):
                return "a dynamic subscript"
        return None

    def run(self, ctx: ModuleContext) -> List[Finding]:
        bound = self._jit_bound(ctx)
        findings: List[Finding] = []
        for fn in ast.walk(ctx.tree):
            if not isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if not _is_hotpath(fn, ctx):
                continue
            for sub in _body_nodes(fn.body, skip_defs=False):
                if not isinstance(sub, ast.Call):
                    continue
                name = dotted_name(sub.func)
                jitted = (
                    name in bound
                    or name.rpartition(".")[2] in bound
                    or name in self.SCAN_NAMES
                )
                if not jitted:
                    continue
                for arg in list(sub.args) + [
                    kw.value for kw in sub.keywords
                ]:
                    reason = self._varying(arg)
                    if reason is None:
                        continue
                    f = self.finding(
                        ctx, sub,
                        f"jitted `{name}` called with {reason} in a "
                        "hot path: every distinct value is a silent "
                        "recompile — pad/bucket it or hoist it out",
                    )
                    if f:
                        findings.append(f)
                    break  # one report per call site
        return findings


ALL_RULES: Tuple[Rule, ...] = (
    HotSyncRule(),
    DonateRule(),
    LockPubRule(),
    SwallowRule(),
    ThreadRule(),
    TopicRule(),
    AsyncBlockRule(),
    TaskLeakRule(),
    AwaitHoldRule(),
    RetraceRule(),
)

RULES_BY_ID: Dict[str, Rule] = {r.rule_id: r for r in ALL_RULES}


# -- drivers ---------------------------------------------------------------


def _default_project_rules(
    rules: Sequence[Rule], project_rules
) -> Sequence:
    """The interprocedural rules a scan runs: an explicit sequence
    wins; by default they ride along only with the full lexical
    catalog (a caller scanning with a hand-picked rule subset is
    asking for exactly those rules, nothing extra)."""
    if project_rules is not None:
        return project_rules
    if rules is ALL_RULES:
        from .callgraph import PROJECT_RULES

        return PROJECT_RULES
    return ()


def scan_source(
    source: str,
    path: str = "<string>",
    rules: Sequence[Rule] = ALL_RULES,
    project_rules=None,
) -> List[Finding]:
    """Scan one module's source text; returns findings sorted by
    (file, line, rule). Interprocedural rules see a single-module
    project — enough for same-file reachability fixtures."""
    tree = ast.parse(source, filename=path)
    ctx = ModuleContext(
        path=path,
        tree=tree,
        lines=source.splitlines(),
        pragmas=_Pragmas(source),
    )
    _index_scopes(ctx)
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.run(ctx))
    project_rules = _default_project_rules(rules, project_rules)
    if project_rules:
        from .callgraph import ProjectContext, run_project_rules

        findings.extend(
            run_project_rules(ProjectContext([ctx]), project_rules)
        )
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


def scan_file(
    path: str,
    relative_to: Optional[str] = None,
    rules: Sequence[Rule] = ALL_RULES,
    project_rules=None,
) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    rel = (
        os.path.relpath(path, relative_to) if relative_to else path
    ).replace(os.sep, "/")
    return scan_source(source, rel, rules, project_rules)


def iter_package_files(root: str) -> List[str]:
    out: List[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d != "__pycache__"
        )
        for name in sorted(filenames):
            if name.endswith(".py"):
                out.append(os.path.join(dirpath, name))
    return out


def scan_project(
    project,
    rules: Sequence[Rule] = ALL_RULES,
    project_rules=None,
) -> List[Finding]:
    """Run lexical rules over every module in a prebuilt
    ProjectContext, then the interprocedural rules once over the
    whole forest. The project's parsed ASTs are shared by every rule
    — each file is parsed exactly once per scan."""
    findings: List[Finding] = []
    for ctx in project.contexts:
        for rule in rules:
            findings.extend(rule.run(ctx))
    project_rules = _default_project_rules(rules, project_rules)
    if project_rules:
        from .callgraph import run_project_rules

        findings.extend(run_project_rules(project, project_rules))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


def scan_package(
    root: str,
    relative_to: Optional[str] = None,
    rules: Sequence[Rule] = ALL_RULES,
    project_rules=None,
) -> List[Finding]:
    """Scan every .py under ``root``; paths are reported relative to
    ``relative_to`` (default: root's parent, so 'containerpilot_tpu/...')."""
    from .callgraph import build_project_from_paths

    base = relative_to or os.path.dirname(os.path.abspath(root))
    project = build_project_from_paths(iter_package_files(root), base)
    return scan_project(project, rules, project_rules)


# -- baseline --------------------------------------------------------------


def baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: Optional[str] = None) -> List[dict]:
    path = path or baseline_path()
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return list(data.get("entries", []))


def write_baseline(
    findings: Sequence[Finding], path: Optional[str] = None
) -> str:
    path = path or baseline_path()
    # regeneration keeps hand-written "reason" annotations for entries
    # that survive
    reasons: Dict[Tuple[str, str, str, str], str] = {}
    for old in load_baseline(path):
        if "reason" in old:
            reasons[_entry_key(old)] = old["reason"]
    entries = []
    for f in findings:
        entry = {
            "rule": f.rule,
            "file": f.file,
            "scope": f.scope,
            "text": f.text,
        }
        reason = reasons.get(f.key)
        if reason:
            entry["reason"] = reason
        entries.append(entry)
    payload = {
        "comment": (
            "cpcheck baseline: pre-existing findings enumerated, not "
            "hidden. Regenerate with `make lint-baseline`; shrink it, "
            "never grow it."
        ),
        "version": 1,
        "entries": entries,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


def _entry_key(entry: dict) -> Tuple[str, str, str, str]:
    return (
        entry.get("rule", ""),
        entry.get("file", ""),
        entry.get("scope", ""),
        entry.get("text", ""),
    )


def diff_against_baseline(
    findings: Sequence[Finding], entries: Sequence[dict]
) -> Tuple[List[Finding], List[dict]]:
    """(new findings not in the baseline, stale entries no longer seen).

    Multiset semantics: two identical findings need two baseline
    entries, so a copy-pasted second violation cannot hide behind the
    first one's entry.
    """
    budget: Dict[Tuple[str, str, str, str], int] = {}
    for entry in entries:
        key = _entry_key(entry)
        budget[key] = budget.get(key, 0) + 1
    new: List[Finding] = []
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
        else:
            new.append(f)
    stale: List[dict] = []
    for entry in entries:
        key = _entry_key(entry)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            stale.append(entry)
    return new, stale


def explain_stale(
    new: Sequence[Finding], stale: Sequence[dict]
) -> List[str]:
    """One human-readable line per stale baseline entry, saying WHY
    it went stale. The fingerprint includes line text, so an
    unrelated edit to a baselined line silently drops its
    suppression and the finding resurfaces as 'new' — pair each
    stale entry with any new finding at the same (rule, file, scope)
    so the failure tells the builder what actually happened instead
    of presenting two disconnected lists."""
    out: List[str] = []
    for entry in stale:
        match = next(
            (
                f for f in new
                if f.rule == entry.get("rule")
                and f.file == entry.get("file")
                and f.scope == entry.get("scope")
            ),
            None,
        )
        where = (
            f"{entry.get('file')} [{entry.get('scope')}] "
            f"{entry.get('rule')}"
        )
        if match is not None:
            out.append(
                f"{where}: line text drifted — baseline pinned "
                f"{entry.get('text')!r} but the scan now sees "
                f"{match.text!r} (line {match.line}); an edit to a "
                "baselined line drops its suppression — fix the "
                "finding or re-run `make lint-baseline` after review"
            )
        else:
            out.append(
                f"{where}: finding no longer present — it was fixed;"
                " run `make lint-baseline` to shrink the ledger"
            )
    return out
