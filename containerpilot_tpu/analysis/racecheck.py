"""racecheck: a runtime lock-order / publish-discipline harness.

The Go reference leans on ``go test -race`` to keep its bus and job
state machine honest; this is the Python reproduction's analog for the
two hazards a synchronous fan-out bus actually has:

- **Lock-order inversion.** Thread A takes L1 then L2 while thread B
  takes L2 then L1 — no deadlock *this* run, but the cycle in the
  acquisition-order graph proves one is reachable. The harness hands
  out instrumented locks (``RaceCheck.lock``/``rlock``) that record,
  per thread, every held->acquired edge; ``assert_clean()`` fails on
  any cycle, naming the locks and the threads that witnessed each
  edge.
- **Publish-while-held.** ``EventBus.publish`` fans out to
  subscribers synchronously; publishing while holding an application
  lock hands every subscriber callback that lock's scope
  (ContainerPilot's classic bus deadlock — the shape CP-LOCKPUB
  catches lexically, checked here dynamically through
  ``RaceCheck.wrap_bus``).

Opt-in and test-oriented: nothing in the production path imports this
module. Typical use::

    rc = RaceCheck()
    table_lock = rc.lock("replica-table")
    rc.wrap_bus(bus)
    ... run the scenario ...
    rc.assert_clean()

Violations are recorded, not raised at the faulting site, so a test
exercises its whole scenario and then reports every hazard at once.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple


@dataclass
class Violation:
    """One recorded hazard."""

    kind: str  # "lock-order-cycle" | "publish-while-held"
    detail: str

    def __str__(self) -> str:  # pragma: no cover - trivial
        return f"{self.kind}: {self.detail}"


@dataclass
class _Edge:
    """held -> acquired, with the thread that witnessed it."""

    held: str
    acquired: str
    thread: str


class CheckedLock:
    """A named Lock/RLock recording acquisition order into a harness.

    Supports the context-manager protocol and explicit
    ``acquire``/``release``, like the lock it wraps. Re-entrant
    acquisition of the same RLock adds no edge (it cannot deadlock
    against itself).
    """

    def __init__(
        self, harness: "RaceCheck", name: str, reentrant: bool
    ) -> None:
        self._harness = harness
        self.name = name
        self._inner: Any = (
            threading.RLock() if reentrant else threading.Lock()
        )

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._harness._note_acquire(self.name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._harness._note_acquired(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        self._harness._note_released(self.name)

    def __enter__(self) -> "CheckedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: object) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - trivial
        return f"CheckedLock({self.name!r})"


class RaceCheck:
    """Collects lock-order edges and publish-discipline violations."""

    def __init__(self) -> None:
        self._state_lock = threading.Lock()
        self._tls = threading.local()
        self._edges: List[_Edge] = []
        self._edge_set: Set[Tuple[str, str]] = set()
        self._violations: List[Violation] = []
        self._wrapped: List[Tuple[Any, Any]] = []  # (bus, orig publish)

    # -- lock factory ---------------------------------------------------

    def lock(self, name: str) -> CheckedLock:
        return CheckedLock(self, name, reentrant=False)

    def rlock(self, name: str) -> CheckedLock:
        return CheckedLock(self, name, reentrant=True)

    # -- per-thread held stack ------------------------------------------

    def _held(self) -> List[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _note_acquire(self, name: str) -> None:
        """Record edges BEFORE blocking on the lock: the hazard exists
        whether or not this particular acquisition waits."""
        held = self._held()
        thread = threading.current_thread().name
        with self._state_lock:
            for h in held:
                if h == name:  # re-entrant same-lock: no self-edge
                    continue
                if (h, name) not in self._edge_set:
                    self._edge_set.add((h, name))
                    self._edges.append(_Edge(h, name, thread))

    def _note_acquired(self, name: str) -> None:
        self._held().append(name)

    def _note_released(self, name: str) -> None:
        held = self._held()
        # release order may not mirror acquisition; drop the LAST match
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break

    # -- bus instrumentation --------------------------------------------

    def wrap_bus(self, bus: Any) -> Any:
        """Instrument ``bus.publish`` to record a violation whenever a
        publish happens while the calling thread holds ANY of this
        harness's locks. Returns the same bus for chaining."""
        orig = bus.publish

        def checked_publish(event: Any, _orig=orig) -> None:
            held = list(self._held())
            if held:
                with self._state_lock:
                    self._violations.append(
                        Violation(
                            "publish-while-held",
                            f"publish({event}) on thread "
                            f"{threading.current_thread().name!r} while "
                            f"holding {held}",
                        )
                    )
            _orig(event)

        bus.publish = checked_publish
        self._wrapped.append((bus, orig))
        return bus

    def unwrap(self) -> None:
        """Restore every wrapped bus's original publish."""
        while self._wrapped:
            bus, orig = self._wrapped.pop()
            bus.publish = orig

    # -- reporting ------------------------------------------------------

    def _find_cycle(self, edges: List[_Edge]) -> Optional[List[str]]:
        graph: Dict[str, List[str]] = {}
        for edge in edges:
            graph.setdefault(edge.held, []).append(edge.acquired)
        WHITE, GRAY, BLACK = 0, 1, 2
        color: Dict[str, int] = {}
        stack_path: List[str] = []

        def visit(node: str) -> Optional[List[str]]:
            color[node] = GRAY
            stack_path.append(node)
            for nxt in graph.get(node, []):
                state = color.get(nxt, WHITE)
                if state == GRAY:
                    return stack_path[stack_path.index(nxt):] + [nxt]
                if state == WHITE:
                    cycle = visit(nxt)
                    if cycle:
                        return cycle
            stack_path.pop()
            color[node] = BLACK
            return None

        for node in list(graph):
            if color.get(node, WHITE) == WHITE:
                cycle = visit(node)
                if cycle:
                    return cycle
        return None

    def violations(self) -> List[Violation]:
        """All recorded violations, including lock-order cycles found
        in the accumulated acquisition graph."""
        with self._state_lock:
            out = list(self._violations)
            edges = list(self._edges)
        cycle = self._find_cycle(edges)
        if cycle:
            witnesses = [
                f"{e.held}->{e.acquired} (thread {e.thread})"
                for e in edges
                if e.held in cycle and e.acquired in cycle
            ]
            out.append(
                Violation(
                    "lock-order-cycle",
                    " -> ".join(cycle)
                    + "; witnessed: "
                    + "; ".join(witnesses),
                )
            )
        return out

    def assert_clean(self) -> None:
        """Raise AssertionError listing every recorded hazard."""
        found = self.violations()
        if found:
            raise AssertionError(
                "racecheck found %d violation(s):\n%s"
                % (len(found), "\n".join(f"  - {v}" for v in found))
            )

    # -- context manager -------------------------------------------------

    def __enter__(self) -> "RaceCheck":
        return self

    def __exit__(self, exc_type: object, *exc: object) -> None:
        self.unwrap()
        if exc_type is None:
            self.assert_clean()
