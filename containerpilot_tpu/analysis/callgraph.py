"""cpcheck's interprocedural layer: a project-wide call graph and the
rules that need to see across function and module boundaries.

All ten PR-4/11 rules are lexical — each looks at one function body in
one module. That misses exactly the failures this repo's runtime
machinery exists to catch late: a ``time.sleep`` one sync-helper deep
on an async request path (CP-ASYNCREACH), a ``# cpcheck: hotpath``
region whose helpers do host syncs the lexical CP-HOTSYNC never sees
(CP-HOTREACH), a lock-order inversion split across two modules that
racecheck's runtime tests never happened to drive (CP-LOCKORDER), and
a heartbeat note field whose producer and parser drifted apart
(CP-NOTEWIRE, the static face of ``fleet/notes.py``).

The graph is deliberately honest rather than clever:

- **Resolved edges** come only from constructs the resolver actually
  understands: module functions (local or imported by name),
  ``self.``/``cls.`` methods (including single-inheritance bases the
  project can see), methods on module-level or function-local
  instances of project classes, and ``mod.func`` through an imported
  module alias.
- **Deferred edges** — ``functools.partial(f, ...)`` targets and
  ``spawn(coro())`` / ``create_task`` / ``ensure_future`` targets —
  are resolved and recorded (kind ``partial`` / ``spawn``) but NOT
  walked by the synchronous-reachability rules: the callee runs
  later, on some other frame, not inside the caller's await-free
  window.
- **Sanctioned edges** are callables referenced inside
  ``run_in_executor(...)`` / ``to_thread(...)`` arguments: the escape
  hatch, recognized at ANY hop, never traversed.
- **Unknown edges** (a duck-typed ``self.server.foo()``, a method on
  an attribute-sourced object, a name the resolver can't find) are
  RECORDED with a reason, never guessed at. Reachability simply
  stops there; ``CallGraph.unknown`` keeps the honesty auditable.

Parsing is paid once: ``ProjectContext`` holds the parsed-AST forest
(one ``ModuleContext`` per file) and the built ``CallGraph``, shared
by every rule in a scan.
"""
from __future__ import annotations

import ast
import builtins
import os
from collections import deque
from dataclasses import dataclass, field
from typing import (
    Dict,
    Iterable,
    List,
    Mapping,
    Optional,
    Sequence,
    Set,
    Tuple,
)

from .cpcheck import (
    AsyncBlockRule,
    Finding,
    HotSyncRule,
    LockPubRule,
    ModuleContext,
    RetraceRule,
    _body_nodes,
    _expr_path,
    _is_hotpath,
    _Pragmas,
    _index_scopes,
    dotted_name,
)

_BUILTIN_NAMES = frozenset(dir(builtins))

#: call tails whose arguments are the sanctioned off-loop escape
EXECUTOR_TAILS = AsyncBlockRule.EXECUTOR_TAILS
#: call tails that schedule their first argument to run LATER
SPAWN_TAILS = {"spawn", "create_task", "ensure_future"}
PARTIAL_TAILS = {"partial"}

#: edge kinds the synchronous-reachability rules may walk
SYNC_KINDS = ("direct", "method")


def module_name(path: str) -> str:
    """Dotted module name for a repo-relative path:
    ``containerpilot_tpu/fleet/member.py`` ->
    ``containerpilot_tpu.fleet.member``; ``__init__.py`` names the
    package itself; non-.py scratch paths name themselves."""
    name = path[:-3] if path.endswith(".py") else path
    name = name.replace(os.sep, "/").replace("/", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


@dataclass
class FunctionInfo:
    """One function or method the graph knows about."""

    key: str          # "<module>:<qualified scope>"
    module: str       # dotted module name
    scope: str        # qualname inside the module ("Cls.meth")
    node: ast.AST     # the FunctionDef / AsyncFunctionDef
    ctx: ModuleContext
    cls: Optional[str] = None  # enclosing class name, if a method

    @property
    def is_async(self) -> bool:
        return isinstance(self.node, ast.AsyncFunctionDef)

    @property
    def display(self) -> str:
        return f"{self.module}.{self.scope}"


@dataclass(frozen=True)
class CallEdge:
    """A resolved call: caller -> callee, with enough provenance to
    print a witness path."""

    caller: str
    callee: str
    lineno: int
    kind: str          # direct | method | partial | spawn
    sanctioned: bool   # referenced inside run_in_executor/to_thread


@dataclass(frozen=True)
class UnknownEdge:
    """A call the resolver refused to guess at — recorded, not lost."""

    caller: str
    name: str
    lineno: int
    reason: str


@dataclass
class _ClassInfo:
    name: str
    methods: Dict[str, ast.AST] = field(default_factory=dict)
    bases: Tuple[str, ...] = ()


@dataclass
class _ModuleInfo:
    """Per-module symbol table feeding resolution."""

    ctx: ModuleContext
    name: str
    is_package: bool = False
    funcs: Dict[str, ast.AST] = field(default_factory=dict)
    classes: Dict[str, _ClassInfo] = field(default_factory=dict)
    #: import alias -> dotted module name
    imports_mod: Dict[str, str] = field(default_factory=dict)
    #: import alias -> (dotted module name, symbol)
    imports_sym: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    #: module-level `name = SomeClass()` instances -> (module, class)
    instances: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    #: every module-level assigned name (lock-identity qualification)
    global_names: Set[str] = field(default_factory=set)


class ProjectContext:
    """The parsed-AST forest for one scan: every ModuleContext, the
    symbol tables, and (built once, lazily) the call graph. Shared by
    all interprocedural rules so each file is parsed exactly once."""

    def __init__(self, contexts: Sequence[ModuleContext]) -> None:
        self.contexts: List[ModuleContext] = list(contexts)
        self.by_path: Dict[str, ModuleContext] = {
            ctx.path: ctx for ctx in self.contexts
        }
        self._graph: Optional[CallGraph] = None

    @property
    def graph(self) -> "CallGraph":
        if self._graph is None:
            self._graph = CallGraph(self)
        return self._graph


def build_project(sources: Mapping[str, str]) -> ProjectContext:
    """Parse a ``{path: source}`` mapping into a ProjectContext —
    the in-memory entry point tests and scan_source use."""
    contexts = []
    for path in sorted(sources):
        tree = ast.parse(sources[path], filename=path)
        ctx = ModuleContext(
            path=path,
            tree=tree,
            lines=sources[path].splitlines(),
            pragmas=_Pragmas(sources[path]),
        )
        _index_scopes(ctx)
        contexts.append(ctx)
    return ProjectContext(contexts)


def build_project_from_paths(
    paths: Sequence[str], relative_to: str
) -> ProjectContext:
    """Parse files from disk; paths are reported (and keyed)
    relative to ``relative_to``, matching scan_file's convention."""
    sources: Dict[str, str] = {}
    for path in paths:
        rel = os.path.relpath(path, relative_to).replace(os.sep, "/")
        with open(path, "r", encoding="utf-8") as fh:
            sources[rel] = fh.read()
    return build_project(sources)


class CallGraph:
    """Project-wide symbol table + call edges + reachability."""

    def __init__(self, project: ProjectContext) -> None:
        self.project = project
        self.modules: Dict[str, _ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.edges_from: Dict[str, List[CallEdge]] = {}
        self.unknown: List[UnknownEdge] = []
        for ctx in project.contexts:
            self._index_module(ctx)
        for info in list(self.functions.values()):
            self._extract_edges(info)

    # -- symbol tables -------------------------------------------------

    def _index_module(self, ctx: ModuleContext) -> None:
        mod = _ModuleInfo(
            ctx=ctx,
            name=module_name(ctx.path),
            is_package=ctx.path.endswith("__init__.py"),
        )
        self.modules[mod.name] = mod
        for stmt in ctx.tree.body:
            if isinstance(stmt, (ast.Import,)):
                for alias in stmt.names:
                    mod.imports_mod[
                        alias.asname or alias.name.partition(".")[0]
                    ] = alias.name if alias.asname else (
                        alias.name.partition(".")[0]
                    )
            elif isinstance(stmt, ast.ImportFrom):
                target = self._import_base(mod, stmt)
                if target is None:
                    continue
                for alias in stmt.names:
                    if alias.name == "*":
                        continue
                    mod.imports_sym[alias.asname or alias.name] = (
                        target, alias.name
                    )
            elif isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                mod.funcs[stmt.name] = stmt
                self._add_function(mod, ctx, stmt, cls=None)
            elif isinstance(stmt, ast.ClassDef):
                info = _ClassInfo(
                    name=stmt.name,
                    bases=tuple(
                        dotted_name(b) for b in stmt.bases
                        if dotted_name(b)
                    ),
                )
                mod.classes[stmt.name] = info
                for member in stmt.body:
                    if isinstance(
                        member,
                        (ast.FunctionDef, ast.AsyncFunctionDef),
                    ):
                        info.methods[member.name] = member
                        self._add_function(
                            mod, ctx, member, cls=stmt.name
                        )
            elif isinstance(stmt, ast.Assign):
                for target in stmt.targets:
                    if isinstance(target, ast.Name):
                        mod.global_names.add(target.id)
        # module-level instances need classes + imports indexed first
        for stmt in ctx.tree.body:
            if not (
                isinstance(stmt, ast.Assign)
                and isinstance(stmt.value, ast.Call)
            ):
                continue
            cls = self._resolve_class(mod, dotted_name(stmt.value.func))
            if cls is None:
                continue
            for target in stmt.targets:
                if isinstance(target, ast.Name):
                    mod.instances[target.id] = cls

    @staticmethod
    def _import_base(
        mod: _ModuleInfo, stmt: ast.ImportFrom
    ) -> Optional[str]:
        """Absolute module a ``from X import ...`` names, resolving
        relative levels against this module's package."""
        if stmt.level == 0:
            return stmt.module
        # a package __init__'s own name IS its package; a plain
        # module's package is its parent
        pkg = mod.name.split(".")
        if not mod.is_package:
            pkg = pkg[:-1]
        drop = stmt.level - 1
        if drop > len(pkg):
            return None
        base = pkg[: len(pkg) - drop]
        if stmt.module:
            base = base + stmt.module.split(".")
        return ".".join(base) if base else None

    def _add_function(
        self,
        mod: _ModuleInfo,
        ctx: ModuleContext,
        node: ast.AST,
        cls: Optional[str],
    ) -> None:
        scope = f"{cls}.{node.name}" if cls else node.name
        key = f"{mod.name}:{scope}"
        self.functions[key] = FunctionInfo(
            key=key, module=mod.name, scope=scope,
            node=node, ctx=ctx, cls=cls,
        )

    # -- resolution ----------------------------------------------------

    def _resolve_class(
        self, mod: _ModuleInfo, name: str
    ) -> Optional[Tuple[str, str]]:
        """``(module, class)`` a dotted name refers to, else None."""
        if not name:
            return None
        parts = name.split(".")
        if len(parts) == 1:
            if parts[0] in mod.classes:
                return (mod.name, parts[0])
            sym = mod.imports_sym.get(parts[0])
            if sym:
                target = self.modules.get(sym[0])
                if target and sym[1] in target.classes:
                    return (sym[0], sym[1])
            return None
        if len(parts) == 2 and parts[0] in mod.imports_mod:
            target = self.modules.get(mod.imports_mod[parts[0]])
            if target and parts[1] in target.classes:
                return (target.name, parts[1])
        return None

    def _method_key(
        self, cmod: str, cname: str, meth: str,
        seen: Optional[Set[str]] = None,
    ) -> Optional[str]:
        """Resolve a method on class ``cmod.cname``, walking base
        classes the project can see (single inheritance chains)."""
        seen = seen if seen is not None else set()
        if f"{cmod}.{cname}" in seen:
            return None
        seen.add(f"{cmod}.{cname}")
        mod = self.modules.get(cmod)
        if mod is None:
            return None
        info = mod.classes.get(cname)
        if info is None:
            return None
        if meth in info.methods:
            return f"{cmod}:{cname}.{meth}"
        for base in info.bases:
            resolved = self._resolve_class(mod, base)
            if resolved:
                key = self._method_key(
                    resolved[0], resolved[1], meth, seen
                )
                if key:
                    return key
        return None

    def resolve(
        self,
        mod: _ModuleInfo,
        name: str,
        current_cls: Optional[str],
        local_types: Mapping[str, Tuple[str, str]],
    ) -> Tuple[Optional[str], Optional[str]]:
        """Resolve a dotted call name to a function key.

        Returns ``(key, None)`` on success, ``(None, reason)`` for an
        honest unknown, and ``(None, None)`` for calls that are
        out of scope for the graph (builtins, external modules,
        constructors — nothing to record)."""
        if not name:
            return None, "unresolvable call expression"
        parts = name.split(".")
        head = parts[0]
        if len(parts) == 1:
            if head in mod.funcs:
                return f"{mod.name}:{head}", None
            sym = mod.imports_sym.get(head)
            if sym is not None:
                target = self.modules.get(sym[0])
                if target is None:
                    return None, None  # external import
                if sym[1] in target.funcs:
                    return f"{target.name}:{sym[1]}", None
                if sym[1] in target.classes:
                    return None, None  # constructor
                # re-exported through a package __init__ we parsed
                hop = target.imports_sym.get(sym[1])
                if hop is not None:
                    hop_mod = self.modules.get(hop[0])
                    if hop_mod and hop[1] in hop_mod.funcs:
                        return f"{hop_mod.name}:{hop[1]}", None
                return None, None
            if head in mod.classes or head in _BUILTIN_NAMES:
                return None, None  # constructor / builtin
            if head in local_types or head in mod.instances:
                return None, None  # calling the instance itself
            return None, None  # plain local callable variable etc.
        # dotted: resolve the receiver
        rest = parts[1:]
        if head in ("self", "cls") and current_cls:
            if len(rest) == 1:
                key = self._method_key(mod.name, current_cls, rest[0])
                if key:
                    return key, None
                return None, (
                    f"method `{name}` not found on "
                    f"{mod.name}.{current_cls} or its visible bases"
                )
            return None, f"attribute chain `{name}` not typed"
        receiver_cls = local_types.get(head) or mod.instances.get(head)
        if receiver_cls and len(rest) == 1:
            key = self._method_key(
                receiver_cls[0], receiver_cls[1], rest[0]
            )
            if key:
                return key, None
            return None, (
                f"method `{rest[0]}` not found on instance of "
                f"{receiver_cls[0]}.{receiver_cls[1]}"
            )
        if head in mod.imports_mod:
            target = self.modules.get(mod.imports_mod[head])
            if target is None:
                return None, None  # stdlib / external module
            if len(rest) == 1 and rest[0] in target.funcs:
                return f"{target.name}:{rest[0]}", None
            if len(rest) == 2 and rest[0] in target.classes:
                key = self._method_key(target.name, rest[0], rest[1])
                if key:
                    return key, None
            return None, None
        if head in mod.imports_sym:
            # module imported from a package: `from .. import notes`
            sym = mod.imports_sym[head]
            dotted = f"{sym[0]}.{sym[1]}"
            target = self.modules.get(dotted)
            if target and len(rest) == 1 and rest[0] in target.funcs:
                return f"{target.name}:{rest[0]}", None
            if target is not None:
                return None, None
        if head in ("self", "cls"):
            return None, f"`{name}` outside a known class"
        # a call through an untyped receiver: the honest unknown
        return None, f"receiver `{head}` has no known type"

    # -- edge extraction -----------------------------------------------

    def _extract_edges(self, info: FunctionInfo) -> None:
        mod = self.modules[info.module]
        edges: List[CallEdge] = []
        local_types: Dict[str, Tuple[str, str]] = {}
        body = getattr(info.node, "body", [])
        for node in _body_nodes(body, skip_defs=True):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                cls = self._resolve_class(
                    mod, dotted_name(node.value.func)
                )
                if cls:
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            local_types[target.id] = cls

        def add(
            target: Optional[str],
            reason: Optional[str],
            node: ast.AST,
            kind: str,
            sanctioned: bool,
            name: str,
        ) -> None:
            if target is not None:
                edges.append(CallEdge(
                    caller=info.key, callee=target,
                    lineno=node.lineno, kind=kind,
                    sanctioned=sanctioned,
                ))
            elif reason is not None:
                self.unknown.append(UnknownEdge(
                    caller=info.key, name=name,
                    lineno=node.lineno, reason=reason,
                ))

        def resolve_ref(expr: ast.AST) -> Tuple[
            Optional[str], Optional[str], str
        ]:
            name = dotted_name(expr)
            key, reason = self.resolve(
                mod, name, info.cls, local_types
            )
            return key, reason, name

        def visit(node: ast.AST, sanctioned: bool) -> None:
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef,
                 ast.ClassDef, ast.Lambda),
            ):
                return  # nested defs run later, on their own frames
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                tail = name.rpartition(".")[2]
                if tail in EXECUTOR_TAILS:
                    # arguments are the escape hatch: callables named
                    # here become sanctioned edges, and calls nested
                    # inside run on the executor, not this frame
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        visit(arg, True)
                    return
                if tail in SPAWN_TAILS and node.args and isinstance(
                    node.args[0], ast.Call
                ):
                    inner = node.args[0]
                    key, reason, iname = resolve_ref(inner.func)
                    add(key, reason, inner, "spawn", True, iname)
                    for arg in list(inner.args) + [
                        kw.value for kw in inner.keywords
                    ]:
                        visit(arg, sanctioned)
                    for arg in list(node.args[1:]) + [
                        kw.value for kw in node.keywords
                    ]:
                        visit(arg, sanctioned)
                    return
                if tail in PARTIAL_TAILS and node.args:
                    key, reason, iname = resolve_ref(node.args[0])
                    add(
                        key, reason, node, "partial", sanctioned,
                        iname,
                    )
                    for arg in list(node.args[1:]) + [
                        kw.value for kw in node.keywords
                    ]:
                        visit(arg, sanctioned)
                    return
                key, reason, _ = resolve_ref(node.func)
                if key is not None:
                    callee = self.functions.get(key)
                    kind = (
                        "method"
                        if callee is not None and callee.cls
                        else "direct"
                    )
                    add(key, None, node, kind, sanctioned, name)
                elif reason is not None:
                    add(None, reason, node, "direct", sanctioned, name)
                # descend into arguments (and a computed func
                # expression), but not the plain func name itself —
                # the edge above already covers it
                if not isinstance(
                    node.func, (ast.Name, ast.Attribute)
                ):
                    visit(node.func, sanctioned)
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    visit(arg, sanctioned)
                return
            # a bare callable reference inside executor args (the
            # `run_in_executor(None, fn)` shape) becomes a
            # sanctioned edge; its identity resolving to nothing is
            # normal data, not an unknown worth recording
            if sanctioned and isinstance(
                node, (ast.Name, ast.Attribute)
            ):
                key, _reason, name = resolve_ref(node)
                if key is not None:
                    add(key, None, node, "direct", True, name)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, sanctioned)

        for stmt in body:
            visit(stmt, False)
        self.edges_from[info.key] = edges

    # -- queries -------------------------------------------------------

    def sync_reachable(
        self,
        root: str,
        max_hops: Optional[int] = None,
    ) -> Iterable[Tuple[FunctionInfo, Tuple[CallEdge, ...]]]:
        """BFS over UNsanctioned, synchronous (direct/method) edges
        from ``root``, yielding each reached SYNC function once with
        the (shortest) edge path that reached it. Async callees are
        not yielded or traversed: an awaited coroutine suspends, it
        does not hold the caller's frame; deferred kinds (partial,
        spawn) run later, elsewhere."""
        seen: Set[str] = {root}
        queue: deque = deque([(root, ())])
        while queue:
            key, path = queue.popleft()
            if max_hops is not None and len(path) >= max_hops:
                continue
            for edge in self.edges_from.get(key, ()):
                if edge.sanctioned or edge.kind not in SYNC_KINDS:
                    continue
                if edge.callee in seen:
                    continue
                callee = self.functions.get(edge.callee)
                if callee is None or callee.is_async:
                    continue
                seen.add(edge.callee)
                new_path = path + (edge,)
                yield callee, new_path
                queue.append((edge.callee, new_path))


# -- interprocedural rules -------------------------------------------------


class ProjectRule:
    """Base: like cpcheck.Rule, but ``run_project`` sees the whole
    forest + graph at once instead of one module."""

    rule_id = "CP-NONE"

    def run_project(
        self, project: ProjectContext
    ) -> List[Finding]:  # pragma: no cover - interface
        raise NotImplementedError

    def finding_at(
        self,
        ctx: ModuleContext,
        lineno: int,
        scope: str,
        message: str,
    ) -> Optional[Finding]:
        if ctx.pragmas.is_disabled(self.rule_id, lineno):
            return None
        return Finding(
            rule=self.rule_id, file=ctx.path, line=lineno,
            scope=scope, text=ctx.line_text(lineno), message=message,
        )


def _blocking_calls(
    fn: ast.AST,
) -> Iterable[Tuple[ast.Call, str]]:
    """CP-ASYNCBLOCK catalog hits in a function body, with the
    executor escape honored lexically (calls inside
    run_in_executor/to_thread arguments are healed) and nested defs
    skipped. Name-catalog only — the .result()/.join() dataflow part
    of CP-ASYNCBLOCK stays lexical, where its aliasing is sound."""
    out: List[Tuple[ast.Call, str]] = []

    def visit(node: ast.AST) -> None:
        if isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
             ast.Lambda),
        ):
            return
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            tail = name.rpartition(".")[2]
            if tail in EXECUTOR_TAILS:
                visit(node.func)
                return
            if (
                name in AsyncBlockRule.BLOCKED_NAMES
                or tail in AsyncBlockRule.BLOCKED_TAILS
            ):
                out.append((node, name or tail))
        for child in ast.iter_child_nodes(node):
            visit(child)

    for stmt in getattr(fn, "body", []):
        visit(stmt)
    return out


def _chain(root: FunctionInfo, path: Sequence[CallEdge],
           graph: CallGraph) -> str:
    names = [root.display]
    for edge in path:
        callee = graph.functions.get(edge.callee)
        names.append(callee.display if callee else edge.callee)
    return " -> ".join(names)


class AsyncReachRule(ProjectRule):
    """CP-ASYNCREACH: a blocking call reachable from an ``async def``
    through at most 3 synchronous call hops.

    CP-ASYNCBLOCK only fires on direct lexical containment; one
    innocent-looking sync helper hides the stall. This rule walks the
    call graph from every async function over resolved sync edges
    (hop bound 3 — deep chains get noisy and helper 4 is still
    covered from helper 1's own callers), flagging CP-ASYNCBLOCK
    name-catalog hits in any reached helper. The executor heal is
    recognized at ANY hop: a sanctioned edge is never traversed, and
    a blocking call lexically inside executor args inside a helper is
    healed exactly as the lexical rule heals it. The finding anchors
    at the FIRST hop's call site in the async function — that is the
    line its author can fix — with the full chain in the message."""

    rule_id = "CP-ASYNCREACH"

    MAX_HOPS = 3

    def run_project(self, project: ProjectContext) -> List[Finding]:
        graph = project.graph
        findings: List[Finding] = []
        for info in graph.functions.values():
            if not info.is_async:
                continue
            for helper, path in graph.sync_reachable(
                info.key, max_hops=self.MAX_HOPS
            ):
                for call, name in _blocking_calls(helper.node):
                    if helper.ctx.pragmas.is_disabled(
                        self.rule_id, call.lineno
                    ):
                        continue
                    first = path[0]
                    f = self.finding_at(
                        info.ctx, first.lineno, info.scope,
                        f"blocking `{name}` reachable from async "
                        f"`{info.scope}` via "
                        f"{_chain(info, path, graph)} "
                        f"({helper.ctx.path}:{call.lineno}): "
                        "stalls the event loop — run the chain in "
                        "an executor or heal the hop",
                    )
                    if f:
                        findings.append(f)
        return findings


class HotReachRule(ProjectRule):
    """CP-HOTREACH: ``# cpcheck: hotpath`` propagates through the
    call graph.

    A hot function's helpers execute inside the same decode round;
    lexically they escape CP-HOTSYNC/CP-RETRACE entirely. This rule
    reaches every sync helper transitively callable from a hot root
    (no hop bound — heat is transitive; sanctioned and deferred edges
    excluded) and runs the HOTSYNC catalog and RETRACE varying-arg
    checks on the INHERITED functions, anchoring each finding at the
    violating line in the helper with the inheritance chain in the
    message. Roots themselves stay the lexical rules' business. A
    helper's existing `disable=CP-HOTSYNC` / `CP-RETRACE` pragma is
    honored for the inherited check too — one deliberate sync point
    stays one annotation."""

    rule_id = "CP-HOTREACH"

    def run_project(self, project: ProjectContext) -> List[Finding]:
        graph = project.graph
        retrace = RetraceRule()
        jit_bound_cache: Dict[str, Set[str]] = {}
        findings: List[Finding] = []
        hot_roots = [
            info for info in graph.functions.values()
            if _is_hotpath(info.node, info.ctx)
        ]
        reported: Set[Tuple[str, int]] = set()
        for root in hot_roots:
            for helper, path in graph.sync_reachable(root.key):
                if _is_hotpath(helper.node, helper.ctx):
                    continue  # its own root; lexical rules cover it
                if helper.ctx.pragmas.is_disabled(
                    self.rule_id, helper.node.lineno
                ):
                    # a disable pragma on the `def` line opts the whole
                    # function out of heat inheritance — for helpers
                    # that are deliberately cold (debug dumps, guarded
                    # slow paths) one annotation beats one per line
                    continue
                chain = _chain(root, path, graph)
                findings.extend(self._check_inherited(
                    helper, chain, retrace, jit_bound_cache, reported
                ))
        return findings

    def _check_inherited(
        self,
        helper: FunctionInfo,
        chain: str,
        retrace: RetraceRule,
        jit_bound_cache: Dict[str, Set[str]],
        reported: Set[Tuple[str, int]],
    ) -> List[Finding]:
        ctx = helper.ctx
        findings: List[Finding] = []

        def emit(node: ast.AST, message: str, shadow: str) -> None:
            # a pragma for the lexical twin rule heals the inherited
            # check too; dedupe across multiple hot roots
            if ctx.pragmas.is_disabled(shadow, node.lineno):
                return
            if (ctx.path, node.lineno) in reported:
                return
            f = self.finding_at(
                ctx, node.lineno, helper.scope, message
            )
            if f:
                reported.add((ctx.path, node.lineno))
                findings.append(f)

        for sub in _body_nodes(
            getattr(helper.node, "body", []), skip_defs=True
        ):
            if not isinstance(sub, ast.Call):
                continue
            name = dotted_name(sub.func)
            tail = name.rpartition(".")[2]
            if (
                name in HotSyncRule.BLOCKED_NAMES
                or tail in HotSyncRule.BLOCKED_ATTRS
            ):
                emit(
                    sub,
                    f"host sync `{name or tail}` in `{helper.scope}`,"
                    f" which inherits hotpath via {chain}",
                    HotSyncRule.rule_id,
                )
                continue
            if ctx.path not in jit_bound_cache:
                jit_bound_cache[ctx.path] = retrace._jit_bound(ctx)
            bound = jit_bound_cache[ctx.path]
            jitted = (
                name in bound
                or tail in bound
                or name in RetraceRule.SCAN_NAMES
            )
            if not jitted:
                continue
            for arg in list(sub.args) + [
                kw.value for kw in sub.keywords
            ]:
                reason = retrace._varying(arg)
                if reason is None:
                    continue
                emit(
                    sub,
                    f"jitted `{name}` called with {reason} in "
                    f"`{helper.scope}`, which inherits hotpath via "
                    f"{chain}: every distinct value is a silent "
                    "recompile",
                    RetraceRule.rule_id,
                )
                break
        return findings


@dataclass(frozen=True)
class _LockEdge:
    """held -> acquired, with one witness location."""

    held: str
    acquired: str
    ctx: ModuleContext
    lineno: int
    scope: str
    via: str  # "" for a direct nested acquire, else the call chain


class LockOrderRule(ProjectRule):
    """CP-LOCKORDER: a cycle in the project-wide lock acquisition-
    order graph — the static face of racecheck.

    Per function, ``with``/``async with`` acquisitions of lockish
    objects (LockPubRule's heuristic: a name containing lock/mutex,
    or an ``.acquire()`` context) are summarized; while lock A is
    held, a directly-nested acquisition of B — or a call into a
    function whose TRANSITIVE summary acquires B — adds the edge
    A -> B. Identities are qualified (``self._lock`` on a method of
    ``m.C`` is ``m.C._lock``; module globals are module-qualified;
    anything else stays function-local and can't alias). A cycle
    means two code paths can interleave into a deadlock racecheck's
    runtime tests would only catch if they happened to drive both
    orders under contention; the finding carries BOTH witness paths.
    Reentrant self-edges (A -> A) are skipped: same-lock reentry is
    RLock's business, not ordering's."""

    rule_id = "CP-LOCKORDER"

    def run_project(self, project: ProjectContext) -> List[Finding]:
        graph = project.graph
        # per-function: direct acquisitions + (held, call-edge) pairs
        direct: Dict[str, List[Tuple[str, int]]] = {}
        held_calls: Dict[
            str, List[Tuple[Tuple[str, ...], CallEdge]]
        ] = {}
        direct_edges: List[_LockEdge] = []
        for info in graph.functions.values():
            self._summarize(
                graph, info, direct, held_calls, direct_edges
            )
        # transitive acquisition summaries, memoized over the graph
        memo: Dict[str, Dict[str, str]] = {}

        def transitive(key: str, stack: Set[str]) -> Dict[str, str]:
            """lock -> display-chain of the function that acquires
            it, for every lock a call to ``key`` may take."""
            if key in memo:
                return memo[key]
            if key in stack:
                return {}
            stack.add(key)
            info = graph.functions.get(key)
            out: Dict[str, str] = {}
            for lock, _lineno in direct.get(key, ()):
                out.setdefault(lock, info.display if info else key)
            for edge in graph.edges_from.get(key, ()):
                if edge.sanctioned or edge.kind not in SYNC_KINDS:
                    continue
                for lock, via in transitive(
                    edge.callee, stack
                ).items():
                    out.setdefault(lock, via)
            stack.discard(key)
            memo[key] = out
            return out

        # build the acquisition-order graph with witnesses
        order: Dict[str, Dict[str, _LockEdge]] = {}

        def add_edge(edge: _LockEdge) -> None:
            if edge.held == edge.acquired:
                return  # reentry, not ordering
            order.setdefault(edge.held, {}).setdefault(
                edge.acquired, edge
            )

        for key, pairs in held_calls.items():
            info = graph.functions[key]
            for held_stack, item in pairs:
                callee_locks = transitive(item.callee, set())
                for lock, via in callee_locks.items():
                    for held in held_stack:
                        add_edge(_LockEdge(
                            held=held, acquired=lock,
                            ctx=info.ctx, lineno=item.lineno,
                            scope=info.scope,
                            via=via,
                        ))
        for edge in direct_edges:
            add_edge(edge)

        return self._report_cycles(order)

    def _summarize(
        self,
        graph: CallGraph,
        info: FunctionInfo,
        direct: Dict[str, List[Tuple[str, int]]],
        held_calls: Dict[
            str, List[Tuple[Tuple[str, ...], CallEdge]]
        ],
        direct_edges: List[_LockEdge],
    ) -> None:
        mod = graph.modules[info.module]
        acquired: List[Tuple[str, int]] = []
        pairs: List[Tuple[Tuple[str, ...], CallEdge]] = []
        edges_by_line: Dict[int, List[CallEdge]] = {}
        for edge in graph.edges_from.get(info.key, ()):
            edges_by_line.setdefault(edge.lineno, []).append(edge)

        def lock_id(expr: ast.AST) -> Optional[str]:
            target = expr
            if isinstance(expr, ast.Call) and isinstance(
                expr.func, ast.Attribute
            ) and expr.func.attr == "acquire":
                target = expr.func.value
            if not LockPubRule._is_lockish(expr):
                return None
            path = _expr_path(target)
            if path is None:
                return None
            head, _, rest = path.partition(".")
            if head in ("self", "cls") and info.cls and rest:
                return f"{info.module}.{info.cls}.{rest}"
            if "." not in path and path in mod.global_names:
                return f"{info.module}.{path}"
            # function-local lock: scoped so it can never alias
            return f"{info.module}.{info.scope}:{path}"

        def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef,
                 ast.ClassDef, ast.Lambda),
            ):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                locks = []
                for item in node.items:
                    lk = lock_id(item.context_expr)
                    if lk is not None:
                        locks.append(lk)
                        acquired.append((lk, node.lineno))
                        for h in held:
                            direct_edges.append(_LockEdge(
                                held=h, acquired=lk,
                                ctx=info.ctx, lineno=node.lineno,
                                scope=info.scope, via="",
                            ))
                inner = held + tuple(locks)
                for item in node.items:
                    visit(item.context_expr, held)
                for stmt in node.body:
                    visit(stmt, inner)
                return
            if isinstance(node, ast.Call) and held:
                for edge in edges_by_line.get(node.lineno, ()):
                    pairs.append((held, edge))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in getattr(info.node, "body", []):
            visit(stmt, ())
        if acquired:
            direct[info.key] = acquired
        if pairs:
            held_calls[info.key] = pairs

    def _report_cycles(
        self, order: Dict[str, Dict[str, _LockEdge]]
    ) -> List[Finding]:
        findings: List[Finding] = []
        seen_cycles: Set[Tuple[str, ...]] = set()
        for a in sorted(order):
            for b in sorted(order[a]):
                path = self._find_path(order, b, a)
                if path is None:
                    continue
                cycle = [order[a][b]] + path
                locks = tuple(sorted({e.held for e in cycle}))
                if locks in seen_cycles:
                    continue
                seen_cycles.add(locks)
                witness = "; ".join(
                    f"{e.held} -> {e.acquired} at "
                    f"{e.ctx.path}:{e.lineno} in {e.scope}"
                    + (f" (via {e.via})" if e.via else "")
                    for e in cycle
                )
                anchor = cycle[0]
                f = self.finding_at(
                    anchor.ctx, anchor.lineno, anchor.scope,
                    "lock-order cycle "
                    f"{' -> '.join(locks + (locks[0],))}: two "
                    "threads driving these paths concurrently can "
                    f"deadlock — witness: {witness}",
                )
                if f:
                    findings.append(f)
        return findings

    @staticmethod
    def _find_path(
        order: Dict[str, Dict[str, _LockEdge]],
        start: str,
        goal: str,
    ) -> Optional[List[_LockEdge]]:
        """Shortest edge path start -> ... -> goal, else None."""
        queue: deque = deque([(start, [])])
        seen = {start}
        while queue:
            node, path = queue.popleft()
            if node == goal:
                return path
            for nxt in sorted(order.get(node, ())):
                if nxt in seen:
                    continue
                seen.add(nxt)
                queue.append((nxt, path + [order[node][nxt]]))
        return None


class NoteWireRule(ProjectRule):
    """CP-NOTEWIRE: the heartbeat note wire has ONE schema —
    ``fleet/notes.py`` — and nothing routes around it.

    The registry is discovered structurally (a module assigning
    ``FIELDS = (NoteField(name="...", produce=..., parse=...), ...)``)
    so the rule checks what the code SHIPS, not what this rule
    remembers. Three checks:

    1. every registered field carries both a producer and a parser
       (a field produced that nothing can read — or parsed but never
       produced — is schema drift by construction);
    2. outside the registry module, no f-string or ``"x=" +``
       concatenation emits a registered field name — that emission
       bypasses ``member_note`` and whatever encoding discipline the
       registry's producer applies;
    3. every field CONSUMED from a split note (``fields["x"]``,
       ``fields.get("x")``, ``"x" in fields`` on a name bound from
       ``split_note``/``parse_kv_note``, or a literal
       ``parse_field("x", ...)``) must be registered — parsing a
       field nothing produces is dead wire vocabulary.

    Projects with no registry module (every fixture in the test
    suite's other rules) are out of scope: the rule is silent."""

    rule_id = "CP-NOTEWIRE"

    SPLIT_TAILS = {"split_note", "parse_kv_note"}

    def run_project(self, project: ProjectContext) -> List[Finding]:
        registries = self._find_registries(project)
        if not registries:
            return []
        findings: List[Finding] = []
        names: Set[str] = set()
        registry_paths = set()
        for ctx, fields in registries:
            registry_paths.add(ctx.path)
            for fname, (node, has_produce, has_parse) in (
                fields.items()
            ):
                names.add(fname)
                if not has_produce or not has_parse:
                    missing = "producer" if not has_produce else (
                        "parser"
                    )
                    f = self.finding_at(
                        ctx, node.lineno, ctx.scope_of(node),
                        f"note field `{fname}` registered without a "
                        f"{missing}: every wire field needs both "
                        "ends",
                    )
                    if f:
                        findings.append(f)
        for ctx in project.contexts:
            if ctx.path in registry_paths:
                continue
            findings.extend(self._check_bypass(ctx, names))
            findings.extend(self._check_consumption(ctx, names))
        return findings

    def _find_registries(
        self, project: ProjectContext
    ) -> List[Tuple[ModuleContext, Dict]]:
        out = []
        for ctx in project.contexts:
            fields = {}
            for stmt in ctx.tree.body:
                value = None
                if isinstance(stmt, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "FIELDS"
                    for t in stmt.targets
                ):
                    value = stmt.value
                elif isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ) and stmt.target.id == "FIELDS":
                    value = stmt.value
                if not isinstance(value, (ast.Tuple, ast.List)):
                    continue
                for elt in value.elts:
                    if not (
                        isinstance(elt, ast.Call)
                        and dotted_name(elt.func).rpartition(".")[2]
                        == "NoteField"
                    ):
                        continue
                    kw = {k.arg: k.value for k in elt.keywords}
                    name_node = kw.get("name")
                    if not (
                        isinstance(name_node, ast.Constant)
                        and isinstance(name_node.value, str)
                    ):
                        continue
                    fields[name_node.value] = (
                        elt,
                        _non_none(kw.get("produce")),
                        _non_none(kw.get("parse")),
                    )
            if fields:
                out.append((ctx, fields))
        return out

    def _check_bypass(
        self, ctx: ModuleContext, names: Set[str]
    ) -> List[Finding]:
        findings: List[Finding] = []
        markers = {f"{n}=" for n in names}

        def emit(node: ast.AST, fname: str, how: str) -> None:
            f = self.finding_at(
                ctx, node.lineno, ctx.scope_of(node),
                f"ad-hoc `{fname}=` {how} bypasses the note-wire "
                "registry: emit through fleet/notes.py's "
                "member_note/producers",
            )
            if f:
                findings.append(f)

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.JoinedStr):
                parts = node.values
                for i, part in enumerate(parts[:-1]):
                    if not (
                        isinstance(part, ast.Constant)
                        and isinstance(part.value, str)
                    ):
                        continue
                    if not isinstance(
                        parts[i + 1], ast.FormattedValue
                    ):
                        continue
                    for marker in markers:
                        if part.value.endswith(marker):
                            emit(node, marker[:-1], "f-string")
                            break
            elif isinstance(node, ast.BinOp) and isinstance(
                node.op, ast.Add
            ):
                for side in (node.left, node.right):
                    if (
                        isinstance(side, ast.Constant)
                        and isinstance(side.value, str)
                        and side.value.strip() in markers
                    ):
                        emit(
                            node, side.value.strip()[:-1],
                            "concatenation",
                        )
        return findings

    def _check_consumption(
        self, ctx: ModuleContext, names: Set[str]
    ) -> List[Finding]:
        findings: List[Finding] = []

        def emit(node: ast.AST, fname: str) -> None:
            f = self.finding_at(
                ctx, node.lineno, ctx.scope_of(node),
                f"field `{fname}` parsed from a heartbeat note but "
                "not registered in fleet/notes.py: nothing produces "
                "it",
            )
            if f:
                findings.append(f)

        def scan_scope(body: Sequence[ast.AST]) -> None:
            split_vars: Set[str] = set()
            nodes = list(_body_nodes(body, skip_defs=True))
            for node in nodes:
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    tail = dotted_name(
                        node.value.func
                    ).rpartition(".")[2]
                    if tail in self.SPLIT_TAILS:
                        for target in node.targets:
                            if isinstance(target, ast.Name):
                                split_vars.add(target.id)
            for node in nodes:
                fname = _literal_field_use(node, split_vars)
                if fname is not None and fname not in names:
                    emit(node, fname)

        scan_scope(ctx.tree.body)
        for node in ast.walk(ctx.tree):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                scan_scope(node.body)
        return findings


def _non_none(node: Optional[ast.AST]) -> bool:
    return node is not None and not (
        isinstance(node, ast.Constant) and node.value is None
    )


def _literal_field_use(
    node: ast.AST, split_vars: Set[str]
) -> Optional[str]:
    """The literal field name this node consumes from a split-note
    dict (subscript, .get, membership) or passes to parse_field."""
    if isinstance(node, ast.Subscript) and isinstance(
        node.value, ast.Name
    ) and node.value.id in split_vars and isinstance(
        node.slice, ast.Constant
    ) and isinstance(node.slice.value, str):
        return node.slice.value
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        tail = name.rpartition(".")[2]
        if (
            tail == "get"
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in split_vars
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            return node.args[0].value
        if (
            tail == "parse_field"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            return node.args[0].value
    if isinstance(node, ast.Compare) and len(node.ops) == 1 and (
        isinstance(node.ops[0], (ast.In, ast.NotIn))
    ):
        left, right = node.left, node.comparators[0]
        if (
            isinstance(left, ast.Constant)
            and isinstance(left.value, str)
            and isinstance(right, ast.Name)
            and right.id in split_vars
        ):
            return left.value
    return None


PROJECT_RULES: Tuple[ProjectRule, ...] = (
    AsyncReachRule(),
    HotReachRule(),
    LockOrderRule(),
    NoteWireRule(),
)

PROJECT_RULES_BY_ID: Dict[str, ProjectRule] = {
    r.rule_id: r for r in PROJECT_RULES
}


def run_project_rules(
    project: ProjectContext,
    rules: Sequence[ProjectRule] = PROJECT_RULES,
) -> List[Finding]:
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.run_project(project))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings
