"""Static + dynamic invariant checking for this repo (cpcheck).

``python -m containerpilot_tpu.analysis`` is the ``make lint`` body:
it byte-compiles the package (the old lint) and then runs the cpcheck
AST rules over every module, comparing findings against the committed
``analysis/baseline.json``. New findings exit non-zero; the baseline
enumerates pre-existing, justified debt instead of hiding it.

See ``docs/70-static-analysis.md`` for the rule catalog, the pragma
escape hatches, and the baseline workflow; ``racecheck.py`` is the
opt-in runtime lock-order/publish-discipline harness tests use, and
``loopcheck.py`` is its event-loop sibling (scheduling-lag probe +
leaked-task watchdog) that the gateway, replicas, and the chaos
harness run in production paths.
"""
from .callgraph import (
    PROJECT_RULES,
    PROJECT_RULES_BY_ID,
    CallGraph,
    ProjectContext,
    build_project,
    build_project_from_paths,
    run_project_rules,
)
from .cpcheck import (
    ALL_RULES,
    Finding,
    RULES_BY_ID,
    baseline_path,
    diff_against_baseline,
    explain_stale,
    hotpath,
    load_baseline,
    scan_file,
    scan_package,
    scan_project,
    scan_source,
    write_baseline,
)
from .loopcheck import LoopLagProbe, TaskWatchdog
from .racecheck import CheckedLock, RaceCheck, Violation

__all__ = [
    "LoopLagProbe",
    "TaskWatchdog",
    "ALL_RULES",
    "RULES_BY_ID",
    "PROJECT_RULES",
    "PROJECT_RULES_BY_ID",
    "CallGraph",
    "ProjectContext",
    "build_project",
    "build_project_from_paths",
    "run_project_rules",
    "Finding",
    "scan_source",
    "scan_file",
    "scan_package",
    "scan_project",
    "baseline_path",
    "load_baseline",
    "write_baseline",
    "diff_against_baseline",
    "explain_stale",
    "RaceCheck",
    "CheckedLock",
    "Violation",
    "hotpath",
]
