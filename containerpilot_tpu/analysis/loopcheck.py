"""loopcheck: a runtime event-loop health sentinel.

``racecheck.py`` keeps the *thread* half of this tree honest at
runtime; this module does the same for the *event-loop* half that
PRs 5-10 grew under the gateway, admission control, autoscaler, mux
transport, and every replica HTTP surface. The hazard is the one
CP-ASYNCBLOCK (cpcheck.py) catches lexically: the loop is
cooperative, so ONE blocking call — a sync sleep, a file read, a
``device_get`` on the wrong thread — stalls every multiplexed stream,
heartbeat, and catalog poll on the box at once. Under the
ML-goodput framing that stall is pure badput, and without a probe it
has no name: clients see TTFT jitter, /metrics sees nothing.

Two instruments, both cheap enough to run in production:

- **LoopLagProbe** — a monotonic heartbeat scheduled with
  ``call_later`` that measures how late the loop actually ran it
  versus when it asked to run (scheduling delay). Samples land in a
  fixed-size ring; ``max_ms``/``p99_ms`` are exposed as the
  ``cp_loop_lag_ms{stat}`` gauge on the gateway and replica
  ``/metrics`` surfaces, and the chaos harness gates every quick
  scenario on ``loop_lag_max_ms`` staying under a stated bound — so
  "the gateway hiccuped" is a named, gated regression, not a vibe.
  Overhead: one timer callback per ``interval_s`` (default 50ms),
  no allocation beyond the ring slot.
- **TaskWatchdog** — a task-factory wrapper (the runtime face of
  CP-TASKLEAK): every task created on the instrumented loop gets a
  done-callback, and a task that finished with an exception nobody
  retrieved within ``grace_s`` is recorded (ring) and logged with
  its name. ``CancelledError`` is never a leak. The grace window
  exists because a *handled* failure is retrieved by its awaiter on
  the very next wakeup; only orphans are still unretrieved after it.

Typical use (the chaos harness does exactly this)::

    probe = LoopLagProbe()
    watchdog = TaskWatchdog()
    probe.start(); watchdog.install()
    ... run the scenario ...
    probe.stop(); watchdog.uninstall()
    assert probe.max_ms() < BOUND
    assert watchdog.exceptions == []

Reading ``loop_lag_ms`` when paged: docs/70-static-analysis.md has
the runbook.
"""
from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

log = logging.getLogger("containerpilot.loopcheck")

#: heartbeat cadence: 20/s is fine-grained enough to catch a 100ms
#: stall while costing one trivial callback per 50ms
DEFAULT_INTERVAL_S = 0.05
#: lag samples retained (~51s of history at the default cadence)
RING_SIZE = 1024
#: how long an unretrieved task exception may wait for its awaiter
#: before the watchdog calls it leaked
DEFAULT_GRACE_S = 0.05


class LoopLagProbe:
    """Event-loop scheduling-delay probe: a self-rescheduling
    ``call_later`` heartbeat that records, per beat, how late the
    loop ran it (ms) into a fixed-size ring.

    The measured quantity is exactly what a request experiences: a
    callback due at T that runs at T+lag means every I/O wakeup,
    timer, and stream write due in that window also waited ``lag``.
    A clean loop reports ~0; a blocking call on the loop reports its
    own duration.
    """

    def __init__(
        self,
        interval_s: float = DEFAULT_INTERVAL_S,
        ring: int = RING_SIZE,
    ) -> None:
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self.interval_s = interval_s
        self._ring: Deque[float] = deque(maxlen=ring)
        self._handle: Optional[asyncio.TimerHandle] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._due = 0.0
        self.beats = 0
        self.running = False

    # -- lifecycle ------------------------------------------------------

    def start(
        self, loop: Optional[asyncio.AbstractEventLoop] = None
    ) -> "LoopLagProbe":
        """Begin heartbeating on ``loop`` (default: the current
        loop). Idempotent while running."""
        if self.running:
            return self
        self._loop = loop or asyncio.get_event_loop()
        self.running = True
        self._due = time.monotonic() + self.interval_s
        self._handle = self._loop.call_later(self.interval_s, self._beat)
        return self

    def stop(self) -> None:
        """Stop heartbeating; the ring keeps its samples."""
        self.running = False
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None

    def _beat(self) -> None:
        now = time.monotonic()
        # the loop ran this callback (now - due) late; clamp the
        # sub-ms early-fire jitter some platforms exhibit to zero
        self._ring.append(max(0.0, (now - self._due) * 1e3))
        self.beats += 1
        if self.running and self._loop is not None:
            self._due = now + self.interval_s
            self._handle = self._loop.call_later(
                self.interval_s, self._beat
            )

    # -- readings -------------------------------------------------------

    def max_ms(self) -> float:
        return max(self._ring) if self._ring else 0.0

    def p99_ms(self) -> float:
        if not self._ring:
            return 0.0
        ordered = sorted(self._ring)
        return ordered[min(len(ordered) - 1, int(0.99 * len(ordered)))]

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able summary (the chaos report's ``loop`` blob)."""
        return {
            "lag_max_ms": round(self.max_ms(), 2),
            "lag_p99_ms": round(self.p99_ms(), 2),
            "heartbeats": self.beats,
            "interval_ms": self.interval_s * 1e3,
        }


class TaskWatchdog:
    """Task-factory wrapper recording leaked-task exceptions.

    Installed on a loop, every task it creates gets a done-callback.
    A task that finishes with an exception is re-checked one grace
    window later: if no awaiter retrieved the exception by then (the
    fire-and-forget case — an awaited task's exception is retrieved
    on the awaiter's next wakeup, well inside the window), the
    exception is recorded in a fixed-size ring and logged with the
    task's name. Retrieving it here also takes ownership, so the
    interpreter's own destructor-time "exception was never retrieved"
    complaint (which fires at GC, far from the scene) is replaced by
    an immediate, attributed record.
    """

    def __init__(
        self, ring: int = 64, grace_s: float = DEFAULT_GRACE_S
    ) -> None:
        #: (task name, exception repr) per leaked exception
        self.exceptions: Deque[Tuple[str, str]] = deque(maxlen=ring)
        self.tasks_created = 0
        self.grace_s = grace_s
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._prev_factory: Any = None
        self.installed = False

    def install(
        self, loop: Optional[asyncio.AbstractEventLoop] = None
    ) -> "TaskWatchdog":
        if self.installed:
            return self
        self._loop = loop or asyncio.get_event_loop()
        self._prev_factory = self._loop.get_task_factory()
        self._loop.set_task_factory(self._factory)
        self.installed = True
        return self

    def uninstall(self) -> None:
        if not self.installed or self._loop is None:
            return
        self._loop.set_task_factory(self._prev_factory)
        self._prev_factory = None
        self.installed = False

    def _factory(self, loop, coro, **kwargs):
        if self._prev_factory is not None:
            task = self._prev_factory(loop, coro, **kwargs)
        else:
            task = asyncio.Task(coro, loop=loop, **kwargs)
        self.tasks_created += 1
        task.add_done_callback(self._on_done)
        return task

    def _on_done(self, task: "asyncio.Task") -> None:
        if task.cancelled():
            return
        # cheap pre-filter without retrieving: Future.exception()
        # would mark the exception retrieved and hide a real leak
        if getattr(task, "_exception", True) is None:  # noqa: SLF001
            return
        # defer the verdict one grace window: a legitimate awaiter
        # (await / gather / wait+result()) retrieves on its next
        # wakeup, which the loop schedules before this timer fires
        if self._loop is not None:
            self._loop.call_later(self.grace_s, self._check, task)

    def _check(self, task: "asyncio.Task") -> None:
        # _log_traceback flips False the moment anyone retrieves the
        # exception; still True after the grace window == leaked.
        # (CPython implementation detail; on others the getattr
        # default records every task exception, which errs loud.)
        if not getattr(task, "_log_traceback", True):
            return
        exc = task.exception()  # retrieve: we own it now
        if exc is None or isinstance(exc, asyncio.CancelledError):
            return
        self.exceptions.append((task.get_name(), repr(exc)))
        log.error(
            "leaked task %r died unobserved: %r", task.get_name(), exc,
            exc_info=exc,
        )

    def snapshot(self) -> List[Dict[str, str]]:
        """JSON-able list of recorded leaks."""
        return [
            {"task": name, "exception": exc}
            for name, exc in self.exceptions
        ]
