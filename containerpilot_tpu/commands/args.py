"""Exec argument parsing: accept a shell-ish string or a list.

Capability parity with the reference's argument parsing
(reference: commands/args.go:12-31): a string is whitespace-split, a
list is coerced to strings, and an empty exec is a config error.
"""
from __future__ import annotations

from typing import Any, List, Tuple


class ArgsError(ValueError):
    """Raised for an unusable exec specification."""


def parse_args(raw: Any) -> Tuple[str, List[str]]:
    """Return (executable, args) from a raw config value."""
    if isinstance(raw, str):
        parts = raw.strip().split()
    elif isinstance(raw, (list, tuple)):
        parts = [str(a) for a in raw]
    elif raw is None:
        parts = []
    else:
        raise ArgsError(f"unparseable exec: {raw!r}")
    if not parts:
        raise ArgsError("received zero-length argument")
    return parts[0], parts[1:]
