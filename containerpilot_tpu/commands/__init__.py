"""Process execution layer (reference: commands/ package)."""
from .args import ArgsError, parse_args
from .commands import Command

__all__ = ["Command", "parse_args", "ArgsError"]
