"""Process execution layer (reference: commands/ package)."""
from .args import ArgsError, parse_args
from .commands import Command, env_name

__all__ = ["Command", "env_name", "parse_args", "ArgsError"]
