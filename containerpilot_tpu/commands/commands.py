"""Process execution: spawn, supervise, and tear down child processes.

Capability parity with the reference's command wrapper
(reference: commands/commands.go). Semantics preserved:

- every child runs in its own process group / session so the whole
  subtree can be signalled together (reference: commands.go:104);
- per-exec timeout: on deadline the group is SIGKILLed and the exec is
  reported failed (reference: commands.go:114-120);
- ``term``/``kill`` signal the *group* (reference: commands.go:172-188);
- exit publishes ``{EXIT_SUCCESS|EXIT_FAILED, name}`` plus an
  ``{ERROR, <msg>}`` on failure (reference: commands.go:151-159);
- the child's PID is exported as ``CONTAINERPILOT_<NAME>_PID``
  (reference: commands.go:139-141);
- stdout/stderr are captured line-by-line into structured logging when
  log fields are configured, else passed through raw
  (reference: commands.go:97-103, jobs/config.go:280-283).

TPU-host note: supervised children here are typically per-host JAX
training/serving processes; group signalling matters because JAX
runtimes fork helper processes (e.g. compilation workers, dataloaders)
that must die with the trainer.
"""
from __future__ import annotations

import asyncio
import logging
import os
import signal
import re
import time
from typing import Any, Dict, List, Optional

from ..events import Event, EventBus, EventCode
from ..utils.tasks import spawn
from .args import parse_args

log = logging.getLogger("containerpilot.commands")

_NON_ALNUM = re.compile(r"[^A-Za-z0-9]+")
_MULTI_SCORE = re.compile(r"__+")


def env_name(name: str) -> str:
    """Format a job name for env-var use — CONTAINERPILOT_<NAME>_PID /
    _IP (reference: commands/commands.go:59-81): basename, extension
    stripped, non-alphanumerics collapsed to single underscores,
    uppercased."""
    if not name:
        return name
    base = os.path.basename(name)
    root, ext = os.path.splitext(base)
    if ext:
        base = root
    base = _NON_ALNUM.sub("_", base)
    base = _MULTI_SCORE.sub("_", base)
    return base.upper()


class Command:
    """A runnable child-process specification plus its live handle."""

    def __init__(
        self,
        exec_: str,
        args: Optional[List[str]] = None,
        timeout: Optional[float] = None,
        fields: Optional[Dict[str, Any]] = None,
        name: Optional[str] = None,
    ) -> None:
        self.exec = exec_
        self.args = list(args or [])
        self.name = name or exec_
        self.timeout = timeout
        # fields set => capture output into structured logs; fields
        # None => raw passthrough to the supervisor's own stdio.
        self.fields = fields
        self._proc: Optional[asyncio.subprocess.Process] = None
        self._lock = asyncio.Lock()
        self._reader_tasks: List["asyncio.Task[None]"] = []
        # a term/kill that arrives after run() but before the (fire-and-
        # forget) spawn task has actually started the child is remembered
        # and delivered right after spawn, so teardown can't race it; a
        # term/kill with no spawn in flight is simply a no-op
        self._pending_signal: Optional[signal.Signals] = None
        self._spawn_pending = False

    @classmethod
    def from_config(
        cls,
        raw: Any,
        timeout: Optional[float] = None,
        fields: Optional[Dict[str, Any]] = None,
        name: Optional[str] = None,
    ) -> "Command":
        """Build from a raw config value (string or list of args)."""
        exec_, args = parse_args(raw)
        return cls(exec_, args, timeout=timeout, fields=fields, name=name)

    # -- naming ---------------------------------------------------------

    def env_name(self) -> str:
        """Format the name for the CONTAINERPILOT_<NAME>_PID env var."""
        return env_name(self.name)

    # -- state ----------------------------------------------------------

    @property
    def pid(self) -> Optional[int]:
        return self._proc.pid if self._proc is not None else None

    @property
    def running(self) -> bool:
        return self._proc is not None and self._proc.returncode is None

    # -- execution ------------------------------------------------------

    def run(self, bus: EventBus) -> "asyncio.Task[Optional[int]]":
        """Start the child and return the waiter task.

        The waiter publishes exit events on the bus; callers normally
        fire-and-forget the task (the job state machine reacts to the
        published events, not the task result).
        """
        self._spawn_pending = True
        self._pending_signal = None  # nothing queued from before this run
        return spawn(self._run(bus), name=f"exec:{self.name}")

    async def _run(self, bus: EventBus) -> Optional[int]:
        # Exit events are collected while the run lock is held and
        # published only after it is released: fan-out is synchronous,
        # and a subscriber reacting to an exit event may re-enter this
        # command (restart paths) — publishing under the lock is the
        # CP-LOCKPUB deadlock shape.
        events: List[Event] = []
        try:
            async with self._lock:  # never more than one live instance
                return await self._run_locked(events)
        finally:
            for event in events:
                bus.publish(event)

    async def _run_locked(self, events: List[Event]) -> Optional[int]:
        log.debug("%s.run start", self.name)
        started = time.monotonic()
        capture = self.fields is not None
        # drop the previous run's handle so a term/kill arriving
        # mid-spawn queues instead of hitting the dead process
        self._proc = None
        try:
            self._proc = await asyncio.create_subprocess_exec(
                self.exec,
                *self.args,
                stdout=asyncio.subprocess.PIPE if capture else None,
                stderr=asyncio.subprocess.PIPE if capture else None,
                start_new_session=True,
            )
        except Exception as exc:  # spawn failure (ENOENT, EACCES, ...)
            log.error("unable to start %s: %s", self.name, exc)
            self._spawn_pending = False
            self._pending_signal = None
            events.append(Event(EventCode.EXIT_FAILED, self.name))
            events.append(Event(EventCode.ERROR, str(exc)))
            return None
        proc = self._proc
        self._spawn_pending = False
        if self._pending_signal is not None:
            sig, self._pending_signal = self._pending_signal, None
            log.debug(
                "%s: delivering %s queued before spawn", self.name, sig.name
            )
            try:
                os.killpg(proc.pid, sig)
            except ProcessLookupError:
                pass
        env_key = f"CONTAINERPILOT_{self.env_name()}_PID"
        os.environ[env_key] = str(proc.pid)
        if capture:
            fields = dict(self.fields or {})
            fields["pid"] = proc.pid
            self._reader_tasks = [
                asyncio.ensure_future(self._log_stream(proc.stdout, fields)),
                asyncio.ensure_future(self._log_stream(proc.stderr, fields)),
            ]
        try:
            returncode = await self._wait_with_timeout(proc)
        finally:
            if os.environ.get(env_key) == str(proc.pid):
                os.environ.pop(env_key, None)
            if self._reader_tasks:
                # streams EOF once the child exits; drain them fully
                # so trailing output isn't lost
                try:
                    await asyncio.wait_for(
                        asyncio.gather(*self._reader_tasks), timeout=5.0
                    )
                except asyncio.TimeoutError:
                    for t in self._reader_tasks:
                        if not t.done():
                            t.cancel()
            self._reader_tasks = []
            log.debug(
                "%s.run end (%.1fms)",
                self.name,
                (time.monotonic() - started) * 1e3,
            )
        if returncode == 0:
            log.debug("%s exited without error", self.name)
            events.append(Event(EventCode.EXIT_SUCCESS, self.name))
        else:
            log.error("%s exited with error: code %s", self.name, returncode)
            events.append(Event(EventCode.EXIT_FAILED, self.name))
            events.append(
                Event(EventCode.ERROR, f"{self.name}: exit code {returncode}")
            )
        return returncode

    async def _wait_with_timeout(self, proc: asyncio.subprocess.Process) -> int:
        if self.timeout and self.timeout > 0:
            try:
                return await asyncio.wait_for(
                    asyncio.shield(proc.wait()), self.timeout
                )
            except asyncio.TimeoutError:
                log.warning(
                    "%s timeout after %ss: %r",
                    self.name,
                    self.timeout,
                    [self.exec] + self.args,
                )
                self.kill()
                return await proc.wait()
        return await proc.wait()

    async def _log_stream(
        self, stream: Optional[asyncio.StreamReader], fields: Dict[str, Any]
    ) -> None:
        """Forward a child stream into structured logging, line by line."""
        if stream is None:
            return
        job_log = logging.getLogger(f"containerpilot.job.{self.name}")
        try:
            while True:
                line = await stream.readline()
                if not line:
                    break
                job_log.info(
                    line.decode("utf-8", "replace").rstrip("\n"), extra=fields
                )
        except asyncio.CancelledError:
            pass

    # -- signalling (whole process group) -------------------------------

    def _signal_group(self, sig: signal.Signals) -> None:
        if self._proc is None:
            if self._spawn_pending:
                # spawn task created but child not started yet: queue it
                self._pending_signal = sig
            return
        if self._proc.returncode is not None:
            return
        pid = self._proc.pid
        log.debug("%s: signalling group %d with %s", self.name, pid, sig.name)
        try:
            os.killpg(pid, sig)
        except ProcessLookupError:
            pass

    def kill(self) -> None:
        """SIGKILL the whole process group (reference: commands.go:172-178)."""
        self._signal_group(signal.SIGKILL)

    def term(self) -> None:
        """SIGTERM the whole process group (reference: commands.go:182-188)."""
        self._signal_group(signal.SIGTERM)
