"""Watch actors: poll the catalog for upstream membership changes.

Capability parity with the reference's watches
(reference: watches/watches.go, watches/config.go): every ``interval``
seconds poll the discovery backend for healthy instances of an upstream
service; when membership changes, publish ``{STATUS_CHANGED,
watch.<name>}`` followed by ``{STATUS_HEALTHY|STATUS_UNHEALTHY,
watch.<name>}``. Jobs with ``when: {source: "watch.<name>", each:
"changed"}`` react to these (e.g. re-render an nginx upstream list, or
repoint a JAX serving process at a moved parameter server).

Config names get the ``watch.`` prefix so watch events can't collide
with job events (reference: watches/config.go:45).
"""
from __future__ import annotations

import asyncio
import logging
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

from ..config.decode import coerce_number
from ..config.services import validate_name
from ..discovery import Backend
from ..events import (
    Event,
    EventBus,
    EventCode,
    EventHandler,
    QUIT_BY_TEST,
    cancel_timer,
    event_timer,
)
from ..utils.tasks import spawn

log = logging.getLogger("containerpilot.watches")


class WatchConfigError(ValueError):
    pass


# catalog polls run on a SMALL dedicated pool, not the default
# executor: HTTP backends keep one persistent agent connection per
# thread (discovery/consul.py), so concentrating every poll onto a
# few long-lived threads means the poll reuses a warm connection each
# interval instead of spreading dials across whatever transient
# default-executor thread happens to be free. Eight workers bounds
# head-of-line blocking when a backend call blackholes for its full
# timeout (every watch actor AND every gateway in the process shares
# this pool) while still keeping the per-thread connections warm.
_POLL_EXECUTOR = ThreadPoolExecutor(
    max_workers=8, thread_name_prefix="catalog-poll"
)


async def poll_upstream(
    backend: Backend, service_name: str, tag: str = "", dc: str = ""
) -> tuple:
    """One catalog poll for healthy instances of ``service_name``,
    run OFF the event loop (catalog polls are blocking HTTP/file
    I/O — on the single asyncio loop a slow catalog would stall every
    actor's timers). Returns the backend's (did_change, is_healthy).

    Shared by the supervisor's Watch actors and the fleet gateway's
    replica-discovery loop so both sides poll with one discipline —
    and with one persistent catalog connection per poll thread.
    """
    return await asyncio.get_event_loop().run_in_executor(
        _POLL_EXECUTOR,
        lambda: backend.check_for_upstream_changes(service_name, tag, dc),
    )


class WatchConfig:
    """One validated watch definition (reference: watches/config.go)."""

    def __init__(self, raw: Dict[str, Any]) -> None:
        if not isinstance(raw, dict):
            raise WatchConfigError(f"watch configuration must be a mapping: {raw!r}")
        unknown = set(raw) - {"name", "interval", "tag", "dc"}
        if unknown:
            raise WatchConfigError(
                f"watch[{raw.get('name', '?')}]: unknown keys {sorted(unknown)}"
            )
        self.service_name: str = raw.get("name", "")
        # weakly-typed numerics, like the reference's mapstructure
        # decoding (reference: config/decode/decode.go:14-18)
        self.poll = coerce_number(raw.get("interval", 0))
        self.tag: str = raw.get("tag", "")
        self.dc: str = raw.get("dc", "")
        self.name = ""
        self.backend: Optional[Backend] = None

    def validate(self, disc: Optional[Backend]) -> "WatchConfig":
        try:
            validate_name(self.service_name)
        except ValueError as exc:
            raise WatchConfigError(str(exc)) from None
        self.name = f"watch.{self.service_name}"
        if not isinstance(self.poll, (int, float)) or self.poll < 1:
            raise WatchConfigError(
                f"watch[{self.service_name}].interval must be > 0"
            )
        self.backend = disc
        return self


def new_watch_configs(
    raw: Optional[List[Dict[str, Any]]], disc: Optional[Backend]
) -> List[WatchConfig]:
    if raw is None:
        return []
    if not isinstance(raw, list):
        raise WatchConfigError("watch configuration must be a list")
    return [WatchConfig(item).validate(disc) for item in raw]


class Watch(EventHandler):
    """One watch actor (reference: watches/watches.go:13-117)."""

    def __init__(self, cfg: WatchConfig) -> None:
        super().__init__()
        self.name = cfg.name
        self.service_name = cfg.service_name
        self.tag = cfg.tag
        self.dc = cfg.dc
        self.poll = float(cfg.poll)
        self.backend = cfg.backend
        self._timer: Optional["asyncio.Task[None]"] = None
        self._task: Optional["asyncio.Task[None]"] = None

    def run(self, bus: EventBus) -> "asyncio.Task[None]":
        """Register, start the poll ticker, and run the event loop
        (reference: watches/watches.go:66-103). Unlike jobs, watches
        are registered-only (they publish but don't need global
        subscription — their only input is the private poll timer)."""
        self.register(bus)
        timer_source = f"{self.name}.poll"
        # immediate=True: the first poll happens right away rather than
        # one full interval after startup (improvement over the
        # reference, whose dependents see no upstream state until the
        # first tick)
        self._timer = event_timer(
            self.receive, self.poll, timer_source, immediate=True
        )
        self._task = spawn(
            self._loop(timer_source), name=f"watch:{self.name}"
        )
        return self._task

    def stop(self) -> None:
        """Stop the poll loop (the app cancels watches on teardown)."""
        if self._task is not None and not self._task.done():
            self._task.cancel()

    async def _loop(self, timer_source: str) -> None:
        try:
            while True:
                event = await self.next_event()
                if event == QUIT_BY_TEST:
                    return
                if event == Event(EventCode.TIMER_EXPIRED, timer_source):
                    assert self.backend is not None
                    try:
                        did_change, is_healthy = await poll_upstream(
                            self.backend, self.service_name,
                            self.tag, self.dc,
                        )
                    except Exception as exc:  # a flaky catalog isn't fatal
                        log.warning("%s: poll failed: %s", self.name, exc)
                        continue
                    if did_change:
                        self.publish(Event(EventCode.STATUS_CHANGED, self.name))
                        if is_healthy:
                            self.publish(Event(EventCode.STATUS_HEALTHY, self.name))
                        else:
                            self.publish(Event(EventCode.STATUS_UNHEALTHY, self.name))
        except asyncio.CancelledError:
            pass
        finally:
            cancel_timer(self._timer)
            self.unregister()

    def __repr__(self) -> str:  # pragma: no cover - trivial
        return f"watches.Watch[{self.name}]"


def from_configs(configs: List[WatchConfig]) -> List[Watch]:
    return [Watch(cfg) for cfg in configs]
