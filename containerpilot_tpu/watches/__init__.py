"""Watches: upstream-change pollers (reference: watches/ package)."""
from .watches import Watch, WatchConfig, WatchConfigError, from_configs, new_watch_configs

__all__ = [
    "Watch",
    "WatchConfig",
    "WatchConfigError",
    "from_configs",
    "new_watch_configs",
]
