"""Watches: upstream-change pollers (reference: watches/ package)."""
from .watches import (
    Watch,
    WatchConfig,
    WatchConfigError,
    from_configs,
    new_watch_configs,
    poll_upstream,
)

__all__ = [
    "Watch",
    "WatchConfig",
    "WatchConfigError",
    "from_configs",
    "new_watch_configs",
    "poll_upstream",
]
