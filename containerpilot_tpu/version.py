"""Version metadata (reference: version/version.go:5-9, injected by LDFLAGS;
here set at release time and optionally overridden by the build)."""

VERSION = "0.7.0"
GIT_HASH = "dev"
