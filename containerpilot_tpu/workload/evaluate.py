"""Standalone evaluation: checkpoint + token shards -> loss/perplexity.

Completes the train/eval/serve triad: the trainer's in-loop eval
(``--eval-every``) tracks progress DURING a run; this CLI scores any
checkpoint after the fact — the raw params, the EMA shadow
(``--use-ema``), or a LoRA-adapted base (``--lora-dir``) — over a
dataset's held-out windows (or the whole stream with
``--eval-holdout 0 --max-batches N``). One JSON line on stdout so a
supervisor job or script can consume it:

    python -m containerpilot_tpu.workload.evaluate \
        --checkpoint-dir /ckpt --data-dir /data --eval-holdout 64 \
        --d-model 1024 ...   (model flags must match the checkpoint)

``--eval-holdout`` is REQUIRED and must match the trainer's value: a
larger value here would silently score trained-on windows as
"held out" (the checkpoint does not record the split).

Runs on whatever devices are visible (the same auto (data, model)
mesh the trainer uses); the loss computation is shared with the
trainer's in-loop eval (workload/modelcfg.py), so a number here is
comparable to training logs by construction.
"""
from __future__ import annotations

import argparse
import json

import jax
import jax.numpy as jnp

from .modelcfg import average_eval_loss, derive_d_ff, restore_merged_params


def main() -> int:
    from .modelcfg import enable_compile_cache

    enable_compile_cache()
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--checkpoint-dir", required=True)
    parser.add_argument("--data-dir", required=True)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=256)
    parser.add_argument("--d-model", type=int, default=256)
    parser.add_argument("--n-layers", type=int, default=2)
    parser.add_argument("--n-heads", type=int, default=4)
    parser.add_argument("--n-kv-heads", type=int, default=0)
    parser.add_argument("--vocab", type=int, default=32_000)
    parser.add_argument("--window", type=int, default=0)
    parser.add_argument("--moe-experts", type=int, default=0)
    parser.add_argument("--loss-chunk", type=int, default=0)
    parser.add_argument(
        "--eval-holdout", type=int, required=True,
        help="score the dataset's LAST N windows; MUST equal the "
        "trainer's --eval-holdout or trained-on windows leak into "
        "the score (0 = score the training stream from its head)",
    )
    parser.add_argument(
        "--max-batches", type=int, default=0,
        help="cap scored batches (0 = the whole selected split)",
    )
    parser.add_argument(
        "--use-ema", action="store_true",
        help="score the checkpoint's EMA shadow weights (falls back "
        "to raw params WITH a warning and \"ema\": false in the "
        "report when the checkpoint has no shadow)",
    )
    parser.add_argument("--lora-dir", default="")
    parser.add_argument("--lora-rank", type=int, default=0)
    args = parser.parse_args()

    from ..models.transformer import TransformerConfig
    from ..parallel import make_mesh
    from .data import TokenShardDataset

    cfg = TransformerConfig(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads,
        n_layers=args.n_layers,
        d_ff=derive_d_ff(args.d_model),
        max_seq_len=args.seq_len,
        moe_experts=args.moe_experts,
        window=args.window,
        loss_chunk=args.loss_chunk,
    )
    restored = restore_merged_params(
        cfg, make_mesh(), args.checkpoint_dir, use_ema=args.use_ema,
        lora_dir=args.lora_dir, lora_rank=args.lora_rank,
    )
    if restored is None:
        raise SystemExit(f"no checkpoint in {args.checkpoint_dir}")
    params, step = restored
    # reported honestly FROM the restore: .ema says whether the shadow
    # weights are what actually came back (the restore falls back to
    # raw params, with a logged warning, when the checkpoint has none)
    ema_scored = restored.ema

    dataset = TokenShardDataset(
        args.data_dir, args.seq_len, args.batch,
        vocab_size=cfg.vocab_size,
        holdout_windows=args.eval_holdout,
    )
    if args.eval_holdout > 0:
        n = dataset.n_eval_batches
        batch_at = dataset.eval_batch
    else:
        n = dataset.n_windows // args.batch
        batch_at = dataset.batch_at
    if args.max_batches > 0:
        n = min(n, args.max_batches)
    if n < 1:
        raise SystemExit("dataset yields no full eval batch at this "
                         "batch/seq-len; shrink --batch or --seq-len")

    loss = average_eval_loss(params, cfg, n, batch_at)
    print(json.dumps({
        "checkpoint_step": int(step),
        "eval_loss": round(loss, 6),
        "perplexity": round(float(jnp.exp(loss)), 4),
        "batches": n,
        "tokens": n * args.batch * args.seq_len,
        "split": "holdout" if args.eval_holdout > 0 else "head",
        "ema": ema_scored,
        "lora": bool(args.lora_dir),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
