"""Non-batched decode strategies for the inference server.

Each runner executes on the inference executor thread and returns the
generated token rows; the server's /v1/generate dispatch picks one
based on the request (beam / cp / chunked prefill — the continuous
batcher and prefix cache live in their own modules). Speculative
decoding no longer lives here: it rides the slot engine as a step
program (models/stepprog.py + models/speculative.py's
SpeculativeStepProgram), inheriting queueing/cancel/tracing from the
one engine driver.
"""
from __future__ import annotations

from typing import Any, List

import jax
import jax.numpy as jnp


def run_beam(
    srv: Any, tokens: List[List[int]], max_new_requested: int,
    beam_width: int, eos_id: int, length_penalty: float,
) -> List[List[int]]:
    from ..models.beam import beam_search

    # beam search is NOT prefix-consistent: the best 16-token beam's
    # first 6 tokens are not the best 6-token continuation, so the
    # compiled horizon is the REQUESTED length, not the bucketed one
    # (beams are explicit requests; the compile churn is theirs)
    out, _score = beam_search(
        srv.params, jnp.asarray(tokens, jnp.int32),
        srv.cfg, max_new_tokens=max_new_requested,
        max_len=srv.max_len, beam_width=beam_width,
        eos_id=eos_id, length_penalty=length_penalty,
        prefill_chunk=srv.prefill_chunk,
    )
    srv.batch_stats["calls"] += 1
    srv.batch_stats["rows"] += 1
    return [jax.device_get(out).tolist()]


def run_cp(srv: Any, tokens: List[List[int]], p: dict) -> List[List[int]]:
    """Context-parallel prefill for one long row: ring attention over
    the server's seq mesh, cache gathered once, normal decode
    (parallel.cp_generate) with the server's key convention."""
    from ..parallel import cp_generate

    srv.batch_stats["calls"] += 1
    srv.batch_stats["rows"] += 1
    out = cp_generate(
        srv.params, jnp.asarray(tokens, jnp.int32), srv.cfg,
        srv.cp_mesh, p["max_new"], srv.max_len,
        temperature=p["temperature"],
        rng=jnp.stack(
            [jax.random.fold_in(jax.random.PRNGKey(p["seed"]), 0)]
        ),
        top_k=p["top_k"], top_p=p["top_p"], eos_id=p["eos_id"],
        min_new_tokens=p["min_new"], presence_penalty=p["presence"],
        frequency_penalty=p["frequency"], logit_bias=p["logit_bias"],
    )
    return jax.device_get(out).tolist()


def run_chunked(
    srv: Any, tokens: List[List[int]], prompt_len: int, max_new: int,
    temperature: float, top_k: int, top_p: float, eos_id: int, seed: int,
    min_new: int = 0,
    presence: float = 0.0,
    frequency: float = 0.0,
    logit_bias: Any = None,
) -> List[List[int]]:
    """Long single-row prompt: stream the prefill in chunks (peak
    prefill activations O(chunk) instead of O(prompt))."""
    from ..models.decode import chunked_prefill, generate_from_cache

    logits, cache = chunked_prefill(
        srv.params, jnp.asarray(tokens, jnp.int32),
        srv.cfg, srv.max_len, srv.prefill_chunk,
    )
    srv.batch_stats["calls"] += 1
    srv.batch_stats["rows"] += 1
    out = generate_from_cache(
        srv.params, cache, logits, srv.cfg,
        max_new_tokens=max_new, temperature=temperature,
        rng=jnp.stack([jax.random.fold_in(jax.random.PRNGKey(seed), 0)]),
        top_k=top_k, top_p=top_p, eos_id=eos_id,
        pos=prompt_len, min_new_tokens=min_new,
        presence_penalty=presence, frequency_penalty=frequency,
        logit_bias=logit_bias,
    )
    return jax.device_get(out).tolist()
