"""The supervised workload: a runnable JAX training process designed to
live under the supervisor (health-checked via a progress file, metrics
posted to the control socket)."""
