"""Prefix KV reuse for the inference server.

Completed prompts' KV caches, keyed by their token tuple, LRU-bounded.
A new single-row request reuses the longest common prefix and only
prefills the (bucketed) suffix — the chat/agent regime where every
turn re-sends a long shared history.

Thread safety: ``match_len`` runs on the asyncio event-loop thread
(the /v1/generate dispatch condition) while the store/evict side runs
on the inference executor thread, so every OrderedDict access holds
``_lock`` (round-2 review: a concurrent request could previously hit
"OrderedDict mutated during iteration" and surface as a 500).

With a **spill tier** attached (``kvtier.HostSpillTier``), LRU
eviction moves the entry's KV to byte-budgeted host RAM instead of
dropping it, and a later match readmits it through the SAME
``get``/``reuse_admission`` path — the slot engines and the rewind+
extend protocol never see the difference, only the stats do
(``spilled``/``readmitted``/``spill_bytes``, zeroed when the tier is
disabled so the ``/v1/model`` schema stays stable either way).
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, List, Optional, Tuple

# import-light by design (no jax): just the fingerprint/codec helpers
from ..kvtier import digest as kvdigest

#: shorter matches aren't worth a device call. Tied to the digest's
#: FP_TOKENS BY CONSTRUCTION: the spill tier indexes keys by their
#: first-FP_TOKENS fingerprint, and that bucket lookup finds every
#: >= MIN_REUSE match only while FP_TOKENS <= MIN_REUSE — tune the
#: floor in kvtier/digest.py, not by breaking the tie here
MIN_REUSE = kvdigest.FP_TOKENS
BUCKET = 16      # suffix lengths compile in these steps


class PrefixCache:
    def __init__(self, entries: int, spill: Optional[Any] = None) -> None:
        self.entries = entries
        #: optional kvtier.HostSpillTier catching LRU evictions
        self.spill = spill
        self._cache: "OrderedDict[Tuple[int, ...], Any]" = OrderedDict()
        self._lock = threading.Lock()
        self.stats = {
            "hits": 0, "misses": 0, "tokens_reused": 0,
            # spill-tier accounting; stays zeroed when no tier is
            # attached so the /v1/model schema is identical either way
            "spilled": 0, "readmitted": 0, "spill_bytes": 0,
        }
        #: seconds the LAST admission spent readmitting from spill —
        #: reset/read by the slot engines around reuse_admission to
        #: stamp the trace's ``kv`` stage (single inference thread per
        #: engine, so a plain float is race-free in practice)
        self.readmit_seconds = 0.0
        #: bumped on any contents change; versions the published
        #: digest so readers can tell fresh from stale
        self.version = 0
        self._digest_memo: Tuple[int, str] = (-1, "")

    def __len__(self) -> int:
        with self._lock:
            return len(self._cache)

    def match_len(self, row: List[int]) -> int:
        """Longest common prefix between ``row`` and any cached prompt
        (host-side scan; cheap relative to a device call)."""
        return self.best_match(row)[0]

    def best_match(
        self, row: List[int]
    ) -> Tuple[int, Optional[Tuple[int, ...]]]:
        """Longest common prefix over device-resident AND spilled
        keys. Device keys scan first, so on equal match length the
        cheaper (no-readmit) base wins. The spill tier is consulted
        by fingerprint bucket, not scanned: a usable (>= MIN_REUSE)
        match shares the row's first-MIN_REUSE ids, so only
        same-fingerprint keys can qualify — the scan stays O(device
        LRU) however large the host budget grows."""
        with self._lock:
            keys: List[Tuple[int, ...]] = list(self._cache)
        if self.spill is not None:
            keys.extend(
                self.spill.candidates(
                    kvdigest.prefix_fingerprint(row)
                )
            )
        best_len, best_key = 0, None
        for stored in keys:
            n = min(len(stored), len(row))
            i = 0
            while i < n and stored[i] == row[i]:
                i += 1
            if i > best_len:
                best_len, best_key = i, stored
        return best_len, best_key

    def get(self, key: Tuple[int, ...]) -> Optional[Any]:
        """Fetch a stored cache and mark it most-recently-used,
        readmitting from the spill tier when the device LRU evicted
        it. Returns None if it is gone from both tiers (evicted
        between match and fetch)."""
        with self._lock:
            cache = self._cache.get(key)
            if cache is not None:
                self._cache.move_to_end(key)
                return cache
        if self.spill is None:
            return None
        t0 = time.monotonic()
        cache = self.spill.take(key)
        if cache is None:
            return None
        self.stats["readmitted"] += 1
        self.readmit_seconds += time.monotonic() - t0
        # back into the device LRU as MRU (which may spill another
        # entry in turn); the caller sees a plain device-tier hit
        self.store(key, cache)
        return cache

    def device_entry(self, key: Tuple[int, ...]) -> Optional[Any]:
        """The device-tier entry for ``key``, untouched: no readmit,
        no MRU bump — the handoff EXPORT path's read (a fresh
        prefill's entry lives here, and serializing it for a peer
        must not disturb LRU order or the spill tier)."""
        with self._lock:
            return self._cache.get(key)

    def adopt_host(self, key: Tuple[int, ...], host_tree: Any) -> int:
        """Inject a handed-off HOST-side entry (kvtier/handoff.py)
        into the spill tier and republish the digest. Returns the
        bytes adopted, 0 without a spill tier or when the budget
        refuses it. The entry readmits through the SAME
        ``get``/``reuse_admission`` path a locally-spilled one takes
        — which is what makes handoff byte-parity hold by
        construction."""
        if self.spill is None:
            return 0
        adopted = self.spill.put_host(key, host_tree)
        if adopted:
            with self._lock:
                self.version += 1
            self.stats["spill_bytes"] = self.spill.bytes_used
        return adopted

    def store(self, key: Tuple[int, ...], cache: Any) -> None:
        evicted: List[Tuple[Tuple[int, ...], Any]] = []
        with self._lock:
            self._cache[key] = cache
            self._cache.move_to_end(key)
            while len(self._cache) > self.entries:
                evicted.append(self._cache.popitem(last=False))
            self.version += 1
        if self.spill is None:
            return
        for k, c in evicted:
            if len(k) < MIN_REUSE:
                # below the reuse floor it can never match again —
                # not worth the host RAM or the transfer
                continue
            # device->host happens inside put(), outside our lock
            if self.spill.put(k, c):
                self.stats["spilled"] += 1
        if evicted:
            self.version += 1
        self.stats["spill_bytes"] = self.spill.bytes_used

    def export_keys(self) -> List[Tuple[int, ...]]:
        """Every migratable prompt key this cache holds, device tier
        first in MRU order, then spilled keys — the drain-migration
        enumeration (kvtier.plan_migration's input). Read-only: no
        MRU bump, no readmit, nothing below the reuse floor (it can
        never match again, so it is not worth moving)."""
        with self._lock:
            keys = list(reversed(self._cache))
        if self.spill is not None:
            seen = set(keys)
            keys.extend(
                k for k in self.spill.keys() if k not in seen
            )
        return [k for k in keys if len(k) >= MIN_REUSE]

    def digest(self, max_bytes: Optional[int] = None) -> str:
        """Versioned fingerprint digest of every reusable prefix this
        cache holds (device + spill tiers), for gateway routing —
        memoized per version, so steady state costs a tuple compare."""
        version = self.version
        memo_version, memo = self._digest_memo
        if memo_version == version:
            return memo
        with self._lock:
            keys = list(self._cache)
        if self.spill is not None:
            keys.extend(self.spill.keys())
        fps = []
        for key in keys:
            fp = kvdigest.prefix_fingerprint(key)
            if fp is not None:
                fps.append(fp)
        encoded = kvdigest.encode_fingerprints(
            version, fps, max_bytes or kvdigest.DIGEST_MAX_BYTES
        )
        self._digest_memo = (version, encoded)
        return encoded


def plan_reuse(pc: "PrefixCache", row: List[int]):
    """The ONE reuse plan both the standalone prefix path and the
    slot engine's admission apply: longest cached match, suffix
    bucketed (a little of the matched prefix re-prefills so jit
    compiles one extend program per BUCKET, not per suffix length).
    Returns (reuse_len, base_cache_or_None); counts a miss when no
    usable base exists."""
    plen = len(row)
    best_len, best_key = pc.best_match(row)
    reuse = 0
    if best_len >= MIN_REUSE:
        suffix = plen - best_len
        bucket = max(1, -(-suffix // BUCKET) * BUCKET) if suffix > 0 else 1
        reuse = plen - min(bucket, plen)
    base = pc.get(best_key) if reuse > 0 and best_key is not None else None
    return (reuse, base) if base is not None else (0, None)


def reuse_admission(pc: "PrefixCache", row_tokens: List[int], cfg,
                    params, chunk_len: int = 0):
    """The ONE admission-side reuse protocol both slot engines apply
    (workload/serve_slots.py and the pod's serve_dist mirror): plan
    the reuse, rewind the cached base (same arrays, earlier pos),
    extend the bucketed suffix — in bounded pieces when ``chunk_len``
    says the configured activation bound applies — and count the
    hit/miss stats. Returns (logits, cache) on a hit, None on a miss.
    Callers store the completed prompt's cache afterwards (with any
    placement transform of their own, e.g. the pod's replicated
    repin)."""
    import jax.numpy as jnp

    from ..models.decode import _jitted_extend, extend_pieces

    reuse, base = plan_reuse(pc, row_tokens)
    if base is None:
        pc.stats["misses"] += 1
        return None
    cache = {**base, "pos": jnp.asarray(reuse, jnp.int32)}
    suffix = jnp.asarray([row_tokens[reuse:]], jnp.int32)
    if chunk_len > 0 and suffix.shape[1] > chunk_len:
        # a huge cached-hit suffix honors the SAME O(chunk)
        # activation bound as a cold prompt
        logits, cache = extend_pieces(
            params, cache, suffix, cfg, chunk_len
        )
    else:
        logits, cache = _jitted_extend(cfg)(params, cache, suffix)
    pc.stats["hits"] += 1
    pc.stats["tokens_reused"] += reuse
    return logits, cache


def generate_with_prefix(
    srv: Any, row: List[int], max_new: int, temperature: float,
    top_k: int, top_p: float, eos_id: int, seed: int,
    min_new: int = 0,
    presence: float = 0.0,
    frequency: float = 0.0,
    logit_bias: Any = None,
) -> List[List[int]]:
    """Single-row generation reusing the longest cached prompt prefix.

    The recomputed suffix is bucketed (a little of the matched prefix
    is re-prefilled) so jit compiles one extend program per bucket, not
    per suffix length. Stale cache rows beyond pos are masked or
    overwritten by design (models/decode.py), which is what makes the
    rewind sound — and why --window (ring cache) refuses this feature.
    Runs on the inference executor thread.
    """
    import jax
    import jax.numpy as jnp

    from ..models.decode import (
        _jitted_prefill,
        generate_from_cache,
    )

    pc: PrefixCache = srv.prefix_cache
    key_row = tuple(row)
    plen = len(row)
    # the ONE admission-side reuse protocol (shared with both slot
    # engines): rewind + bucketed extend, in bounded pieces when
    # prefill_chunk applies — the standalone prefix path honors the
    # same O(chunk) activation bound as the slot-engine paths
    hit = reuse_admission(
        pc, row, srv.cfg, srv.params, chunk_len=srv.prefill_chunk
    )
    if hit is not None:
        logits, cache = hit
    elif srv.prefill_chunk and plen > srv.prefill_chunk:
        # cold long prompt: seed the prefix cache via the chunked
        # stream so the configured prefill HBM bound still holds
        # (the miss was already counted by reuse_admission)
        from ..models.decode import chunked_prefill

        logits, cache = chunked_prefill(
            srv.params, jnp.asarray([row], jnp.int32), srv.cfg,
            srv.max_len, srv.prefill_chunk,
        )
    else:
        logits, cache = _jitted_prefill(srv.cfg, srv.max_len)(
            srv.params, jnp.asarray([row], jnp.int32)
        )
    # store the completed prompt's cache for future turns
    pc.store(key_row, cache)
    # the prefix path is a device call too — keep /v1/model's batching
    # telemetry honest when this path serves the traffic
    srv.batch_stats["calls"] += 1
    srv.batch_stats["rows"] += 1
    out = generate_from_cache(
        srv.params, cache, logits, srv.cfg,
        max_new_tokens=max_new, temperature=temperature,
        rng=jnp.stack([jax.random.fold_in(jax.random.PRNGKey(seed), 0)]),
        top_k=top_k, top_p=top_p, eos_id=eos_id,
        pos=plen, min_new_tokens=min_new,
        presence_penalty=presence, frequency_penalty=frequency,
        logit_bias=logit_bias,
    )
    return jax.device_get(out).tolist()
