"""Model-FLOPs accounting shared by bench.py and the trainer.

PaLM-style: a training step costs ~6 FLOPs per parameter per token
(fwd matmul + 2x bwd) plus the attention score/value matmuls, which
the 6N term misses because they scale with sequence length, not
parameter count: 12 * L * d_model * span per token (fwd+bwd), where
``span`` is the AVERAGE number of keys a query actually attends to —
(seq+1)/2 for full causal (the halving the flash kernels realize by
skipping the dead half), ~window for sliding-window. MFU = achieved
FLOP/s over the chip's published bf16 peak — the honest utilization
number, not a hardware counter; billing the skipped causal half would
flatter MFU ~2x on exactly the configs where the kernels skip it.
"""
from __future__ import annotations

from typing import Any

# bf16 peak FLOP/s by TPU generation (public spec sheets), matched by
# substring of jax Device.device_kind
PEAK_BF16 = [
    ("v6", 918e12),   # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),   # v5e / "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
]


def peak_flops(device_kind: str) -> float:
    kind = device_kind.lower()
    for key, peak in PEAK_BF16:
        if key in kind:
            return peak
    return 197e12  # assume v5e-class if unrecognized


def count_params(params: Any) -> int:
    import jax

    return sum(p.size for p in jax.tree_util.tree_leaves(params))


def train_flops_per_token(
    cfg: Any, n_params: int, seq: int, n_frozen: int = 0
) -> float:
    """FLOPs one training step spends per token.

    - sliding window: the attention term scales with
      min(seq, window) — the kernels skip out-of-window blocks;
    - MoE: only 1 of E experts executes per token (top-1 switch
      routing), so the inactive experts' parameters don't bill;
    - ``n_frozen`` (LoRA base): frozen params do forward + grad
      propagation but no weight-gradient matmul — 4 FLOPs/param
      instead of 6. Without these corrections the MFU gauge reads a
      fictitious number for exactly those configs.

    The attention span is the exact mean over positions of
    min(pos+1, window): sum_{p<s} min(p+1, w) / s = w - w*(w-1)/(2s)
    with w = min(seq, window or seq). Full causal (w == s) reduces to
    (s+1)/2 — the causal halving the kernels actually realize.
    """
    w = float(seq if cfg.window <= 0 else min(seq, cfg.window))
    attn_span = w - w * (w - 1.0) / (2.0 * seq)
    active = float(n_params)
    if getattr(cfg, "moe_experts", 0) > 1:
        expert_total = (
            2.0 * cfg.n_layers * cfg.moe_experts * cfg.d_model * cfg.d_ff
        )
        active -= expert_total * (1.0 - 1.0 / cfg.moe_experts)
    frozen = min(float(n_frozen), active)
    return (
        6.0 * (active - frozen)
        + 4.0 * frozen
        + 12.0 * cfg.n_layers * cfg.d_model * attn_span
    )


def train_step_flops(cfg: Any, n_params: int, batch: int,
                     seq: int) -> float:
    return train_flops_per_token(cfg, n_params, seq) * batch * seq
