"""A supervised inference server: the serving half of the demo workload.

One server process per TPU host, supervised by containerpilot-tpu:
health-checked over ``GET /health`` (so a wedged server goes
catalog-critical and restarts), advertised in the catalog by its job's
``port``, optionally loading weights from a training checkpoint dir.

API (token-level; tokenization is the caller's concern):

    POST /v1/generate {"tokens": [[1,2,3]], "max_new_tokens": 16,
                       "temperature": 0.0}
        -> {"tokens": [[...generated ids...]]}
    POST /v1/score    {"tokens": [[1,2,3,4]]}
        -> {"logprobs": [[lp(t1|t0), lp(t2|t0..1), ...]],
            "sums": [total lp per row]}   (teacher-forced scoring)
    GET /health   -> 200 once the model is compiled and warm
    GET /v1/model -> config summary

Generation runs on a worker thread so the asyncio loop (health checks
included) never blocks on TPU execution.
"""
from __future__ import annotations

import argparse
import asyncio
import json
import logging
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..models.decode import generate
from ..models.transformer import TransformerConfig, init_params
from ..utils.http import HTTPServer, Request, Response

log = logging.getLogger("containerpilot.serve")


def _parse_token_rows(body: Dict[str, Any], vocab: int, min_row_len: int):
    """Shared request validation for token-matrix endpoints: a
    non-empty list of equal-length integer rows within the vocab.
    Raises ValueError with a client-facing message."""
    tokens = body["tokens"]
    if not isinstance(tokens, list) or not tokens or not all(
        isinstance(row, list) and len(row) >= min_row_len for row in tokens
    ):
        raise ValueError(
            f"'tokens' must be a non-empty list of rows with "
            f">= {min_row_len} ids"
        )
    row_len = len(tokens[0])
    if any(len(row) != row_len for row in tokens):
        raise ValueError("all rows must share a length (pad first)")
    if any(
        not isinstance(t, int) or isinstance(t, bool) or t < 0 or t >= vocab
        for row in tokens
        for t in row
    ):
        raise ValueError(f"token ids must be integers in [0, {vocab})")
    return tokens, row_len


@dataclass
class _GenJob:
    """One /v1/generate request waiting in the batcher queue."""

    rows: List[List[int]]
    prompt_len: int
    max_new: int  # bucketed compiled length
    temperature: float
    top_k: int
    top_p: float
    eos_id: int
    seed: int
    future: "asyncio.Future[List[List[int]]]" = field(repr=False, default=None)


class InferenceServer:
    def __init__(
        self,
        cfg: TransformerConfig,
        params: Any,
        host: str,
        port: int,
        max_len: int,
        draft_layers: int = 0,
        speculate: int = 4,
        max_batch_rows: int = 16,
        prefix_cache_entries: int = 0,
        prefill_chunk: int = 0,
        text: bool = False,
    ) -> None:
        self.cfg = cfg
        self.params = params
        self.host = host
        self.port = port
        self.max_len = max_len
        self.ready = False
        # self-speculative decoding: a layer-prefix draft accelerates
        # greedy single-sequence generation, output unchanged
        self.draft_params = self.draft_cfg = None
        self.speculate = speculate
        if draft_layers > 0 and speculate < 1:
            # fail at startup, not as request-time 500s
            raise ValueError("speculate must be >= 1")
        if draft_layers > 0 and cfg.window > 0:
            raise ValueError(
                "--draft-layers does not compose with --window "
                "(speculative rollback cannot undo ring-cache writes)"
            )
        if prefix_cache_entries > 0 and cfg.window > 0:
            raise ValueError(
                "--prefix-cache does not compose with --window (a "
                "ring cache's stale rows are live window context, so "
                "a shorter-prefix rewind cannot reuse them)"
            )
        # prefix KV reuse: completed prompts' caches, keyed by their
        # token tuple, LRU-bounded. A new single-row request reuses
        # the longest common prefix and only prefills the (bucketed)
        # suffix — the chat/agent regime where every turn re-sends a
        # long shared history.
        from collections import OrderedDict

        self._prefix_cache: Optional[OrderedDict] = (
            OrderedDict() if prefix_cache_entries > 0 else None
        )
        self._prefix_cache_entries = prefix_cache_entries
        self.prefix_stats = {"hits": 0, "misses": 0, "tokens_reused": 0}
        # prompts longer than this stream through decode_chunk pieces
        # (peak prefill activations O(chunk) instead of O(prompt))
        self.prefill_chunk = prefill_chunk
        if draft_layers > 0:
            from ..models.speculative import layer_prefix_draft

            self.draft_params, self.draft_cfg = layer_prefix_draft(
                params, cfg, draft_layers
            )
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="inference"
        )
        self._server = HTTPServer()
        self._server.route("GET", "/health", self._health)
        self._server.route("GET", "/v1/model", self._model_info)
        self._server.route("POST", "/v1/generate", self._generate)
        self._server.route("POST", "/v1/score", self._score)
        # text surface: byte-level tokenizer, zero external assets
        self.tokenizer = None
        if text:
            from .text import ByteTokenizer

            self.tokenizer = ByteTokenizer(cfg.vocab_size)
            self._server.route(
                "POST", "/v1/completions", self._completions
            )
        self._score_fn = None  # jitted lazily; jit caches per length
        # continuous batching: requests queue here and the batcher
        # coalesces whatever accumulated while the device was busy
        self.max_batch_rows = max_batch_rows
        self._gen_queue: "asyncio.Queue[_GenJob]" = asyncio.Queue()
        self._batcher: Optional["asyncio.Task[None]"] = None
        self.batch_stats = {"calls": 0, "rows": 0}  # device-call count

    # -- handlers -------------------------------------------------------

    async def _health(self, _req: Request) -> Response:
        if not self.ready:
            return Response(503, b"warming up\n")
        return Response(200, b"ok\n")

    async def _model_info(self, _req: Request) -> Response:
        body = json.dumps(
            {
                "vocab_size": self.cfg.vocab_size,
                "d_model": self.cfg.d_model,
                "n_heads": self.cfg.n_heads,
                "n_kv_heads": self.cfg.kv_heads,
                "n_layers": self.cfg.n_layers,
                "max_len": self.max_len,
                "speculative": (
                    {
                        "draft_layers": self.draft_cfg.n_layers,
                        "speculate": self.speculate,
                    }
                    if self.draft_cfg is not None
                    else None
                ),
                "batching": {
                    "max_batch_rows": self.max_batch_rows,
                    "device_calls": self.batch_stats["calls"],
                    "rows": self.batch_stats["rows"],
                },
                "prefix_cache": (
                    {
                        "entries": self._prefix_cache_entries,
                        **self.prefix_stats,
                    }
                    if self._prefix_cache is not None
                    else None
                ),
            }
        ).encode()
        return Response(200, body, content_type="application/json")

    async def _generate(self, req: Request) -> Response:
        try:
            body = json.loads(req.body.decode() or "{}")
            tokens, prompt_len = _parse_token_rows(
                body, self.cfg.vocab_size, min_row_len=1
            )
            max_new_requested = int(body.get("max_new_tokens", 16))
            temperature = float(body.get("temperature", 0.0))
            seed = int(body.get("seed", 0))
            top_k = int(body.get("top_k", 0))
            top_p = float(body.get("top_p", 0.0))
            eos_id = int(body.get("eos_id", -1))
            beam_width = int(body.get("beam_width", 0))
            length_penalty = float(body.get("length_penalty", 0.0))
            if beam_width:
                from ..models.beam import validate_beam_args

                if temperature > 0.0 or top_k or top_p:
                    raise ValueError(
                        "beam search is deterministic; drop "
                        "temperature/top_k/top_p"
                    )
                validate_beam_args(self.cfg, len(tokens), beam_width)
                if beam_width > self.max_batch_rows:
                    # beams tile the KV cache: one request must not
                    # exceed the server's configured device-row budget
                    raise ValueError(
                        f"beam_width capped at --max-batch-rows "
                        f"({self.max_batch_rows})"
                    )
            if (not 0 <= top_k <= self.cfg.vocab_size
                    or not 0.0 <= top_p <= 1.0):
                raise ValueError(
                    f"top_k must be in [0, vocab {self.cfg.vocab_size}] "
                    "and top_p in [0, 1]"
                )
            if eos_id >= self.cfg.vocab_size:
                raise ValueError(f"eos_id must be < vocab {self.cfg.vocab_size}")
            if prompt_len + max_new_requested > self.max_len:
                raise ValueError(
                    f"prompt_len + max_new_tokens exceeds max_len "
                    f"{self.max_len}"
                )
            if max_new_requested < 1:
                raise ValueError("max_new_tokens must be >= 1")
            # bucket the compiled decode length to multiples of 16 so
            # per-request max_new variation can't churn the jit cache
            max_new = min(
                -(-max_new_requested // 16) * 16,
                self.max_len - prompt_len,
            )
        except (ValueError, KeyError, TypeError) as exc:
            return Response(422, f"{exc}\n".encode())

        if beam_width:

            def run_beam() -> Any:
                from ..models.beam import beam_search

                # beam search is NOT prefix-consistent: the best
                # 16-token beam's first 6 tokens are not the best
                # 6-token continuation, so the compiled horizon is the
                # REQUESTED length, not the bucketed one (beams are
                # explicit requests; the compile churn is theirs)
                out, score = beam_search(
                    self.params, jnp.asarray(tokens, jnp.int32),
                    self.cfg, max_new_tokens=max_new_requested,
                    max_len=self.max_len, beam_width=beam_width,
                    eos_id=eos_id, length_penalty=length_penalty,
                    prefill_chunk=self.prefill_chunk,
                )
                self.batch_stats["calls"] += 1
                self.batch_stats["rows"] += 1
                return [jax.device_get(out).tolist()]

            loop = asyncio.get_event_loop()
            generated = await loop.run_in_executor(
                self._executor, run_beam
            )
        elif (
            self.draft_params is not None
            and temperature <= 0.0
            and len(tokens) == 1
        ):
            # greedy single-sequence: draft-and-verify, identical
            # output, ~accepted-per-round fewer target passes. An eos
            # trim below applies the same truncation the padded greedy
            # path would get.
            def run() -> Any:
                from ..models.speculative import speculative_generate

                out, _stats = speculative_generate(
                    self.params, self.draft_params,
                    jnp.asarray(tokens, jnp.int32), self.cfg,
                    self.draft_cfg, max_new_tokens=max_new,
                    max_len=self.max_len, speculate=self.speculate,
                )
                return jax.device_get(out).tolist()

            loop = asyncio.get_event_loop()
            generated = await loop.run_in_executor(self._executor, run)
        elif (
            self._prefix_cache is not None
            and len(tokens) == 1
            and (
                self._prefix_match_len(tokens[0])
                >= self._PREFIX_MIN_REUSE
                or self._gen_queue.empty()
            )
        ):
            # hit -> reuse; miss -> still seed the cache, but only when
            # nothing is queued (otherwise continuous batching would
            # have coalesced this request — don't trade batching
            # throughput for a cold-path seed)

            def run_prefix() -> Any:
                return self._generate_with_prefix(
                    tokens[0], max_new, temperature, top_k, top_p,
                    eos_id, seed,
                )

            loop = asyncio.get_event_loop()
            generated = await loop.run_in_executor(
                self._executor, run_prefix
            )
        elif (
            self.prefill_chunk > 0
            and len(tokens) == 1
            and prompt_len > self.prefill_chunk
        ):
            # long single-row prompt: stream the prefill in chunks

            def run_chunked() -> Any:
                from ..models.decode import (
                    chunked_prefill,
                    generate_from_cache,
                )

                logits, cache = chunked_prefill(
                    self.params, jnp.asarray(tokens, jnp.int32),
                    self.cfg, self.max_len, self.prefill_chunk,
                )
                self.batch_stats["calls"] += 1
                self.batch_stats["rows"] += 1
                out = generate_from_cache(
                    self.params, cache, logits, self.cfg,
                    max_new_tokens=max_new, temperature=temperature,
                    rng=jnp.stack([jax.random.fold_in(
                        jax.random.PRNGKey(seed), 0)]),
                    top_k=top_k, top_p=top_p, eos_id=eos_id,
                    pos=prompt_len,
                )
                return jax.device_get(out).tolist()

            loop = asyncio.get_event_loop()
            generated = await loop.run_in_executor(
                self._executor, run_chunked
            )
        else:
            job = _GenJob(
                rows=tokens, prompt_len=prompt_len, max_new=max_new,
                temperature=temperature, top_k=top_k, top_p=top_p,
                eos_id=eos_id, seed=seed,
                future=asyncio.get_event_loop().create_future(),
            )
            await self._gen_queue.put(job)
            generated = await job.future
        generated = [r[:max_new_requested] for r in generated]
        if eos_id >= 0:
            # trim each row at its first eos (inclusive); the model
            # emitted pad beyond it anyway
            generated = [
                row[: row.index(eos_id) + 1] if eos_id in row else row
                for row in generated
            ]
        return Response(
            200,
            json.dumps({"tokens": generated}).encode(),
            content_type="application/json",
        )

    async def _score(self, req: Request) -> Response:
        """Teacher-forced per-token logprobs of the given sequences —
        the standard scoring/perplexity endpoint (no sampling)."""
        try:
            body = json.loads(req.body.decode() or "{}")
            tokens, row_len = _parse_token_rows(
                body, self.cfg.vocab_size, min_row_len=2
            )
            if row_len > self.max_len:
                raise ValueError(f"row length exceeds max_len {self.max_len}")
        except (ValueError, KeyError, TypeError) as exc:
            return Response(422, f"{exc}\n".encode())

        if self._score_fn is None:
            from ..models.transformer import forward

            def score(params, toks):
                logits = forward(params, toks[:, :-1], self.cfg)
                logp = jax.nn.log_softmax(logits, axis=-1)
                picked = jnp.take_along_axis(
                    logp, toks[:, 1:, None], axis=-1
                )[..., 0]
                return picked  # [batch, len-1]

            self._score_fn = jax.jit(score)

        def run() -> Any:
            toks = jnp.asarray(tokens, jnp.int32)
            picked = self._score_fn(self.params, toks)
            picked = jax.device_get(picked).astype(float)
            return picked

        loop = asyncio.get_event_loop()
        picked = await loop.run_in_executor(self._executor, run)
        return Response(
            200,
            json.dumps(
                {
                    "logprobs": [[round(float(x), 6) for x in row]
                                 for row in picked],
                    "sums": [round(float(row.sum()), 6) for row in picked],
                }
            ).encode(),
            content_type="application/json",
        )

    # -- prefix KV reuse ------------------------------------------------

    _PREFIX_MIN_REUSE = 16  # shorter matches aren't worth a device call
    _PREFIX_BUCKET = 16     # suffix lengths compile in these steps

    def _prefix_match_len(self, row: List[int]) -> int:
        """Longest common prefix between ``row`` and any cached prompt
        (host-side scan; cheap relative to a device call)."""
        best = 0
        for stored in self._prefix_cache:
            n = min(len(stored), len(row))
            i = 0
            while i < n and stored[i] == row[i]:
                i += 1
            best = max(best, i)
        return best

    def _generate_with_prefix(
        self, row: List[int], max_new: int, temperature: float,
        top_k: int, top_p: float, eos_id: int, seed: int,
    ) -> List[List[int]]:
        """Single-row generation reusing the longest cached prompt
        prefix. The recomputed suffix is bucketed (a little of the
        matched prefix is re-prefilled) so jit compiles one extend
        program per bucket, not per suffix length. Stale cache rows
        beyond pos are masked/overwritten by design (models/decode.py),
        which is what makes the rewind sound — and why --window (ring
        cache) refuses this feature."""
        from ..models.decode import (
            _jitted_extend,
            _jitted_prefill,
            generate_from_cache,
        )

        key_row = tuple(row)
        plen = len(row)
        best_len, best_key = 0, None
        for stored in self._prefix_cache:
            n = min(len(stored), plen)
            i = 0
            while i < n and stored[i] == row[i]:
                i += 1
            if i > best_len:
                best_len, best_key = i, stored

        if best_len >= self._PREFIX_MIN_REUSE:
            suffix = plen - best_len
            bucket = max(
                1, -(-suffix // self._PREFIX_BUCKET) * self._PREFIX_BUCKET
            ) if suffix > 0 else 1
            reuse = plen - min(bucket, plen)
        else:
            reuse = 0
        if reuse > 0:
            base = self._prefix_cache[best_key]
            self._prefix_cache.move_to_end(best_key)
            # rewind: same arrays (incl. kv_int8 scales), earlier pos
            cache = {**base, "pos": jnp.asarray(reuse, jnp.int32)}
            chunk = jnp.asarray([row[reuse:]], jnp.int32)
            logits, cache = _jitted_extend(self.cfg)(
                self.params, cache, chunk
            )
            self.prefix_stats["hits"] += 1
            self.prefix_stats["tokens_reused"] += reuse
        elif self.prefill_chunk and plen > self.prefill_chunk:
            # cold long prompt: seed the prefix cache via the chunked
            # stream so the configured prefill HBM bound still holds
            from ..models.decode import chunked_prefill

            logits, cache = chunked_prefill(
                self.params, jnp.asarray([row], jnp.int32), self.cfg,
                self.max_len, self.prefill_chunk,
            )
            self.prefix_stats["misses"] += 1
        else:
            logits, cache = _jitted_prefill(self.cfg, self.max_len)(
                self.params, jnp.asarray([row], jnp.int32)
            )
            self.prefix_stats["misses"] += 1
        # store the completed prompt's cache for future turns
        self._prefix_cache[key_row] = cache
        self._prefix_cache.move_to_end(key_row)
        while len(self._prefix_cache) > self._prefix_cache_entries:
            self._prefix_cache.popitem(last=False)
        # the prefix path is a device call too — keep /v1/model's
        # batching telemetry honest when this path serves the traffic
        self.batch_stats["calls"] += 1
        self.batch_stats["rows"] += 1
        out = generate_from_cache(
            self.params, cache, logits, self.cfg,
            max_new_tokens=max_new, temperature=temperature,
            rng=jnp.stack([jax.random.fold_in(
                jax.random.PRNGKey(seed), 0)]),
            top_k=top_k, top_p=top_p, eos_id=eos_id,
            pos=plen,
        )
        return jax.device_get(out).tolist()

    # -- continuous batching -------------------------------------------

    async def _batch_loop(self) -> None:
        """Drain whatever requests queued while the device was busy,
        group the compatible ones (same prompt length and compiled
        decode length), and run each group as ONE device call with
        per-row sampling params. Per-row PRNG keys derive from each
        request's own seed, so a request's output never depends on
        what it happened to be batched with (tested)."""
        carry: Optional[_GenJob] = None
        try:
            while True:
                first = (
                    carry if carry is not None
                    else await self._gen_queue.get()
                )
                carry = None
                jobs = [first]
                rows = len(first.rows)
                # cap by ROW count (a request may carry several rows);
                # a job that would overflow carries to the next drain
                while (
                    rows < self.max_batch_rows
                    and not self._gen_queue.empty()
                ):
                    nxt = self._gen_queue.get_nowait()
                    if rows + len(nxt.rows) > self.max_batch_rows:
                        carry = nxt
                        break
                    jobs.append(nxt)
                    rows += len(nxt.rows)
                groups: Dict[Any, List[_GenJob]] = {}
                for job in jobs:
                    groups.setdefault(
                        (job.prompt_len, job.max_new), []
                    ).append(job)
                for group in groups.values():
                    await self._run_group(group)
        finally:
            # cancellation with a carried-over job in hand: fail it so
            # its handler doesn't await forever
            if carry is not None and not carry.future.done():
                carry.future.set_exception(RuntimeError("server stopping"))

    async def _run_group(self, jobs: List[_GenJob]) -> None:
        def run() -> List[List[int]]:
            rows: List[List[int]] = []
            temps: List[float] = []
            ks: List[int] = []
            ps: List[float] = []
            eoss: List[int] = []
            keys = []
            for job in jobs:
                base = jax.random.PRNGKey(job.seed)
                for i, r in enumerate(job.rows):
                    rows.append(r)
                    temps.append(job.temperature)
                    ks.append(job.top_k)
                    ps.append(job.top_p)
                    eoss.append(job.eos_id)
                    keys.append(jax.random.fold_in(base, i))
            # bucket the batch dim to powers of two so concurrency
            # spikes can't compile one program per row count
            target = 1
            while target < len(rows):
                target *= 2
            pad_rows = target - len(rows)
            for _ in range(pad_rows):
                rows.append([0] * len(rows[0]))
                temps.append(0.0)
                ks.append(0)
                ps.append(0.0)
                eoss.append(-1)
                keys.append(jax.random.PRNGKey(0))
            out = generate(
                self.params,
                jnp.asarray(rows, jnp.int32),
                self.cfg,
                max_new_tokens=jobs[0].max_new,
                max_len=self.max_len,
                temperature=temps,
                rng=jnp.stack(keys),
                top_k=ks,
                top_p=ps,
                eos_id=eoss,
            )
            n_real = len(rows) - pad_rows
            return jax.device_get(out[:n_real]).tolist()

        loop = asyncio.get_event_loop()
        self.batch_stats["calls"] += 1
        self.batch_stats["rows"] += sum(len(j.rows) for j in jobs)
        try:
            outs = await loop.run_in_executor(self._executor, run)
        except asyncio.CancelledError:
            # batcher cancelled mid-call (stop()): fail the waiters so
            # their handlers don't hang forever, then propagate
            for job in jobs:
                if not job.future.done():
                    job.future.set_exception(
                        RuntimeError("server stopping")
                    )
            raise
        except Exception as exc:  # surface as a per-request 500
            for job in jobs:
                if not job.future.done():
                    job.future.set_exception(exc)
            return
        i = 0
        for job in jobs:
            if not job.future.done():  # waiter may have been cancelled
                job.future.set_result(outs[i:i + len(job.rows)])
            i += len(job.rows)

    # -- lifecycle ------------------------------------------------------

    async def warmup(self) -> None:
        """Compile the default-shaped programs before reporting healthy.

        Requests with other prompt lengths still compile on first use
        (shapes are static); the bucketed max_new keeps that churn
        bounded."""

        def run() -> None:
            for prompt_len in (4, 16):
                if prompt_len + 16 > self.max_len:
                    continue
                prompt = jnp.zeros((1, prompt_len), jnp.int32)
                generate(
                    self.params, prompt, self.cfg, max_new_tokens=16,
                    max_len=self.max_len,
                )
                if self.draft_params is not None and prompt_len == 4:
                    # the DEFAULT path for greedy traffic: compile the
                    # draft prefill and EVERY per-k draft/verify
                    # variant — k varies 1..speculate at request time
                    # with data-dependent acceptance, and any uncompiled
                    # k would stall a live request
                    from ..models.decode import prefill
                    from ..models.speculative import (
                        _jit_draft_round,
                        _jit_verify_round,
                    )

                    _logits, cache = prefill(
                        self.params, prompt, self.cfg, self.max_len
                    )
                    _dlogits, dcache = prefill(
                        self.draft_params, prompt, self.draft_cfg,
                        self.max_len,
                    )
                    prev = jnp.zeros((1,), jnp.int32)
                    for k in range(1, self.speculate + 1):
                        _jit_draft_round(self.draft_cfg, k)(
                            self.draft_params, dcache, prev
                        )
                        # verify chunks are k+1 tokens ([prev, drafts])
                        _jit_verify_round(self.cfg, k + 1)(
                            self.params, cache,
                            jnp.zeros((1, k + 1), jnp.int32),
                        )

        await asyncio.get_event_loop().run_in_executor(self._executor, run)
        self.ready = True
        log.info("serve: default shapes warm; accepting traffic")

    async def run(self) -> None:
        await self._server.start_tcp(self.host, self.port)
        self.port = self._server.bound_port or self.port
        self._batcher = asyncio.get_event_loop().create_task(
            self._batch_loop()
        )
        log.info("serve: listening on %s:%d", self.host, self.port)
        await self.warmup()

    async def stop(self) -> None:
        if self._batcher is not None:
            self._batcher.cancel()
            try:
                await self._batcher
            except asyncio.CancelledError:
                pass
            # fail anything still queued so no handler awaits forever
            while not self._gen_queue.empty():
                job = self._gen_queue.get_nowait()
                if not job.future.done():
                    job.future.set_exception(
                        RuntimeError("server stopping")
                    )
        await self._server.stop()


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--max-len", type=int, default=512)
    parser.add_argument("--d-model", type=int, default=256)
    parser.add_argument("--n-layers", type=int, default=2)
    parser.add_argument("--n-heads", type=int, default=4)
    parser.add_argument("--n-kv-heads", type=int, default=0,
                        help="GQA kv heads (0 = full multi-head); must "
                        "match the checkpoint being served")
    parser.add_argument("--moe-experts", type=int, default=0,
                        help="switch-MoE experts; must match the "
                        "checkpoint being served")
    parser.add_argument("--window", type=int, default=0,
                        help="sliding-window attention; must match the "
                        "checkpoint being served. Decode KV memory "
                        "becomes a ring of `window` slots")
    parser.add_argument("--vocab", type=int, default=1024)
    parser.add_argument(
        "--checkpoint-dir", default="",
        help="load trained params from the latest checkpoint",
    )
    parser.add_argument(
        "--use-ema", action="store_true",
        help="serve the EMA shadow weights from the checkpoint "
        "(trained with --ema-decay) instead of the raw params",
    )
    parser.add_argument(
        "--int8", action="store_true",
        help="weight-only int8: ~4x smaller resident params",
    )
    parser.add_argument(
        "--kv-int8", action="store_true",
        help="int8 KV cache: halves decode KV memory vs bf16 "
        "(per-token-per-head scales; composes with GQA and --window)",
    )
    parser.add_argument(
        "--lora-dir", default="",
        help="merge a trained LoRA adapter checkpoint into the base "
        "weights at startup (zero runtime overhead); requires "
        "--lora-rank to match the adapter",
    )
    parser.add_argument(
        "--lora-rank", type=int, default=0,
        help="rank of the adapter in --lora-dir",
    )
    parser.add_argument(
        "--draft-layers", type=int, default=0,
        help="self-speculative decoding: draft with the model's first "
        "N layers; greedy single-sequence requests decode several "
        "tokens per target pass with identical output (0 = off)",
    )
    parser.add_argument(
        "--speculate", type=int, default=4,
        help="draft tokens proposed per verify round",
    )
    parser.add_argument(
        "--max-batch-rows", type=int, default=16,
        help="continuous batching: max sequences coalesced into one "
        "device call",
    )
    parser.add_argument(
        "--prefill-chunk", type=int, default=0,
        help="stream prompts longer than N through chunked prefill "
        "(peak prefill activations O(N) instead of O(prompt)); 0 = "
        "one-shot prefill",
    )
    parser.add_argument(
        "--prefix-cache", type=int, default=0,
        help="prefix KV reuse: keep the KV caches of the last N "
        "prompts and re-prefill only the unseen suffix of single-row "
        "requests sharing a prefix (the chat/agent regime); 0 = off",
    )
    args = parser.parse_args()

    cfg = TransformerConfig(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads,
        n_layers=args.n_layers,
        d_ff=args.d_model * 3 // 128 * 128 or 128,
        max_seq_len=args.max_len,
        moe_experts=args.moe_experts,
        window=args.window,
        kv_int8=args.kv_int8,
    )
    params = None
    if args.checkpoint_dir:
        from ..parallel import (
            abstract_train_state,
            make_mesh,
            restore_params,
        )

        mesh = make_mesh()
        # params-only restore: optimizer moments stay PLACEHOLDERs on
        # disk, so the server never pays train-state memory
        abstract = abstract_train_state(jax.random.PRNGKey(0), cfg, mesh)
        restored = restore_params(
            args.checkpoint_dir, abstract, prefer_ema=args.use_ema
        )
        if restored is not None:
            params, step = restored
            print(f"serving checkpoint step {int(step)}"
                  + (" (EMA weights)" if args.use_ema else ""))
    if params is None:
        params = init_params(jax.random.PRNGKey(0), cfg)
    if args.lora_rank > 0 and not args.lora_dir:
        raise SystemExit("--lora-rank without --lora-dir does nothing; "
                         "pass the adapter checkpoint dir")
    if args.lora_dir:
        if args.lora_rank < 1:
            raise SystemExit("--lora-dir requires --lora-rank")
        from ..models.lora import apply_lora
        from ..parallel import (
            lora_abstract_state,
            make_mesh,
            restore_params,
        )

        # the adapter must land on the SAME mesh the base weights use
        # (make_mesh() == all local devices, matching the
        # --checkpoint-dir restore above); a mismatched device set
        # makes the merge add uncompilable
        restored_lora = restore_params(
            args.lora_dir,
            lora_abstract_state(cfg, args.lora_rank, make_mesh()),
        )
        if restored_lora is None:
            raise SystemExit(f"no adapter checkpoint in {args.lora_dir}")
        lora, lora_step_n = restored_lora
        # merge BEFORE any quantization: int8 bases aren't adaptable
        params = apply_lora(params, lora, cfg)
        print(f"merged lora adapter (rank {args.lora_rank}, "
              f"step {int(lora_step_n)})")
    if args.int8:
        from ..models.quantized import param_bytes, quantize_model_params

        before = param_bytes(params)
        params = quantize_model_params(params)
        print(
            f"int8: params {before} -> {param_bytes(params)} bytes "
            f"({before / param_bytes(params):.1f}x smaller)"
        )

    server = InferenceServer(
        cfg, params, args.host, args.port, args.max_len,
        draft_layers=args.draft_layers, speculate=args.speculate,
        max_batch_rows=args.max_batch_rows,
        prefix_cache_entries=args.prefix_cache,
        prefill_chunk=args.prefill_chunk,
    )

    async def serve() -> None:
        import signal as signal_mod

        await server.run()
        stop = asyncio.Event()
        loop = asyncio.get_event_loop()
        for sig in (signal_mod.SIGTERM, signal_mod.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await server.stop()

    asyncio.run(serve())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
