"""A supervised inference server: the serving half of the demo workload.

One server process per TPU host, supervised by containerpilot-tpu:
health-checked over ``GET /health`` (so a wedged server goes
catalog-critical and restarts), advertised in the catalog by its job's
``port``, optionally loading weights from a training checkpoint dir.

API (token-level; tokenization is the caller's concern):

    POST /v1/generate {"tokens": [[1,2,3]], "max_new_tokens": 16,
                       "temperature": 0.0}
        -> {"tokens": [[...generated ids...]]}
        ("logprobs": true echoes per-token logprobs of the trimmed
         output via one teacher-forced pass — decode is bit-equal to
         the forward, so these are exactly the sampler's numbers;
         approximate only under --kv-int8, whose decode reads a
         quantized KV cache)
    POST /v1/score    {"tokens": [[1,2,3,4]]}
        -> {"logprobs": [[lp(t1|t0), lp(t2|t0..1), ...]],
            "sums": [total lp per row]}   (teacher-forced scoring)
    POST /v1/completions {"prompt": "text", ...}   (behind --text)
        -> {"text": "...", "tokens": [...]}  (byte-level tokenizer)
    GET /health   -> 200 once the model is compiled and warm
    GET /v1/model -> config summary
    GET /metrics  -> Prometheus exposition (requests, latency, tokens)

Generation runs on a worker thread so the asyncio loop (health checks
included) never blocks on TPU execution. The serving concerns live in
sibling modules: serve_batcher (continuous batching), serve_prefix
(prefix KV reuse), serve_strategies (beam/cp/chunked), serve_slots +
models/stepprog (the step-program engine — plain, quantized, and
speculative decode), serve_cli (flags + model loading).
"""
from __future__ import annotations

import asyncio
import json
import logging
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Awaitable, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..models.transformer import TransformerConfig
from ..telemetry import tracing
from ..utils.http import HTTPServer, Request, Response, StreamingResponse
from . import serve_strategies
from .serve_batcher import Batcher, GenJob
from .serve_cli import main  # noqa: F401  (one import path for the CLI)
from .serve_prefix import MIN_REUSE, PrefixCache, generate_with_prefix

log = logging.getLogger("containerpilot.serve")

# warmup()'s slot-engine dummy request: this many prompt ids +
# (chunk+1) new tokens. The construction-time max_len guard and the
# warm request itself must agree or the guard stops protecting.
WARMUP_PROMPT_LEN = 4

_GenJob = GenJob  # pre-split name, kept for importers


def _parse_token_rows(body: Dict[str, Any], vocab: int, min_row_len: int):
    """Shared request validation for token-matrix endpoints: a
    non-empty list of equal-length integer rows within the vocab.
    Raises ValueError with a client-facing message."""
    tokens = body["tokens"]
    if not isinstance(tokens, list) or not tokens or not all(
        isinstance(row, list) and len(row) >= min_row_len for row in tokens
    ):
        raise ValueError(
            f"'tokens' must be a non-empty list of rows with "
            f">= {min_row_len} ids"
        )
    row_len = len(tokens[0])
    if any(len(row) != row_len for row in tokens):
        raise ValueError("all rows must share a length (pad first)")
    if any(
        not isinstance(t, int) or isinstance(t, bool) or t < 0 or t >= vocab
        for row in tokens
        for t in row
    ):
        raise ValueError(f"token ids must be integers in [0, {vocab})")
    return tokens, row_len


class InferenceServer:
    def __init__(
        self,
        cfg: TransformerConfig,
        params: Any,
        host: str,
        port: int,
        max_len: int,
        draft_layers: int = 0,
        speculate: int = 4,
        max_batch_rows: int = 16,
        prefix_cache_entries: int = 0,
        kv_spill_bytes: int = 0,
        prefill_chunk: int = 0,
        text: bool = False,
        slots: int = 0,
        slot_chunk: int = 8,
        slot_window: int = 4,
        cp_mesh: Any = None,
        cp_min_len: int = 0,
        mux: bool = True,
        role: str = "active",
        compile_cache_dir: str = "",
        prefill_floor_s: float = 0.0,
    ) -> None:
        self.cfg = cfg
        self.params = params
        self.host = host
        self.port = port
        self.max_len = max_len
        self.ready = False
        # fleet role: a "standby" replica boots, loads weights, and
        # warmup-compiles exactly like an active one, but /health says
        # so (503 standby) and new decode work is refused — it
        # heartbeats into the catalog under role=standby and waits for
        # POST /v3/standby/promote to flip it active in one
        # assignment (fleet/standby.py is the pool that promotes).
        # "prefill" and "decode" are the disaggregated pools' phase
        # roles: both serve traffic and answer /health 200 like an
        # active replica (so degradation to mixed routing always has
        # somewhere to go) — the role is ROUTING ADVICE the gateway
        # reads off the same heartbeat note channel, steering fresh
        # prompts at the prefill pool and decode continuations at the
        # decode pool (fleet/gateway.py's phase-aware _pick).
        if role not in ("active", "standby", "prefill", "decode"):
            raise ValueError(
                "role must be 'active', 'standby', 'prefill', or "
                "'decode'"
            )
        self.role = role
        # persistent XLA compile cache dir this replica serves with
        # (advertised through heartbeat notes so same-host launches
        # adopt it); warmup consults its warm-bucket marker and skips
        # buckets a previous process already compiled. Enabled HERE,
        # not only in the CLI: a warm-bucket marker must never be
        # written by a process whose compiles didn't actually land in
        # the disk cache — that marker would promise executables a
        # later launch won't find
        self.compile_cache_dir = compile_cache_dir
        if compile_cache_dir:
            from .modelcfg import enable_compile_cache

            enable_compile_cache(compile_cache_dir)
        # the cc= heartbeat advertisement, computed once at warmup
        # end (executor-wrapped): heartbeats must never pay marker
        # file I/O on the serving loop
        self._compile_cache_note = ""
        # peer weight transfer: the manifest is built once (executor)
        # and cached — chunk bytes are re-derived lazily per request
        # so the server never holds a second full copy of the params
        self._weights_manifest_cache: Optional[Any] = None
        self._weights_manifest_bytes = b""
        self._weights_lock: Optional[asyncio.Lock] = None
        # device-time ledger (telemetry/goodput.py): every wall-second
        # of this replica's life attributed to exactly one stage,
        # starting NOW in ``boot`` — weight setup, engine construction
        # and port binding are costed before warmup() moves the ledger
        # to compile_warmup and, finally, idle (before /health flips
        # 200, so a scale-up replica's badput is visible from its very
        # first scrape)
        from ..telemetry.goodput import DeviceTimeLedger

        self.ledger = DeviceTimeLedger()
        # maintenance drain: /health goes 503 and NEW generate/
        # completions are rejected with 503 + Retry-After while
        # everything already admitted (including running slot-engine
        # rows) decodes to completion. Flipped by enter_maintenance/
        # exit_maintenance — the hook fleet.FleetMember drives off the
        # control plane's /v3/maintenance endpoints.
        self.draining = False
        self._inflight = 0
        # drain migration (kvtier/handoff.py in reverse): progress of
        # the CURRENT evacuation plus cumulative counters for the
        # ``mg=`` heartbeat field. ``landed`` maps fingerprint ->
        # target instance id, most-recent-last (the note encoder
        # reverses it so truncation drops the oldest repoints); the
        # gateway repoints its sticky pins off these landings.
        self.migration: Dict[str, Any] = {
            "active": False, "total": 0, "done": 0, "failed": 0,
            "timeout": 0, "window_s": 0.0, "started_at": 0.0,
        }
        self._migration_landed: "OrderedDict[int, str]" = OrderedDict()
        self._migration_counters = {
            "done": 0, "total": 0, "failed": 0, "timeout": 0,
        }
        # test-only fault-injection seam (chaos harness): when set,
        # awaited before every instrumented API handler. Injects
        # per-request latency (slow-replica brownouts) or raises to
        # fail requests, without touching any serving path. Never set
        # in production; None costs one attribute load per request.
        self.chaos_hook: Optional[
            Callable[[str], Awaitable[None]]
        ] = None
        # context-parallel prefill: single-row prompts at least
        # cp_min_len long ring over the mesh's seq axis
        # (parallel.cp_generate); everything else takes the usual
        # paths. Composition is validated at startup below.
        self.cp_mesh = cp_mesh
        self.cp_min_len = cp_min_len
        if cp_mesh is not None:
            seq_axis = cp_mesh.shape.get("seq", 1)
            if seq_axis <= 1:
                raise ValueError(
                    "--cp mesh needs a seq axis > 1 "
                    "(MeshPlan(seq=...))"
                )
            # ONE policy for deriving/clamping/refusing the threshold,
            # shared with the pod's --sp (parallel/context.py)
            from ..parallel.context import resolve_cp_min_len

            self.cp_min_len = resolve_cp_min_len(
                cp_min_len, seq_axis, max_len
            )
            for flag, why in (
                (draft_layers > 0, "--draft-layers (speculative "
                 "prefill is chunk-driven)"),
                (prefix_cache_entries > 0, "--prefix-cache (cached "
                 "prefixes bypass the ring)"),
                (cfg.window > 0, "--window (ring attention rejects "
                 "sliding windows)"),
            ):
                if flag:
                    raise ValueError(
                        f"--cp does not compose with {why}"
                    )
        # self-speculative decoding: a layer-prefix draft accelerates
        # greedy single-sequence generation, output unchanged
        self.draft_params = self.draft_cfg = None
        self.speculate = speculate
        if draft_layers > 0 and speculate < 1:
            # fail at startup, not as request-time 500s
            raise ValueError("speculate must be >= 1")
        if draft_layers > 0 and cfg.window > 0:
            raise ValueError(
                "--draft-layers does not compose with --window "
                "(speculative rollback cannot undo ring-cache writes)"
            )
        if prefix_cache_entries > 0 and cfg.window > 0:
            raise ValueError(
                "--prefix-cache does not compose with --window (a "
                "ring cache's stale rows are live window context, so "
                "a shorter-prefix rewind cannot reuse them)"
            )
        if kv_spill_bytes > 0 and prefix_cache_entries <= 0:
            raise ValueError(
                "--kv-spill requires --prefix-cache (the spill tier "
                "catches the prefix cache's evictions)"
            )
        spill = None
        if kv_spill_bytes > 0:
            # host-RAM floor under the device LRU: evictions spill,
            # later matches readmit via device_put (kvtier/spill.py)
            from ..kvtier import HostSpillTier

            spill = HostSpillTier(kv_spill_bytes)
        self.prefix_cache: Optional[PrefixCache] = (
            PrefixCache(prefix_cache_entries, spill=spill)
            if prefix_cache_entries > 0 else None
        )
        # continuous decode admission: single-row requests join a
        # running K-token chunk loop over a fixed slot pool instead of
        # queueing behind whole generations (serve_slots.py)
        self.slot_engine = None
        if slot_window < 1:
            raise ValueError("slot_window must be >= 1")
        if slots > 0:
            # warmup() pushes a dummy request of 4 prompt ids +
            # (chunk+1) new tokens through the engine; a legal but
            # tiny --max-len must fail HERE with a clean message, not
            # after the port is bound with a submit() traceback
            if WARMUP_PROMPT_LEN + slot_chunk + 1 > max_len:
                raise ValueError(
                    f"--slots requires max_len >= slot_chunk + "
                    f"{WARMUP_PROMPT_LEN + 1} (warmup request needs "
                    f"{WARMUP_PROMPT_LEN} prompt ids + "
                    f"chunk+1={slot_chunk + 1} new tokens; max_len is "
                    f"{max_len})"
                )
            # fused K-round windows need a warmup request that rides
            # at least one pure-decode cycle (chunk+2 new tokens); a
            # max_len too tight for that clamps the engine back to
            # one-round dispatches rather than leaving the fused
            # program to compile under a live request behind a 200
            # /health (the no-post-grace-compiles invariant)
            if WARMUP_PROMPT_LEN + slot_chunk + 2 > max_len:
                slot_window = 1
            from .serve_slots import SlotEngine

            # --cp composes: long-prompt admissions ring their
            # prefill over the cp mesh's seq axis before joining the
            # pool (the engine runs the same cp_prefill_with_remainder
            # recipe the pod's --sp path does)
            # --prefill-chunk composes (admissions longer than the
            # chunk prefill in pieces) and so does --prefix-cache
            # (admissions with a cached prefix rewind+extend; every
            # admission seeds the cache) — both inside the engine
            self.slot_engine = SlotEngine(
                cfg, params, max_len, slots=slots, chunk=slot_chunk,
                window=slot_window,
                cp_mesh=self.cp_mesh, cp_min_len=self.cp_min_len,
                prefill_chunk=prefill_chunk,
                prefix_cache=self.prefix_cache,
                ledger=self.ledger,
                prefill_floor_s=prefill_floor_s,
            )
        self.slot_window = slot_window
        # prompts longer than this stream through decode_chunk pieces
        # (peak prefill activations O(chunk) instead of O(prompt))
        self.prefill_chunk = prefill_chunk
        self.spec_engine = None
        if draft_layers > 0:
            from ..models.speculative import (
                SpeculativeStepProgram,
                layer_prefix_draft,
            )
            from .serve_slots import SlotEngine

            self.draft_params, self.draft_cfg = layer_prefix_draft(
                params, cfg, draft_layers
            )
            # speculative decoding rides the slot engine as a step
            # program (models/stepprog.py) instead of the legacy
            # one-shot serve_strategies path: the engine brings
            # queueing/cancel/tracing and the protocol brings
            # multi-token emission per round. One slot, batch 1 —
            # the verify rollback is a per-sequence pos rewind.
            # ledger=None deliberately: with a slot engine present
            # it owns the prefill/decode stamps, and without one the
            # handler-inflight window in _instrumented coarse-stamps
            # every compute request (spec included) — a second
            # stamping authority would fight either one.
            self.spec_engine = SlotEngine(
                cfg, params, max_len,
                prefill_chunk=prefill_chunk,
                program=SpeculativeStepProgram(
                    cfg, self.draft_cfg, params, self.draft_params,
                    max_len, speculate=speculate,
                ),
            )
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="inference"
        )
        # serving observability: request/latency/token metrics in a
        # private registry (the supervisor's own /metrics lives on the
        # telemetry server and must not collide in-process)
        from prometheus_client import (
            CollectorRegistry,
            Counter,
            Histogram,
        )

        self._metrics_registry = CollectorRegistry()
        self._m_requests = Counter(
            "containerpilot_serve_requests",
            "requests served, by endpoint and status code",
            ["endpoint", "code"], registry=self._metrics_registry,
        )
        self._m_latency = Histogram(
            "containerpilot_serve_request_seconds",
            "request wall time, by endpoint",
            ["endpoint"], registry=self._metrics_registry,
            buckets=(.005, .02, .05, .1, .25, .5, 1, 2.5, 5, 10, 30, 60),
        )
        self._m_tokens = Counter(
            "containerpilot_serve_generated_tokens",
            "tokens returned by generate/completions (post-trim)",
            registry=self._metrics_registry,
        )
        from ..utils.prom import (
            ensure_build_info,
            ensure_goodput_gauges,
            ensure_loop_lag_gauge,
        )

        ensure_build_info(self._metrics_registry, "replica")
        # the goodput ledger's metrics face: cp_device_seconds_total
        # {stage} + the dispatches/token counter pair (engine-less
        # servers report zeros; the ledger still accounts their life)
        ensure_goodput_gauges(
            self._metrics_registry, self.ledger, self._decode_counters
        )
        # event-loop health sentinel (analysis/loopcheck.py): one
        # blocking call on this loop stalls every stream, heartbeat,
        # and health check the replica serves — cp_loop_lag_ms is the
        # named form of that stall, gated in the chaos quick suite
        from ..analysis.loopcheck import LoopLagProbe

        self._loop_probe = LoopLagProbe()
        ensure_loop_lag_gauge(self._metrics_registry, self._loop_probe)
        # replica-side request tracing: spans recorded under the
        # gateway's trace id (X-CP-Trace / the mux HEADERS field) —
        # or a freshly minted one for direct clients — retained in a
        # per-server ring on GET /v1/traces, and handed back to the
        # caller as a compact digest (header / final SSE frame) so
        # the gateway stitches a cross-hop timeline without a second
        # RPC. See telemetry/tracing.py.
        self._tracer = tracing.TraceRecorder("replica")
        self._server = HTTPServer()
        # cp-mux/1 accept path (the fleet gateway's multiplexed
        # transport); --no-mux keeps this replica plain HTTP/1.1 and
        # the gateway falls back per-replica
        self._server.mux_enabled = mux
        self._server.route("GET", "/health", self._health)
        self._server.route("GET", "/metrics", self._metrics)
        self._server.route("GET", "/v1/traces", self._traces)
        self._server.route("GET", "/v1/goodput", self._goodput)
        # cold-start collapse seams (fleet/standby.py): the promote
        # verb flips a standby active in one assignment, and the
        # weights endpoint serves this replica's params as a
        # digest-verified chunk stream a launching peer fetches over
        # cp-mux/1 instead of re-reading disk
        self._server.route(
            "POST", "/v3/standby/promote", self._promote_verb
        )
        self._server.route("GET", "/v1/weights", self._weights)
        # disaggregated prefill/decode handoff (kvtier/handoff.py):
        # the prefill verb seeds this replica's prefix cache through
        # the ordinary slot-engine admission; the kv export serves
        # one cached entry as a digest-verified chunk stream; the
        # pull verb fetches an entry from a named peer and injects
        # it into the spill tier for the next request to readmit
        self._server.route("POST", "/v1/prefill", self._prefill_verb)
        self._server.route("POST", "/v1/kv", self._kv_export)
        self._server.route("POST", "/v1/kv/pull", self._kv_pull)
        # drain migration: registered DIRECTLY (not _instrumented)
        # like /v1/kv — a DRAINING replica must still take migration
        # instructions and answer progress queries
        self._server.route("POST", "/v1/migrate", self._migrate_verb)
        route = self._instrumented
        self._server.route("GET", "/v1/model", route(
            "model", self._model_info
        ))
        self._server.route("POST", "/v1/generate", route(
            "generate", self._generate
        ))
        self._server.route("POST", "/v1/score", route(
            "score", self._score
        ))
        # text surface: byte-level tokenizer, zero external assets
        self.tokenizer = None
        if text:
            from .text import ByteTokenizer

            self.tokenizer = ByteTokenizer(cfg.vocab_size)
            self._server.route("POST", "/v1/completions", route(
                "completions", self._completions
            ))
        self._score_fn = None  # jitted lazily; jit caches per length
        # continuous batching: requests queue here and the batcher
        # coalesces whatever accumulated while the device was busy
        self.max_batch_rows = max_batch_rows
        self._batcher = Batcher(
            params, cfg, max_len, max_batch_rows, self._executor
        )
        self.batch_stats = self._batcher.stats

    # -- handlers -------------------------------------------------------

    async def _health(self, _req: Request) -> Response:
        if self.draining:
            # draining ranks above warming: a supervisor health check
            # (or a fleet gateway) must route away NOW even if the
            # model is warm
            return Response(
                503, b"draining\n", headers={"Retry-After": "1"}
            )
        if not self.ready:
            return Response(503, b"warming up\n")
        if self.role == "standby":
            # warm but deliberately not serving: a standby answers
            # health probes honestly (it is NOT taking traffic) while
            # its catalog heartbeat carries role=standby so gateways
            # know it exists. Promotion flips this to 200 instantly.
            return Response(
                503, b"standby\n", headers={"Retry-After": "1"}
            )
        return Response(200, b"ok\n")

    async def _metrics(self, _req: Request) -> Response:
        from ..utils.prom import exposition

        body, content_type = exposition(self._metrics_registry)
        return Response(200, body, content_type=content_type)

    async def _traces(self, req: Request) -> Response:
        """Per-process trace ring: slowest-N + most-recent-N, JSON."""
        return Response(
            200,
            self._tracer.snapshot_json(req.query),
            content_type="application/json",
        )

    def _decode_counters(self):
        """(dispatches, tokens_out) for the goodput surfaces — the
        slot and speculative engines' cumulative pairs summed (each
        engine bumps dispatches once per DEVICE dispatch: one per
        fused window, two per draft+verify round), zeros without
        either engine."""
        dispatches = tokens_out = 0
        for engine in (self.slot_engine, self.spec_engine):
            if engine is not None:
                dispatches += engine.dispatches
                tokens_out += engine.tokens_out
        return dispatches, tokens_out

    async def _goodput(self, _req: Request) -> Response:
        """The device-time ledger, JSON: per-stage seconds (summing
        to uptime by construction), productive fraction, the
        dispatches/token pair, and any detected scheduling gaps —
        requests whose trace says ``slot_queue_wait`` dominated while
        this ledger shows idle seconds inside the same window (free
        capacity the scheduler didn't use). All computed on this read
        path; record paths stay boundary-floats only."""
        from ..telemetry.goodput import goodput_payload

        dispatches, tokens_out = self._decode_counters()
        payload = goodput_payload(
            self.ledger, self._tracer, dispatches, tokens_out,
            role="replica", ready=self.ready, draining=self.draining,
        )
        return Response(
            200, json.dumps(payload).encode(),
            content_type="application/json",
        )

    # -- cold-start collapse surfaces (fleet/standby.py) ---------------

    def promote(self) -> bool:
        """Standby -> active in one assignment: /health flips 200 and
        generate/completions open on the very next request. False
        when this replica is not a promotable standby (already
        active, or draining) — the 409 the HTTP verb answers, and
        the signal the StandbyLauncher uses to drop a contended or
        dying standby and try the next one."""
        if self.role != "standby" or self.draining:
            return False
        self.role = "active"
        log.info("serve: standby promoted to active")
        return True

    async def _promote_verb(self, _req: Request) -> Response:
        """``POST /v3/standby/promote``: the control-plane face of
        ``promote()``. Exactly one promoter wins a race — the second
        call finds role already active and 409s (its caller returns
        the loser to the pool or takes the cold path)."""
        if self.role == "active":
            return Response(409, b"already active\n")
        if self.draining:
            return Response(409, b"draining\n")
        self.promote()
        return Response(
            200,
            json.dumps(
                {"promoted": True, "ready": self.ready}
            ).encode(),
            content_type="application/json",
        )

    async def _ensure_weights_manifest(self):
        """Build (once, executor-wrapped) and cache the transfer
        manifest: leaf/chunk table + digests. Chunk BYTES are not
        cached — they re-derive deterministically at serve time, so
        the server never holds a second full copy of the params."""
        if self._weights_manifest_cache is not None:
            return self._weights_manifest_cache
        if self._weights_lock is None:
            self._weights_lock = asyncio.Lock()
        async with self._weights_lock:
            if self._weights_manifest_cache is None:
                from ..fleet.standby import (
                    encode_manifest,
                    weights_manifest,
                )

                loop = asyncio.get_event_loop()
                manifest = await loop.run_in_executor(
                    None, weights_manifest, self.params
                )
                self._weights_manifest_bytes = encode_manifest(manifest)
                self._weights_manifest_cache = manifest
        return self._weights_manifest_cache

    async def _weights(self, req: Request) -> Response:
        """``GET /v1/weights[?chunk=K]``: this replica's params as a
        length-prefixed manifest followed by digest-verified chunks,
        from flat chunk index K (the resume point after a connection
        death). Served as a close-delimited stream — over cp-mux/1 it
        rides one flow-controlled stream that interleaves with live
        decode traffic. Each leaf is device-fetched on an executor as
        the stream reaches it; the loop never blocks on a transfer."""
        manifest = await self._ensure_weights_manifest()
        try:
            start = int(req.query.get("chunk", ["0"])[0])
        except (ValueError, IndexError):
            return Response(422, b"chunk must be an integer\n")
        chunk_specs = manifest["chunks"]
        if not 0 <= start <= len(chunk_specs):
            return Response(
                422,
                f"chunk must be in [0, {len(chunk_specs)}]\n".encode(),
            )
        from ..fleet.standby import leaf_bytes

        head = self._weights_manifest_bytes
        flat_leaves = jax.tree_util.tree_leaves(self.params)
        loop = asyncio.get_event_loop()

        async def body():
            yield head
            current = -1
            data = b""
            for spec in chunk_specs[start:]:
                if spec["leaf"] != current:
                    current = spec["leaf"]
                    data = await loop.run_in_executor(
                        None, leaf_bytes, flat_leaves[current]
                    )
                yield data[spec["offset"]:spec["offset"] + spec["len"]]

        return StreamingResponse(
            body(), content_type="application/octet-stream"
        )

    # -- disaggregated prefill/decode handoff (kvtier/handoff.py) ------

    async def _prefill_verb(self, req: Request) -> Response:
        """``POST /v1/prefill {"tokens": [[...]]}``: run one prompt
        through the ordinary slot-engine admission path for its SIDE
        EFFECT — the completed prompt's KV lands in the prefix cache
        (and its fingerprint in the next digest beat) — discarding
        the single sampled token. The prefill half of a disaggregated
        handoff: the gateway calls this on the prefill pool, then
        tells the pinned decode replica to pull the entry."""
        if self.slot_engine is None or self.prefix_cache is None:
            return Response(
                409,
                b"prefill handoff needs --slots and --prefix-cache\n",
            )
        if self.draining:
            return Response(
                503, b"draining\n", headers={"Retry-After": "1"}
            )
        try:
            body = json.loads(req.body.decode() or "{}")
            tokens, prompt_len = _parse_token_rows(
                body, self.cfg.vocab_size, min_row_len=1
            )
            if len(tokens) != 1:
                raise ValueError("prefill takes a single token row")
            if prompt_len + 1 > self.max_len:
                raise ValueError(
                    f"prompt_len + 1 exceeds max_len {self.max_len}"
                )
        except (ValueError, KeyError, TypeError) as exc:
            return Response(422, f"{exc}\n".encode())
        row = tokens[0]
        fut = self.slot_engine.submit(row, max_new=1)
        await asyncio.wrap_future(fut)
        key = tuple(row)
        pc = self.prefix_cache
        cached = pc.device_entry(key) is not None or (
            pc.spill is not None and pc.spill.peek(key) is not None
        )
        return Response(
            200,
            json.dumps(
                {
                    "ok": True,
                    # False for prompts under the reuse floor — they
                    # can never be reused, so the engine didn't cache
                    # them and there is nothing to hand off
                    "cached": bool(cached),
                    "tokens_prefilled": prompt_len,
                }
            ).encode(),
            content_type="application/json",
        )

    async def _kv_export(self, req: Request) -> Response:
        """``POST /v1/kv[?chunk=K] {"tokens": [[...]]}``: this
        replica's prefix-cache entry for exactly that prompt, as a
        length-prefixed manifest followed by digest-verified chunks
        from flat index K — the weight stream's framing and resume
        discipline (kvtier/handoff.py). 404 when the entry is gone
        from both tiers: the puller returns None and its gateway
        falls back to a local prefill. Serialization (device_get +
        tobytes) runs on an executor; the loop never blocks."""
        pc = self.prefix_cache
        if pc is None:
            return Response(409, b"no prefix cache on this replica\n")
        try:
            body = json.loads(req.body.decode() or "{}")
            tokens, _plen = _parse_token_rows(
                body, self.cfg.vocab_size, min_row_len=1
            )
            if len(tokens) != 1:
                raise ValueError("kv export takes a single token row")
        except (ValueError, KeyError, TypeError) as exc:
            return Response(422, f"{exc}\n".encode())
        try:
            start = int(req.query.get("chunk", ["0"])[0])
        except (ValueError, IndexError):
            return Response(422, b"chunk must be an integer\n")
        if start < 0:
            return Response(422, b"chunk must be >= 0\n")
        key = tuple(tokens[0])
        loop = asyncio.get_event_loop()

        def plan():
            from ..kvtier.handoff import kv_transfer_plan

            cache = pc.device_entry(key)
            if cache is not None:
                host = jax.device_get(cache)
            elif pc.spill is not None:
                # spilled entries are already host numpy — export
                # without waking the device or disturbing the LRU
                host = pc.spill.peek(key)
            else:
                host = None
            if host is None:
                return None
            return kv_transfer_plan(host)

        built = await loop.run_in_executor(None, plan)
        if built is None:
            return Response(404, b"prefix not cached here\n")
        manifest, blobs = built
        chunk_specs = manifest["chunks"]
        if start > len(chunk_specs):
            return Response(
                422,
                f"chunk must be in [0, {len(chunk_specs)}]\n".encode(),
            )
        from ..kvtier.handoff import encode_kv_manifest

        head = encode_kv_manifest(manifest)

        async def stream():
            yield head
            for spec in chunk_specs[start:]:
                yield blobs[spec["leaf"]][
                    spec["offset"]:spec["offset"] + spec["len"]
                ]

        return StreamingResponse(
            stream(), content_type="application/octet-stream"
        )

    async def _kv_pull(self, req: Request) -> Response:
        """``POST /v1/kv/pull {"tokens": [[...]], "from":
        "host:port"}``: fetch that prompt's KV entry from the named
        peer (digest-verified, one redial — kvtier/handoff.py) and
        inject it HOST-side into the spill tier; the next request
        for the prompt readmits it through the same reuse_admission
        path a locally-spilled entry takes. Any failure answers
        non-200 and caches nothing — the gateway falls back to a
        local prefill, so corrupt KV is never served."""
        pc = self.prefix_cache
        if pc is None or pc.spill is None:
            return Response(
                409, b"kv pull needs --prefix-cache and --kv-spill\n"
            )
        try:
            body = json.loads(req.body.decode() or "{}")
            tokens, _plen = _parse_token_rows(
                body, self.cfg.vocab_size, min_row_len=1
            )
            if len(tokens) != 1:
                raise ValueError("kv pull takes a single token row")
            peer = body.get("from", "")
            if not isinstance(peer, str) or ":" not in peer:
                raise ValueError("'from' must be \"host:port\"")
            address, _, port_raw = peer.rpartition(":")
            port = int(port_raw)
            if not address or not 0 < port < 65536:
                raise ValueError("'from' must be \"host:port\"")
        except (ValueError, KeyError, TypeError) as exc:
            return Response(422, f"{exc}\n".encode())
        import time as time_mod

        from ..kvtier.handoff import fetch_kv

        row = tokens[0]
        # a DRAIN-driven pull ("migrate": true) mints a trace so the
        # adoption is findable on this survivor's /v1/traces ring —
        # the gateway never saw this hop, so nobody else records it
        trace = (
            self._tracer.start(None, "kv_migrate")
            if body.get("migrate") else None
        )
        t0 = time_mod.monotonic()
        fetched = await fetch_kv(address, port, row)
        if fetched is None:
            if trace is not None:
                trace.add_span("kv_migrate", t0, time_mod.monotonic())
                trace.finish(502)
            return Response(502, b"kv fetch failed\n")
        host_tree, total_bytes = fetched
        loop = asyncio.get_event_loop()
        adopted = await loop.run_in_executor(
            None, pc.adopt_host, tuple(row), host_tree
        )
        if trace is not None:
            trace.add_span("kv_migrate", t0, time_mod.monotonic())
            trace.finish(200 if adopted else 507)
        if not adopted:
            return Response(
                507, b"kv entry refused (spill budget)\n"
            )
        return Response(
            200,
            json.dumps(
                {
                    "ok": True,
                    "bytes": int(total_bytes),
                    "ms": round(
                        (time_mod.monotonic() - t0) * 1e3, 3
                    ),
                }
            ).encode(),
            content_type="application/json",
        )

    async def _migrate_verb(self, req: Request) -> Response:
        """``POST /v1/migrate``: the drain-migration verb. With
        ``"targets"`` in the body, run an evacuation toward them (the
        operator-drain entry point — the FleetMember drain path calls
        :meth:`migrate_sessions` directly instead); without, answer a
        progress report including the landed fp -> target map, the
        POST-back a gateway or operator polls for completion. Served
        while draining by design — that is exactly when it is used."""
        try:
            body = json.loads(req.body.decode() or "{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, UnicodeDecodeError):
            return Response(422, b"body must be a JSON object\n")
        targets_raw = body.get("targets")
        if targets_raw is None:
            report = dict(self.migration)
            report["landed"] = {
                f"{fp:08x}": tid
                for fp, tid in self._migration_landed.items()
            }
            report["cumulative"] = dict(self._migration_counters)
            return Response(
                200, json.dumps(report).encode(),
                content_type="application/json",
            )
        if self.prefix_cache is None:
            return Response(409, b"migration needs --prefix-cache\n")
        if self.migration["active"]:
            return Response(409, b"migration already running\n")
        from ..kvtier.digest import parse_digest

        try:
            targets = []
            for t in targets_raw:
                _ver, fps = parse_digest(t.get("digest", ""))
                targets.append(
                    (str(t["id"]), str(t["address"]), int(t["port"]),
                     fps)
                )
            window = float(body.get("window_s", 5.0))
        except (KeyError, TypeError, ValueError, AttributeError) as exc:
            return Response(422, f"targets malformed: {exc}\n".encode())
        authority = str(body.get("authority", "")) or (
            f"{self.host}:{self.port}"
        )
        summary = await self.migrate_sessions(
            targets, window_s=window, authority=authority
        )
        return Response(
            200, json.dumps(summary).encode(),
            content_type="application/json",
        )

    def _instrumented(self, endpoint: str, handler):
        """Count + time every API request, under a per-request trace
        (adopting the caller's X-CP-Trace id when present); token
        accounting happens in the handlers themselves (they know the
        post-trim lengths)."""
        import time as time_mod

        # without a slot engine the ledger has no prefill/decode
        # authority; the handler inflight window stands in (coarse:
        # whole busy window -> decode), flipped at 0<->1 boundaries
        # only. With an engine, its boundary stamps rule and this
        # path stays off.
        compute_endpoint = endpoint in ("generate", "completions",
                                        "score")

        async def wrapped(req: Request) -> Response:
            # splice-safe ids only (tracing.safe_id): this id is
            # echoed in answer headers and digests verbatim
            inbound_id = tracing.safe_id(
                req.headers.get("x-cp-trace")
            ) or ""
            if (
                self.draining or self.role == "standby"
            ) and endpoint in ("generate", "completions"):
                # drain rejects NEW decode work only; reads (model,
                # score) stay up for the last consumers of this
                # replica, and everything already admitted runs to
                # completion. A standby refuses the same way: it is
                # warm capacity that has not been promoted — gateways
                # never route here, so this answers only direct
                # probes. The refusal still echoes the caller's
                # trace id — an answered-503 must be findable too.
                # A DRAINING answer is migration-aware: Retry-After
                # tracks evacuation progress, and once this request's
                # prefix has landed on a survivor the header names it
                # so the gateway repoints the pin instead of letting
                # the client re-prefill cold.
                self._m_requests.labels(endpoint, "503").inc()
                headers = {"Retry-After": "1"}
                if self.draining:
                    headers["Retry-After"] = self._drain_retry_after()
                    target = self._drain_migrated_to(req)
                    if target:
                        headers["X-CP-Migrated-To"] = target
                if inbound_id:
                    headers[tracing.TRACE_HEADER] = inbound_id
                body = (
                    b"draining\n" if self.draining else b"standby\n"
                )
                return Response(503, body, headers=headers)
            trace = self._tracer.start(inbound_id or None, endpoint)
            trace.stream_id = tracing.current_stream_id()
            token = tracing.activate(trace)
            t0 = time_mod.perf_counter()
            self._inflight += 1
            if (
                self.slot_engine is None and compute_endpoint
                and self._inflight == 1
            ):
                self.ledger.enter("decode")
            try:
                # the hook runs inside the inflight window: a request
                # parked in an injected delay must hold off a drain's
                # inflight==0 wait exactly like one inside the handler
                if self.chaos_hook is not None:
                    await self.chaos_hook(endpoint)
                resp = await handler(req)
            except Exception:
                # the HTTP layer turns this into a 500; the failing
                # (often slowest) requests are exactly what the
                # metrics exist to surface
                trace.finish(500)
                self._m_latency.labels(endpoint).observe(
                    time_mod.perf_counter() - t0
                )
                self._m_requests.labels(endpoint, "500").inc()
                raise
            finally:
                self._inflight -= 1
                if (
                    self.slot_engine is None and compute_endpoint
                    and self._inflight == 0
                ):
                    self.ledger.engine_idle()
                tracing.deactivate(token)
            resp.headers.setdefault(
                tracing.TRACE_HEADER, trace.trace_id
            )
            if not isinstance(resp, StreamingResponse):
                trace.finish(resp.status)
                resp.headers.setdefault(
                    tracing.DIGEST_HEADER, trace.digest()
                )
            # else: the stream plumbing owns the trace's tail — it
            # adds the relay span and ships the digest in the final
            # SSE frame (response headers are already gone by then)
            self._m_latency.labels(endpoint).observe(
                time_mod.perf_counter() - t0
            )
            self._m_requests.labels(endpoint, str(resp.status)).inc()
            return resp

        return wrapped

    def _mesh_info(self) -> Optional[Dict[str, int]]:
        """The device mesh the params actually live on (axis -> size),
        None for single-device serving — derived from the shardings,
        so it reports the truth regardless of how loading happened."""
        for leaf in jax.tree_util.tree_leaves(self.params):
            sharding = getattr(leaf, "sharding", None)
            mesh = getattr(sharding, "mesh", None)
            if mesh is not None and mesh.size > 1:
                return {
                    str(name): int(size)
                    for name, size in mesh.shape.items()
                }
        return None

    async def _model_info(self, _req: Request) -> Response:
        body = json.dumps(
            {
                "vocab_size": self.cfg.vocab_size,
                "d_model": self.cfg.d_model,
                "n_heads": self.cfg.n_heads,
                "n_kv_heads": self.cfg.kv_heads,
                "n_layers": self.cfg.n_layers,
                "max_len": self.max_len,
                "mesh": self._mesh_info(),
                "text": self.tokenizer is not None,
                "speculative": (
                    {
                        "draft_layers": self.draft_cfg.n_layers,
                        "speculate": self.speculate,
                        # draft/verify rides the step-program engine
                        # (not the legacy one-shot path); its
                        # dispatch/token counters fold into the
                        # goodput pair below
                        "engine": self.spec_engine.stats,
                    }
                    if self.draft_cfg is not None
                    else None
                ),
                "batching": {
                    "max_batch_rows": self.max_batch_rows,
                    "device_calls": self.batch_stats["calls"],
                    "rows": self.batch_stats["rows"],
                },
                "prefix_cache": (
                    {
                        "entries": self.prefix_cache.entries,
                        **self.prefix_cache.stats,
                    }
                    if self.prefix_cache is not None
                    else None
                ),
                # cache-aware routing surface: the versioned prefix
                # fingerprint digest (kvtier/digest.py) and the spill
                # tier's accounting; both None when disabled, so the
                # schema is stable across configurations
                "prefix_digest": (
                    self.prefix_cache.digest()
                    if self.prefix_cache is not None else None
                ),
                "kv_spill": (
                    self.prefix_cache.spill.snapshot()
                    if self.prefix_cache is not None
                    and self.prefix_cache.spill is not None
                    else None
                ),
                "slot_engine": (
                    self.slot_engine.stats
                    if self.slot_engine is not None else None
                ),
                # SSE streaming rides the slot engine's chunks
                "stream": self.slot_engine is not None,
                "draining": self.draining,
                "cp": (
                    {
                        "seq": int(self.cp_mesh.shape["seq"]),
                        "min_len": self.cp_min_len,
                    }
                    if self.cp_mesh is not None else None
                ),
            }
        ).encode()
        return Response(200, body, content_type="application/json")

    def _parse_logit_bias(self, raw: Any) -> Optional[Dict[int, float]]:
        """Delegates to the shared parser (modelcfg.parse_logit_bias)
        so the single-host server and the pod frontend accept exactly
        the same requests."""
        from .modelcfg import parse_logit_bias

        return parse_logit_bias(raw, self.cfg.vocab_size)

    def _parse_stops(self, raw: Any) -> List[List[int]]:
        """Delegates to the shared parser (modelcfg.parse_stop_ids)
        so the single-host server and the pod frontend accept exactly
        the same stop sequences."""
        from .modelcfg import parse_stop_ids

        return parse_stop_ids(raw, self.cfg.vocab_size)

    def _parse_sampling(
        self, body: Dict[str, Any], tokens: List[List[int]],
        prompt_len: int, default_eos: int = -1,
    ) -> Dict[str, Any]:
        """Validate the sampling/decode knobs shared by /v1/generate
        and /v1/completions. Raises ValueError for a 422."""
        p = {
            "max_new_requested": int(body.get("max_new_tokens", 16)),
            "temperature": float(body.get("temperature", 0.0)),
            "seed": int(body.get("seed", 0)),
            "top_k": int(body.get("top_k", 0)),
            "top_p": float(body.get("top_p", 0.0)),
            "eos_id": int(body.get("eos_id", default_eos)),
            "min_new": int(body.get("min_new_tokens", 0)),
            "presence": float(body.get("presence_penalty", 0.0)),
            "frequency": float(body.get("frequency_penalty", 0.0)),
            "logprobs": bool(body.get("logprobs", False)),
            "beam_width": int(body.get("beam_width", 0)),
            "length_penalty": float(body.get("length_penalty", 0.0)),
            "stop": self._parse_stops(body.get("stop")),
            "logit_bias": self._parse_logit_bias(
                body.get("logit_bias")
            ),
        }
        if p["logit_bias"] and p["beam_width"]:
            raise ValueError("logit_bias does not apply to beam search")
        p["n"] = int(body.get("n", 1))
        if not 1 <= p["n"] <= self.max_batch_rows:
            raise ValueError(
                f"n must be in [1, --max-batch-rows "
                f"{self.max_batch_rows}]"
            )
        if p["n"] > 1:
            if len(tokens) != 1:
                raise ValueError(
                    "n > 1 takes a single prompt row (it IS the "
                    "row multiplier)"
                )
            if p["beam_width"]:
                raise ValueError(
                    "n does not compose with beam search (beams "
                    "already return one best row)"
                )
        if p["beam_width"]:
            from ..models.beam import validate_beam_args

            if p["temperature"] > 0.0 or p["top_k"] or p["top_p"]:
                raise ValueError(
                    "beam search is deterministic; drop "
                    "temperature/top_k/top_p"
                )
            validate_beam_args(self.cfg, len(tokens), p["beam_width"])
            if p["beam_width"] > self.max_batch_rows:
                # beams tile the KV cache: one request must not exceed
                # the server's configured device-row budget
                raise ValueError(
                    f"beam_width capped at --max-batch-rows "
                    f"({self.max_batch_rows})"
                )
        if (not 0 <= p["top_k"] <= self.cfg.vocab_size
                or not 0.0 <= p["top_p"] <= 1.0):
            raise ValueError(
                f"top_k must be in [0, vocab {self.cfg.vocab_size}] "
                "and top_p in [0, 1]"
            )
        if p["eos_id"] >= self.cfg.vocab_size:
            raise ValueError(f"eos_id must be < vocab {self.cfg.vocab_size}")
        if not 0 <= p["min_new"] <= max(p["max_new_requested"], 0):
            raise ValueError(
                "min_new_tokens must be in [0, max_new_tokens]"
            )
        if p["min_new"] and p["beam_width"]:
            raise ValueError(
                "min_new_tokens does not apply to beam search"
            )
        if not (abs(p["presence"]) <= 100 and abs(p["frequency"]) <= 100):
            raise ValueError(
                "presence/frequency penalties must be in [-100, 100]"
            )
        if (p["presence"] or p["frequency"]) and p["beam_width"]:
            raise ValueError(
                "penalties do not apply to beam search"
            )
        if prompt_len + p["max_new_requested"] > self.max_len:
            raise ValueError(
                f"prompt_len + max_new_tokens exceeds max_len "
                f"{self.max_len}"
            )
        if p["max_new_requested"] < 1:
            raise ValueError("max_new_tokens must be >= 1")
        # bucket the compiled decode length to multiples of 16 so
        # per-request max_new variation can't churn the jit cache
        p["max_new"] = min(
            -(-p["max_new_requested"] // 16) * 16,
            self.max_len - prompt_len,
        )
        return p

    @staticmethod
    async def _timed_compute(trace, awaitable):
        """Record one coarse ``compute`` span around a non-slot decode
        path — the slot engine's requests get the finer
        slot_queue_wait/prefill/decode breakdown instead."""
        if trace is None:
            return await awaitable
        t0 = tracing.now()
        try:
            return await awaitable
        finally:
            trace.add_span("compute", t0, tracing.now())

    async def _dispatch_generate(
        self, tokens: List[List[int]], prompt_len: int, p: Dict[str, Any]
    ) -> List[List[int]]:
        """Route a validated generate request to the right decode
        strategy and return the (untrimmed) generated rows."""
        loop = asyncio.get_event_loop()
        in_exec = loop.run_in_executor
        trace = tracing.current_trace()
        timed = self._timed_compute
        if p["beam_width"]:
            return await timed(trace, in_exec(
                self._executor, serve_strategies.run_beam, self, tokens,
                p["max_new_requested"], p["beam_width"], p["eos_id"],
                p["length_penalty"],
            ))
        if (
            self.spec_engine is not None
            and p["temperature"] <= 0.0
            and p["min_new"] == 0
            and not p["presence"] and not p["frequency"]
            and not p["logit_bias"]
            and len(tokens) == 1
        ):
            # greedy single-sequence: draft-and-verify through the
            # speculative step program (the engine's emission is
            # already eos-capped, and the request's exact max_new
            # bounds it — no bucketed over-decode to trim). Output is
            # byte-identical to speculative_generate and therefore to
            # plain greedy decode. The engine stamps request-boundary
            # timings the trace converts to slot_queue_wait/prefill/
            # decode spans, same as the slot path below.
            timings: Optional[Dict[str, float]] = (
                {} if trace is not None else None
            )
            fut = self.spec_engine.submit(
                tokens[0], p["max_new_requested"],
                eos_id=p["eos_id"], seed=p["seed"],
                timings=timings,
            )
            rows = [await asyncio.wrap_future(fut)]
            if trace is not None:
                tracing.add_engine_spans(trace, timings)
            return rows
        if self.slot_engine is not None and len(tokens) == 1:
            # joins the running chunk loop at the next boundary; output
            # is already pad-trimmed at eos (the _trim downstream is
            # idempotent on it). The engine stamps request-boundary
            # timings the trace converts to slot_queue_wait/prefill/
            # decode spans — batched, nothing recorded per token.
            timings: Optional[Dict[str, float]] = (
                {} if trace is not None else None
            )
            fut = self.slot_engine.submit(
                tokens[0], p["max_new_requested"],
                temperature=p["temperature"], top_k=p["top_k"],
                top_p=p["top_p"], eos_id=p["eos_id"], seed=p["seed"],
                min_new=p["min_new"],
                presence_penalty=p["presence"],
                frequency_penalty=p["frequency"],
                logit_bias=p["logit_bias"],
                timings=timings,
            )
            rows = [await asyncio.wrap_future(fut)]
            if trace is not None:
                tracing.add_engine_spans(trace, timings)
            return rows
        if (
            self.cp_mesh is not None
            and len(tokens) == 1
            and prompt_len >= self.cp_min_len
        ):
            # long prompt: the prefill — the quadratic part — rings
            # over the seq axis; decode runs the normal scan
            return await timed(trace, in_exec(
                self._executor, serve_strategies.run_cp, self,
                tokens, p,
            ))
        if (
            self.prefix_cache is not None
            and len(tokens) == 1
            and (
                self.prefix_cache.match_len(tokens[0]) >= MIN_REUSE
                or self._batcher.idle()
            )
        ):
            # hit -> reuse; miss -> still seed the cache, but only when
            # nothing is queued (otherwise continuous batching would
            # have coalesced this request — don't trade batching
            # throughput for a cold-path seed)
            return await timed(trace, in_exec(
                self._executor, generate_with_prefix, self, tokens[0],
                p["max_new"], p["temperature"], p["top_k"], p["top_p"],
                p["eos_id"], p["seed"], p["min_new"], p["presence"],
                p["frequency"], p["logit_bias"],
            ))
        if (
            self.prefill_chunk > 0
            and len(tokens) == 1
            and prompt_len > self.prefill_chunk
        ):
            return await timed(trace, in_exec(
                self._executor, serve_strategies.run_chunked, self,
                tokens, prompt_len, p["max_new"], p["temperature"],
                p["top_k"], p["top_p"], p["eos_id"], p["seed"],
                p["min_new"], p["presence"], p["frequency"],
                p["logit_bias"],
            ))
        job = GenJob(
            rows=tokens, prompt_len=prompt_len, max_new=p["max_new"],
            temperature=p["temperature"], top_k=p["top_k"],
            top_p=p["top_p"], eos_id=p["eos_id"], seed=p["seed"],
            min_new=p["min_new"], presence=p["presence"],
            frequency=p["frequency"], logit_bias=p["logit_bias"],
            future=loop.create_future(),
        )
        return await timed(trace, self._batcher.submit(job))

    @staticmethod
    def _trim(
        generated: List[List[int]], max_new_requested: int, eos_id: int
    ) -> List[List[int]]:
        generated = [r[:max_new_requested] for r in generated]
        if eos_id >= 0:
            # trim each row at its first eos (inclusive); the model
            # emitted pad beyond it anyway
            generated = [
                row[: row.index(eos_id) + 1] if eos_id in row else row
                for row in generated
            ]
        return generated

    @staticmethod
    def _trim_stops(
        generated: List[List[int]], stops: List[List[int]]
    ) -> List[List[int]]:
        """Cut each row at the earliest occurrence of any stop
        sequence, EXCLUDING the stop itself (the OpenAI convention).
        Decode still ran to its compiled length — static shapes — so
        this is response shaping, not an early exit."""
        if not stops:
            return generated
        out = []
        for row in generated:
            cut = len(row)
            for stop in stops:
                n = len(stop)
                for i in range(0, min(cut, len(row) - n + 1)):
                    if row[i:i + n] == stop:
                        cut = min(cut, i)
                        break
            out.append(row[:cut])
        return out

    async def _generate(self, req: Request) -> Response:
        try:
            body = json.loads(req.body.decode() or "{}")
            tokens, prompt_len = _parse_token_rows(
                body, self.cfg.vocab_size, min_row_len=1
            )
            p = self._parse_sampling(body, tokens, prompt_len)
            stream = bool(body.get("stream", False))
            if p["n"] > 1:
                if stream:
                    # the client sent ONE row; blame the actual
                    # conflict, not the post-duplication row count
                    raise ValueError(
                        "n does not compose with stream (one SSE "
                        "stream carries one row)"
                    )
                # OpenAI's n: one prompt, n independent samples. Each
                # duplicated row draws from fold_in(seed, i) — the
                # server's existing per-row key convention — so the
                # samples differ under temperature (greedy duplicates
                # are identical by definition) and ride the batcher
                # as ONE device call.
                tokens = [list(tokens[0]) for _ in range(p["n"])]
            if stream:
                return self._generate_stream(tokens, p)
        except (ValueError, KeyError, TypeError) as exc:
            return Response(422, f"{exc}\n".encode())

        generated = await self._dispatch_generate(tokens, prompt_len, p)
        generated = self._trim(generated, p["max_new_requested"], p["eos_id"])
        generated = self._trim_stops(generated, p["stop"])
        self._m_tokens.inc(sum(len(r) for r in generated))
        payload: Dict[str, Any] = {"tokens": generated}
        if p["logprobs"]:
            loop = asyncio.get_event_loop()
            payload["logprobs"] = await loop.run_in_executor(
                self._executor, self._echo_logprobs, tokens, generated
            )
        return Response(
            200,
            json.dumps(payload).encode(),
            content_type="application/json",
        )

    def _generate_stream(
        self, tokens: List[List[int]], p: Dict[str, Any]
    ) -> "StreamingResponse":
        """SSE token streaming over the slot engine's chunk
        boundaries: each emitted delta becomes a ``data:`` event, the
        terminal event carries ``done``; concatenating the deltas
        byte-matches the non-streamed response's row (the engine's
        emission IS the post-trim output). A client disconnect sets
        the cancel event — the engine frees the slot at the next
        chunk boundary instead of decoding to the end."""
        if len(tokens) != 1:
            raise ValueError("stream serves a single row per request")
        return self._stream_response(tokens[0], p)

    def _stream_response(
        self,
        row: List[int],
        p: Dict[str, Any],
        delta_event=None,
        tail_events=None,
    ) -> "StreamingResponse":
        """Shared slot-engine SSE plumbing for the token and text
        streaming surfaces. ``delta_event(delta) -> dict`` shapes each
        event; ``tail_events() -> [dict]`` may append events before
        the terminal ``done`` (e.g. a UTF-8 decoder flush)."""
        if self.slot_engine is None:
            raise ValueError(
                "stream requires --slots (token streaming rides the "
                "slot engine's chunk boundaries)"
            )
        for knob, why in (
            ("logprobs", "echo logprobs need the full row"),
            ("beam_width", "beams have no incremental prefix"),
            ("stop", "stop sequences need whole-row trimming"),
        ):
            if p[knob]:
                raise ValueError(f"stream does not compose with "
                                 f"{knob} ({why})")
        if delta_event is None:
            delta_event = lambda d: {"tokens": d}  # noqa: E731
        if tail_events is None:
            tail_events = list  # noqa: E731 — no tail

        import threading as threading_mod

        loop = asyncio.get_event_loop()
        deltas: "asyncio.Queue" = asyncio.Queue()
        _DONE = object()
        cancel = threading_mod.Event()

        def on_tokens(delta: List[int]) -> None:  # worker thread
            loop.call_soon_threadsafe(deltas.put_nowait, delta)

        # the trace outlives the handler's contextvar window (the
        # relay runs after the handler returned), so the stream
        # plumbing holds the object directly
        trace = tracing.current_trace()
        timings: Optional[Dict[str, float]] = (
            {} if trace is not None else None
        )
        fut = self.slot_engine.submit(
            row, p["max_new_requested"],
            temperature=p["temperature"], top_k=p["top_k"],
            top_p=p["top_p"], eos_id=p["eos_id"], seed=p["seed"],
            min_new=p["min_new"],
            presence_penalty=p["presence"],
            frequency_penalty=p["frequency"],
            logit_bias=p["logit_bias"],
            on_tokens=on_tokens, cancel=cancel,
            timings=timings,
        )
        fut.add_done_callback(
            lambda _f: loop.call_soon_threadsafe(deltas.put_nowait, _DONE)
        )

        sent = [0]
        finished = [False]
        first_delta_at = [0.0]

        def finish() -> None:
            # runs on ANY stream end — completion, mid-stream
            # disconnect (generator finally), or a disconnect so
            # early the generator never started (StreamingResponse
            # close callback). Idempotent: both paths may fire.
            if finished[0]:
                return
            finished[0] = True
            cancel.set()  # the engine stops decoding this row
            self._m_tokens.inc(sent[0])
            if trace is not None:
                _finish_stream_trace()

        def _finish_stream_trace() -> None:
            # span conversion happens ONCE, here: engine boundary
            # stamps plus the relay window, then the trace files into
            # the ring (status 200 — an abandoned stream delivered
            # what it delivered; transport failure has no status)
            tracing.add_engine_spans(trace, timings)
            if first_delta_at[0]:
                trace.add_span(
                    "stream_relay", first_delta_at[0], tracing.now(),
                    events=sent[0],
                )
            trace.finish(200)

        def sse(payload: Dict[str, Any]) -> bytes:
            return b"data: " + json.dumps(payload).encode() + b"\n\n"

        async def events():
            try:
                while True:
                    delta = await deltas.get()
                    if delta is _DONE:
                        break
                    if trace is not None and not first_delta_at[0]:
                        first_delta_at[0] = tracing.now()
                    sent[0] += len(delta)
                    yield sse(delta_event(delta))
                for extra in tail_events():
                    yield sse(extra)
                done: Dict[str, Any] = {"done": True, "count": sent[0]}
                if trace is not None:
                    # the final frame is the stream's digest channel
                    # (response headers are long gone): the gateway
                    # splices these spans into its own timeline
                    finish()
                    done["trace"] = trace.trace_id
                    done["spans"] = trace.digest()
                yield sse(done)
            finally:
                finish()

        return StreamingResponse(events(), close=finish)

    async def _completions(self, req: Request) -> Response:
        """Text in/out over the built-in byte-level tokenizer: encode
        the prompt, run the exact same decode dispatch as
        /v1/generate, decode the generated ids back to text. eos
        defaults to the tokenizer's EOS so generation stops naturally;
        pass "eos_id": -1 to disable. "stop" takes STRINGS here (a
        single string or a list); they are byte-encoded and applied
        as token-level stop sequences, excluded from the output."""
        try:
            body = json.loads(req.body.decode() or "{}")
            prompt = body.get("prompt")
            if not isinstance(prompt, str) or not prompt:
                raise ValueError("'prompt' must be a non-empty string")
            row = self.tokenizer.encode(prompt)
            if len(row) >= self.max_len:
                raise ValueError(
                    f"prompt encodes to {len(row)} ids; max_len is "
                    f"{self.max_len}"
                )
            from .modelcfg import parse_stop_strings

            stop_raw = parse_stop_strings(body.pop("stop", None))
            if stop_raw is not None:
                body["stop"] = [
                    self.tokenizer.encode(s, bos=False)
                    for s in stop_raw
                ]
            p = self._parse_sampling(
                body, [row], len(row), default_eos=self.tokenizer.EOS
            )
            if p["n"] > 1:
                raise ValueError(
                    "n returns token rows; use /v1/generate"
                )
            if bool(body.get("stream", False)):
                return self._completions_stream(row, p)
        except (ValueError, KeyError, TypeError) as exc:
            return Response(422, f"{exc}\n".encode())

        generated = await self._dispatch_generate([row], len(row), p)
        generated = self._trim(generated, p["max_new_requested"], p["eos_id"])
        generated = self._trim_stops(generated, p["stop"])
        self._m_tokens.inc(len(generated[0]))
        return Response(
            200,
            json.dumps(
                {
                    "text": self.tokenizer.decode(generated[0]),
                    "tokens": generated[0],
                }
            ).encode(),
            content_type="application/json",
        )

    def _completions_stream(
        self, row: List[int], p: Dict[str, Any]
    ) -> "StreamingResponse":
        """Text SSE over the same slot-chunk plumbing: each event
        carries the delta's ids AND the text they decode to, with
        UTF-8 partial-byte holdback (text.stream_decoder).
        Concatenated event text equals the non-streamed ``text``;
        concatenated ids equal its ``tokens``."""
        from .text import stream_decoder

        delta_event, tail_events = stream_decoder(self.tokenizer)
        return self._stream_response(
            row, p, delta_event=delta_event, tail_events=tail_events
        )

    def _ensure_score_fn(self) -> None:
        if self._score_fn is not None:
            return
        from .modelcfg import score_logprobs_fn

        self._score_fn = jax.jit(score_logprobs_fn(self.cfg))

    def _echo_logprobs(
        self,
        prompts: List[List[int]],
        generated: List[List[int]],
    ) -> List[List[float]]:
        """Per-token logprobs of the TRIMMED generated ids, via one
        teacher-forced pass over prompt+generated. Decode is bit-equal
        to the forward (tested invariant), so these are exactly the
        probabilities the sampler saw — and the approach works
        uniformly across every decode path (batcher, slots, prefix,
        speculative, beam) with no decode changes. With --kv-int8 the
        echo is approximate (the scorer runs full-precision while
        decode read a quantized KV cache; parity there is ~5e-2, not
        bitwise). Rows pad to a 16-multiple width (capped at max_len)
        so arbitrary trimmed lengths cannot compile a fresh scoring
        program per request — causal attention makes the extra pad
        positions free."""
        self._ensure_score_fn()
        rows = [p + g for p, g in zip(prompts, generated)]
        width = min(-(-max(len(r) for r in rows) // 16) * 16,
                    self.max_len)
        padded = [r + [0] * (width - len(r)) for r in rows]
        picked = jax.device_get(
            self._score_fn(self.params, jnp.asarray(padded, jnp.int32))
        ).astype(float)
        out: List[List[float]] = []
        for row_lp, prompt, gen in zip(picked, prompts, generated):
            # lp[i] scores token i+1 of the padded row; generated
            # token j sits at padded index len(prompt)+j
            start = len(prompt) - 1
            out.append([
                round(float(x), 6)
                for x in row_lp[start:start + len(gen)]
            ])
        return out

    async def _score(self, req: Request) -> Response:
        """Teacher-forced per-token logprobs of the given sequences —
        the standard scoring/perplexity endpoint (no sampling)."""
        try:
            body = json.loads(req.body.decode() or "{}")
            tokens, row_len = _parse_token_rows(
                body, self.cfg.vocab_size, min_row_len=2
            )
            if row_len > self.max_len:
                raise ValueError(f"row length exceeds max_len {self.max_len}")
        except (ValueError, KeyError, TypeError) as exc:
            return Response(422, f"{exc}\n".encode())

        self._ensure_score_fn()

        def run() -> Any:
            toks = jnp.asarray(tokens, jnp.int32)
            picked = self._score_fn(self.params, toks)
            picked = jax.device_get(picked).astype(float)
            return picked

        loop = asyncio.get_event_loop()
        picked = await loop.run_in_executor(self._executor, run)
        return Response(
            200,
            json.dumps(
                {
                    "logprobs": [[round(float(x), 6) for x in row]
                                 for row in picked],
                    "sums": [round(float(row.sum()), 6) for row in picked],
                }
            ).encode(),
            content_type="application/json",
        )

    # -- lifecycle ------------------------------------------------------

    @property
    def inflight(self) -> int:
        """Requests still being served: handler-held requests plus
        slot-engine rows still decoding (a streamed generation's
        handler returns immediately; its row lives in the engine).
        The double count while a buffered request waits on its slot
        future only makes drain-waiting conservative."""
        n = self._inflight
        for engine in (self.slot_engine, self.spec_engine):
            if engine is not None:
                stats = engine.stats
                n += stats["active"] + stats["queued"]
        return n

    @property
    def occupancy(self) -> float:
        """Fraction of decode capacity in use, the autoscaling
        signal: (active + queued slot-engine rows) / slots, so queued
        work pushes it past 1.0 — a replica can be *over*-subscribed,
        and a scaler must see that. Without a slot engine the handler
        count stands in (each buffered request is one unit)."""
        if self.slot_engine is not None:
            stats = self.slot_engine.stats
            return (stats["active"] + stats["queued"]) / max(
                1, stats["slots"]
            )
        return float(self._inflight)

    def kv_note(self) -> str:
        """The ``kv=`` heartbeat field's VALUE (the name is owned by
        ``fleet/notes.py``): the prefix cache's reuse counters,
        ``hits,misses,tokens_reused,spilled,readmitted``. Empty
        without a prefix cache, so fleets that don't reuse pay zero
        note bytes."""
        pc = self.prefix_cache
        if pc is None:
            return ""
        s = pc.stats
        return (
            f"{s['hits']},{s['misses']},{s['tokens_reused']},"
            f"{s['spilled']},{s['readmitted']}"
        )

    def prefix_digest_note(self) -> str:
        """The ``pd=`` heartbeat field's value: the prefix
        fingerprint digest the gateway's cache-aware routing scores
        against. Empty without a prefix cache or before the first
        digest build."""
        pc = self.prefix_cache
        if pc is None:
            return ""
        return pc.digest() or ""

    def goodput_note(self) -> str:
        """The device-time ledger's heartbeat field (``gp=`` —
        cumulative per-stage seconds + the dispatches/token pair),
        appended by FleetMember the same duck-typed way ``kv_note``
        is. Always present: a replica with zero reuse still has a
        badput story to tell, and the gateway's fleet ledger must
        fold in every member from its very first beat."""
        dispatches, tokens_out = self._decode_counters()
        return self.ledger.note(dispatches, tokens_out)

    # -- drain migration ------------------------------------------------

    async def migrate_sessions(
        self,
        targets: List[Any],
        window_s: float = 5.0,
        authority: str = "",
    ) -> Dict[str, Any]:
        """Evacuate this replica's cached prefixes to the survivors
        before a drain deregisters it: plan deterministically
        (kvtier.plan_migration — digest-coldest target, fp-family
        affinity, warm fps land with zero bytes), then push each cold
        entry inside the bounded window by POSTing a pull instruction
        at its target (the handoff wire in reverse; the target
        ``fetch_kv``s from ``authority`` — this replica's advertised
        host:port — and adopts via the same ``reuse_admission`` path).
        Every failure is a COUNTED fallback to today's re-prefill
        behavior, never an error: a dead target or poisoned chunk
        bumps ``failed``, window expiry bumps ``timeout`` for each
        un-pushed entry, and the drain proceeds regardless.

        ``targets`` is a list of ``(instance_id, address, port,
        fingerprint_set)`` tuples (a survivor's advertised ``pd=``
        digest, parsed). Returns the migration summary dict."""
        import time as time_mod

        pc = self.prefix_cache
        m = self.migration
        if pc is None or not targets or m["active"]:
            return dict(m)
        from ..kvtier.handoff import plan_migration, push_kv

        loop = asyncio.get_event_loop()
        keys = await loop.run_in_executor(None, pc.export_keys)
        plan = plan_migration(
            keys, [(t[0], t[3]) for t in targets]
        )
        addr = {t[0]: (t[1], int(t[2])) for t in targets}
        m.update(
            active=True, total=len(plan), done=0, failed=0,
            timeout=0, window_s=float(window_s),
            started_at=time_mod.monotonic(),
        )
        self._migration_counters["total"] += len(plan)
        deadline = m["started_at"] + max(0.0, float(window_s))
        bytes_moved = 0
        try:
            for entry in plan:
                if time_mod.monotonic() >= deadline:
                    left = m["total"] - m["done"] - m["failed"]
                    m["timeout"] += left
                    self._migration_counters["timeout"] += left
                    log.warning(
                        "serve: migrate window expired with %d "
                        "entries unmoved", left,
                    )
                    break
                if entry["warm"]:
                    # already warm on the survivor: landed with zero
                    # bytes moved, but the pin still repoints
                    m["done"] += 1
                    self._migration_counters["done"] += 1
                    self._record_landing(entry["fp"], entry["target"])
                    continue
                host, port = addr[entry["target"]]
                got = await push_kv(
                    host, port, list(entry["key"]), authority,
                    read_timeout=max(
                        1.0, deadline - time_mod.monotonic()
                    ),
                )
                if got is None:
                    m["failed"] += 1
                    self._migration_counters["failed"] += 1
                else:
                    bytes_moved += got
                    m["done"] += 1
                    self._migration_counters["done"] += 1
                    self._record_landing(entry["fp"], entry["target"])
        finally:
            m["active"] = False
        summary = dict(m)
        summary["bytes"] = bytes_moved
        log.info(
            "serve: migration moved %d/%d entries (%d bytes, "
            "%d failed, %d timed out)",
            m["done"], m["total"], bytes_moved, m["failed"],
            m["timeout"],
        )
        return summary

    def _record_landing(self, fp: int, target: str) -> None:
        landed = self._migration_landed
        landed[fp] = target
        landed.move_to_end(fp)
        while len(landed) > 256:
            landed.popitem(last=False)

    def migrate_note(self) -> str:
        """The ``mg=`` heartbeat field's value (the name is owned by
        ``fleet/notes.py``): cumulative migration counters plus the
        most recent fp -> target landings, which the gateway uses to
        repoint sticky pins as sessions land. Empty until a
        migration has ever run — replicas that never drain pay zero
        note bytes."""
        c = self._migration_counters
        if not c["total"] and not self.migration["active"]:
            return ""
        from ..kvtier.digest import encode_migration_note

        landed = list(self._migration_landed.items())
        landed.reverse()  # most-recent-first survives truncation
        return encode_migration_note(
            c["done"], c["total"], c["failed"], c["timeout"],
            bool(self.migration["active"]), landed,
        )

    def _drain_retry_after(self) -> str:
        """Retry-After for a drain 503, derived from migration
        progress: the observed per-entry pace extrapolated over what
        is left, capped by the remaining window — a polite-retry
        client comes back right as its session lands warm instead of
        after a fixed beat."""
        import time as time_mod

        m = self.migration
        if not m["active"] or m["total"] <= 0:
            return "1"
        elapsed = max(0.0, time_mod.monotonic() - m["started_at"])
        settled = m["done"] + m["failed"]
        if settled <= 0:
            remaining = float(m["window_s"])
        else:
            remaining = elapsed * (m["total"] - settled) / settled
        remaining = min(
            remaining, max(0.0, float(m["window_s"]) - elapsed)
        )
        return str(max(1, min(30, int(remaining + 0.999))))

    def _drain_migrated_to(self, req: Request) -> str:
        """The survivor instance id this 503'd request's prefix has
        already landed on, or "" — advertised in X-CP-Migrated-To so
        the gateway repoints the pin instead of re-prefilling cold.
        Tolerant: any unparseable body simply gets no header."""
        if not self._migration_landed:
            return ""
        from ..kvtier.digest import prefix_fingerprint

        try:
            body = json.loads(req.body.decode() or "{}")
            rows = body.get("tokens")
            if (isinstance(rows, list) and rows
                    and isinstance(rows[0], list)):
                row = [int(t) for t in rows[0]]
            elif (self.tokenizer is not None
                  and isinstance(body.get("prompt"), str)):
                row = self.tokenizer.encode(body["prompt"])
            else:
                return ""
            fp = prefix_fingerprint(row)
        except (ValueError, TypeError, AttributeError,
                UnicodeDecodeError):
            return ""
        if fp is None:
            return ""
        return self._migration_landed.get(fp, "")

    def enter_maintenance(self) -> None:
        """Start draining: health 503, new generate/completions 503 +
        Retry-After, in-flight work (including running slot-engine
        rows) finishes. Idempotent."""
        if not self.draining:
            log.info("serve: entering maintenance (draining)")
            # ledger: from here until exit, every second is drain
            # badput — capacity leaving the fleet, the in-flight rows
            # it still finishes included (they are the drain's cost)
            self.ledger.set_override("drain")
        self.draining = True

    def exit_maintenance(self) -> None:
        """Stop draining and accept traffic again. Idempotent."""
        if self.draining:
            log.info("serve: exiting maintenance")
            self.ledger.clear_override()
        self.draining = False

    def _warmup_fingerprint(self) -> str:
        """The warm-bucket marker key: everything that shapes this
        server's warmup program set (modelcfg.warmup_fingerprint)."""
        from .modelcfg import warmup_fingerprint

        engine = self.slot_engine
        return warmup_fingerprint(
            self.cfg, self.max_len,
            slots=getattr(engine, "slots", 0) if engine else 0,
            slot_chunk=getattr(engine, "chunk", 0) if engine else 0,
            # the fused window K shapes the engine's compiled program
            # set: a marker written at K=1 must never skip the fused
            # program a K=4 launch needs (PR 13's compile-cache skip
            # stays correct only if K is part of the identity)
            slot_window=(
                getattr(engine, "window", 1) if engine else 0
            ),
            draft_layers=(
                self.draft_cfg.n_layers
                if self.draft_cfg is not None else 0
            ),
            speculate=self.speculate,
        )

    def compile_cache_note(self) -> str:
        """The ``cc=`` heartbeat field's value (the name is owned by
        ``fleet/notes.py``): this replica's compile-cache
        dir + warm-marker digest, so same-host launches adopt the dir
        and skip warm buckets. Computed ONCE at warmup end (the
        marker only changes there) and cached — a heartbeat must
        never pay marker file I/O on the serving loop. Empty without
        a cache dir — fleets not sharing a cache pay zero note
        bytes."""
        return self._compile_cache_note

    async def warmup(self) -> None:
        """Compile the default-shaped programs before reporting healthy.

        Requests with other prompt lengths still compile on first use
        (shapes are static); the bucketed max_new keeps that churn
        bounded. With a shared compile cache dir configured, buckets
        a previous same-shaped process already marked warm are
        SKIPPED — the XLA disk cache holds their executables, so the
        first live request pays a fast cache load instead of a
        compile, and this launch's ``compile_warmup`` seconds
        collapse to near zero (the cold-start-collapse lever)."""
        from ..models.decode import generate

        # ledger: everything from here until ready flips — XLA
        # compiles AND the dummy slot-engine request driving them —
        # is compile_warmup, stamped via an override so the engine's
        # own prefill/decode boundary stamps can't claim it. Costed
        # BEFORE /health goes 200: the very first scrape of a
        # scale-up replica already shows its compile badput.
        self.ledger.set_override("compile_warmup")
        # chaos seam: an injected slow boot parks HERE, inside the
        # compile_warmup attribution window — the fault the standby
        # pool exists to mask
        if self.chaos_hook is not None:
            await self.chaos_hook("warmup")
        loop = asyncio.get_event_loop()
        fingerprint = ""
        warm: set = set()
        if self.compile_cache_dir:
            from .modelcfg import load_warm_buckets

            fingerprint = self._warmup_fingerprint()
            warm = await loop.run_in_executor(
                None, load_warm_buckets,
                self.compile_cache_dir, fingerprint,
            )

        def run() -> None:
            for prompt_len in (4, 16):
                if prompt_len + 16 > self.max_len:
                    continue
                if f"p{prompt_len}" in warm:
                    continue  # a same-shape process already compiled it
                prompt = jnp.zeros((1, prompt_len), jnp.int32)
                generate(
                    self.params, prompt, self.cfg, max_new_tokens=16,
                    max_len=self.max_len,
                )
                if self.draft_params is not None and prompt_len == 4:
                    # the DEFAULT path for greedy traffic: one shared
                    # rule for which spec programs must compile inside
                    # the grace (models/speculative.py)
                    from ..models.speculative import warm_speculative

                    warm_speculative(
                        self.params, self.draft_params, self.cfg,
                        self.draft_cfg, self.speculate, self.max_len,
                    )

        await loop.run_in_executor(self._executor, run)
        if self.slot_engine is not None and "slots" not in warm:
            # one dummy request through the engine compiles its whole
            # program set (standalone prefill, first-sample, insert,
            # the (S, chunk) chunk program and — with window > 1 —
            # the fused (S, chunk, K) window: max_new = chunk+2
            # leaves one token past the admission round, so the
            # second cycle dispatches fused) so the first live
            # request doesn't stall on multi-second compilation
            # behind a 200 /health
            engine = self.slot_engine
            warm_new = engine.chunk + (
                2 if engine.window > 1 else 1
            )
            fut = engine.submit(
                [0] * WARMUP_PROMPT_LEN, max_new=warm_new,
            )
            await asyncio.wrap_future(fut)
        if self.spec_engine is not None and "spec" not in warm:
            # same discipline for the speculative engine: one dummy
            # generation compiles its admission glue (the per-k
            # draft/verify variants compiled in warm_speculative
            # above, inside the same grace)
            spec_new = min(
                self.speculate + 2, self.max_len - WARMUP_PROMPT_LEN
            )
            if spec_new >= 1:
                fut = self.spec_engine.submit(
                    [0] * WARMUP_PROMPT_LEN, max_new=spec_new,
                )
                await asyncio.wrap_future(fut)
        if self.compile_cache_dir:
            from .modelcfg import (
                compile_cache_note,
                mark_warm_buckets,
            )

            buckets = {"p4", "p16"}
            if self.slot_engine is not None:
                buckets.add("slots")
            if self.spec_engine is not None:
                buckets.add("spec")
            await loop.run_in_executor(
                None, mark_warm_buckets,
                self.compile_cache_dir, fingerprint, buckets,
            )
            # the advertisement heartbeats will carry from now on —
            # digested off the marker just written, off-loop, once
            self._compile_cache_note = await loop.run_in_executor(
                None, compile_cache_note, self.compile_cache_dir
            )
        # warmup attribution closes here, and the serving clock opens
        # in ``idle`` — both before ready flips, so no wall-second
        # between "compiled" and "first scrape" is misattributed
        self.ledger.clear_override()
        self.ledger.enter("idle")
        self.ready = True
        log.info(
            "serve: default shapes warm%s; %s",
            " (marker-skipped)" if warm else "",
            "standing by" if self.role == "standby"
            else "accepting traffic",
        )

    async def run(self) -> None:
        await self._server.start_tcp(self.host, self.port)
        self.port = self._server.bound_port or self.port
        self._batcher.start()
        self._loop_probe.start()
        log.info("serve: listening on %s:%d", self.host, self.port)
        await self.warmup()

    async def stop(self) -> None:
        self.ledger.freeze()
        self._loop_probe.stop()
        await self._batcher.stop()
        for engine in (self.slot_engine, self.spec_engine):
            if engine is not None:
                # joins the worker thread; run off-loop so in-flight
                # dispatches can't block the event loop
                await asyncio.get_event_loop().run_in_executor(
                    None, engine.stop
                )
        await self._server.stop()

    async def abort(self) -> None:
        """Test-only (chaos harness): die like SIGKILL. The listener
        and every live connection drop FIRST — in-flight clients see
        resets, exactly as if the process vanished — and only then are
        the decode threads reaped so the test process doesn't leak
        them. No drain, no deregistration: a FleetMember's catalog
        record is left to decay critical by TTL expiry, which is the
        crash signature gateways must route around."""
        self.ready = False
        self.ledger.freeze()
        self._loop_probe.stop()
        await self._server.abort()
        await self._batcher.stop()
        for engine in (self.slot_engine, self.spec_engine):
            if engine is not None:
                await asyncio.get_event_loop().run_in_executor(
                    None, engine.stop
                )


if __name__ == "__main__":
    raise SystemExit(main())
