"""Continuous batching for the inference server.

Requests queue here and the batcher coalesces whatever accumulated
while the device was busy into ONE device call with per-row sampling
params. Per-row PRNG keys derive from each request's own seed, so a
request's output never depends on what it happened to be batched with
(tested). Split out of serve.py (round-2 review: one module per
serving concern).
"""
from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..models.decode import generate
from ..utils.tasks import spawn


@dataclass
class GenJob:
    """One /v1/generate request waiting in the batcher queue."""

    rows: List[List[int]]
    prompt_len: int
    max_new: int  # bucketed compiled length
    temperature: float
    top_k: int
    top_p: float
    eos_id: int
    seed: int
    min_new: int = 0
    presence: float = 0.0
    frequency: float = 0.0
    # one {token_id: bias} dict applied to every row of this job
    # (requests are single-job; rows share the request's bias)
    logit_bias: Optional[dict] = None
    future: "asyncio.Future[List[List[int]]]" = field(repr=False, default=None)


class Batcher:
    """Owns the request queue and the drain loop; one device call per
    compatible group (same prompt length and compiled decode length)."""

    def __init__(self, params: Any, cfg: Any, max_len: int,
                 max_batch_rows: int, executor: Any) -> None:
        self.params = params
        self.cfg = cfg
        self.max_len = max_len
        self.max_batch_rows = max_batch_rows
        self._executor = executor
        self.queue: "asyncio.Queue[GenJob]" = asyncio.Queue()
        self._task: Optional["asyncio.Task[None]"] = None
        self.stats = {"calls": 0, "rows": 0}  # device-call count

    def idle(self) -> bool:
        return self.queue.empty()

    async def submit(self, job: GenJob) -> List[List[int]]:
        await self.queue.put(job)
        return await job.future

    def start(self) -> None:
        self._task = spawn(self._loop(), name="serve-batcher")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            # fail anything still queued so no handler awaits forever
            while not self.queue.empty():
                job = self.queue.get_nowait()
                if not job.future.done():
                    job.future.set_exception(RuntimeError("server stopping"))

    async def _loop(self) -> None:
        """Drain whatever requests queued while the device was busy,
        group the compatible ones, run each group as one device call."""
        carry: Optional[GenJob] = None
        try:
            while True:
                first = (
                    carry if carry is not None else await self.queue.get()
                )
                carry = None
                jobs = [first]
                rows = len(first.rows)
                # cap by ROW count (a request may carry several rows);
                # a job that would overflow carries to the next drain
                while rows < self.max_batch_rows and not self.queue.empty():
                    nxt = self.queue.get_nowait()
                    if rows + len(nxt.rows) > self.max_batch_rows:
                        carry = nxt
                        break
                    jobs.append(nxt)
                    rows += len(nxt.rows)
                groups: Dict[Any, List[GenJob]] = {}
                for job in jobs:
                    groups.setdefault(
                        (job.prompt_len, job.max_new), []
                    ).append(job)
                for group in groups.values():
                    await self._run_group(group)
        finally:
            # cancellation with a carried-over job in hand: fail it so
            # its handler doesn't await forever
            if carry is not None and not carry.future.done():
                carry.future.set_exception(RuntimeError("server stopping"))

    async def _run_group(self, jobs: List[GenJob]) -> None:
        def run() -> List[List[int]]:
            rows: List[List[int]] = []
            temps: List[float] = []
            ks: List[int] = []
            ps: List[float] = []
            eoss: List[int] = []
            mins: List[int] = []
            press: List[float] = []
            freqs: List[float] = []
            biases: List[Optional[dict]] = []
            keys = []
            for job in jobs:
                base = jax.random.PRNGKey(job.seed)
                for i, r in enumerate(job.rows):
                    rows.append(r)
                    temps.append(job.temperature)
                    ks.append(job.top_k)
                    ps.append(job.top_p)
                    eoss.append(job.eos_id)
                    mins.append(job.min_new)
                    press.append(job.presence)
                    freqs.append(job.frequency)
                    biases.append(job.logit_bias)
                    keys.append(jax.random.fold_in(base, i))
            # bucket the batch dim to powers of two so concurrency
            # spikes can't compile one program per row count
            target = 1
            while target < len(rows):
                target *= 2
            pad_rows = target - len(rows)
            for _ in range(pad_rows):
                rows.append([0] * len(rows[0]))
                temps.append(0.0)
                ks.append(0)
                ps.append(0.0)
                eoss.append(-1)
                mins.append(0)
                press.append(0.0)
                freqs.append(0.0)
                biases.append(None)
                keys.append(jax.random.PRNGKey(0))
            out = generate(
                self.params,
                jnp.asarray(rows, jnp.int32),
                self.cfg,
                max_new_tokens=jobs[0].max_new,
                max_len=self.max_len,
                temperature=temps,
                rng=jnp.stack(keys),
                top_k=ks,
                top_p=ps,
                eos_id=eoss,
                min_new_tokens=mins,
                presence_penalty=press,
                frequency_penalty=freqs,
                logit_bias=(
                    biases if any(b for b in biases) else None
                ),
            )
            n_real = len(rows) - pad_rows
            return jax.device_get(out[:n_real]).tolist()

        loop = asyncio.get_event_loop()
        self.stats["calls"] += 1
        self.stats["rows"] += sum(len(j.rows) for j in jobs)
        try:
            outs = await loop.run_in_executor(self._executor, run)
        except asyncio.CancelledError:
            # batcher cancelled mid-call (stop()): fail the waiters so
            # their handlers don't hang forever, then propagate
            for job in jobs:
                if not job.future.done():
                    job.future.set_exception(RuntimeError("server stopping"))
            raise
        except Exception as exc:  # surface as a per-request 500
            for job in jobs:
                if not job.future.done():
                    job.future.set_exception(exc)
            return
        i = 0
        for job in jobs:
            if not job.future.done():  # waiter may have been cancelled
                job.future.set_result(outs[i:i + len(job.rows)])
            i += len(job.rows)
