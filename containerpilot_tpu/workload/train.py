"""A supervised training process: the demo workload.

This is what a job's ``exec`` points at in a TPU deployment — one
training process per host, supervised by containerpilot-tpu:

- writes a progress file every step (``--progress-file``), which the
  job's health check probes (e.g. ``exec: "find /run/progress -newermt
  '-30 seconds'"``) so a hung training loop goes catalog-critical;
- posts step/loss metrics to the supervisor's control socket
  (``--control-socket``) for the Prometheus endpoint;
- trains the flagship transformer on synthetic data over the local
  (data, model) mesh;
- handles preemption gracefully: on SIGTERM (TPU maintenance events,
  the supervisor's stopTimeout window, `docker stop`) it finishes the
  in-flight step, saves a checkpoint, and exits 0 — the supervisor's
  restart brings it back at exactly that step. Single-process only:
  a multi-process pod cannot checkpoint from one signal handler
  (orbax saves hold cross-process barriers), so there the process
  exits cleanly and the pod resumes from the last periodic
  checkpoint.

Run it stand-alone:
    python -m containerpilot_tpu.workload.train --steps 20
or under the supervisor (see examples/training-pod.json5).
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import time

import jax
import jax.numpy as jnp


def main() -> int:
    from .modelcfg import enable_compile_cache

    enable_compile_cache()
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=256)
    parser.add_argument("--d-model", type=int, default=256)
    parser.add_argument("--n-layers", type=int, default=2)
    parser.add_argument("--n-heads", type=int, default=4)
    parser.add_argument("--n-kv-heads", type=int, default=0,
                        help="GQA kv heads (0 = full multi-head)")
    parser.add_argument("--window", type=int, default=0,
                        help="sliding-window attention: each position "
                        "attends the last N positions only (0 = full "
                        "causal); bounds attention FLOPs and the "
                        "serving KV cache")
    parser.add_argument("--loss-chunk", type=int, default=0,
                        help="stream the vocab projection + softmax "
                        "over sequence chunks of N instead of "
                        "materializing [batch, seq, vocab] logits "
                        "(0 = whole-logits loss)")
    parser.add_argument("--moe-experts", type=int, default=0,
                        help="switch-MoE experts (0 = dense MLP)")
    parser.add_argument("--moe-capacity", type=float, default=0.0,
                        help="capacity factor for bounded expert compute "
                        "during training (0 = drop-free routing)")
    parser.add_argument("--vocab", type=int, default=1024)
    parser.add_argument("--data-dir", default="",
                        help="token shards (shard_*.npy; workload/data.py)"
                        " — default is synthetic data")
    parser.add_argument("--eval-every", type=int, default=0,
                        help="report held-out loss every N steps "
                        "(requires --data-dir and --eval-holdout)")
    parser.add_argument("--eval-holdout", type=int, default=0,
                        help="windows reserved from the shard tail as "
                        "the eval split")
    parser.add_argument("--profile-dir", default="",
                        help="capture an XLA/TPU profiler trace of steps "
                        "2..2+profile-steps into this dir (view with "
                        "tensorboard or xprof)")
    parser.add_argument("--profile-steps", type=int, default=3)
    parser.add_argument("--pipeline-stages", type=int, default=0,
                        help="GPipe pipeline stages (0 = no pipeline); "
                        "n_layers must divide by it")
    parser.add_argument("--microbatches", type=int, default=4,
                        help="pipeline microbatches (batch must divide)")
    parser.add_argument("--tensor-parallel", type=int, default=0,
                        help="model-axis size when pipelining "
                        "(0 = all remaining devices go to data)")
    parser.add_argument("--progress-file", default="")
    parser.add_argument("--control-socket", default="")
    parser.add_argument("--learning-rate", type=float, default=3e-4)
    parser.add_argument("--warmup-steps", type=int, default=0,
                        help="linear lr warmup from 0 over N steps")
    parser.add_argument("--decay-steps", type=int, default=0,
                        help="cosine-decay the lr to 10%% of peak over "
                        "N post-warmup steps (0 = constant)")
    parser.add_argument("--lora-rank", type=int, default=0,
                        help="LoRA fine-tuning: train rank-R adapters "
                        "on attention q/v with the base frozen "
                        "(0 = full training)")
    parser.add_argument("--base-checkpoint-dir", default="",
                        help="with --lora-rank: frozen base weights "
                        "from this checkpoint (params-only restore); "
                        "default is a fresh init (demo)")
    parser.add_argument("--zero1", action="store_true",
                        help="ZeRO-1: shard adam moments over the data "
                        "axis; optimizer memory per device drops by "
                        "the data-parallel factor")
    parser.add_argument("--ema-decay", type=float, default=0.0,
                        help="maintain an EMA shadow of the params "
                        "(e.g. 0.999); eval and the checkpoint carry "
                        "it; 0 = off")
    parser.add_argument("--fsdp", action="store_true",
                        help="FSDP (ZeRO-3): shard params, grads, AND "
                        "moments over the data axis; per-device model "
                        "state drops by the dp factor, XLA all-gathers "
                        "weights at each use (subsumes --zero1)")
    parser.add_argument("--accum-steps", type=int, default=1,
                        help="gradient accumulation: split each batch "
                        "into N sequential chunks inside the compiled "
                        "step (batch must divide; not with --pipeline-"
                        "stages, whose microbatching already does this)")
    parser.add_argument("--checkpoint-dir", default="")
    parser.add_argument("--checkpoint-every", type=int, default=50)
    parser.add_argument("--checkpoint-async", action="store_true",
                        help="commit checkpoints on a background "
                        "thread: the loop resumes after the "
                        "device->host copy instead of waiting for "
                        "disk")
    args = parser.parse_args()

    from ..models.transformer import TransformerConfig
    from .modelcfg import derive_d_ff
    from ..parallel import (
        MeshPlan,
        init_train_state,
        make_mesh,
        make_optimizer,
        make_pipeline_train_step,
        make_train_step,
    )

    cfg = TransformerConfig(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads,
        n_layers=args.n_layers,
        d_ff=derive_d_ff(args.d_model),
        max_seq_len=args.seq_len,
        moe_experts=args.moe_experts,
        moe_train_capacity=args.moe_capacity,
        window=args.window,
        loss_chunk=args.loss_chunk,
    )
    rules = None
    if args.pipeline_stages > 1:
        if args.loss_chunk:
            raise SystemExit(
                "--loss-chunk does not apply to the pipelined loss "
                "(pipeline_loss_fn computes its own whole-logits CE)"
            )
        # dp x pp x tp: layers shard over pipe stages, tensor
        # parallelism stays live inside each stage (parallel/pipeline.py)
        n_dev = len(jax.devices())
        tp = args.tensor_parallel or 1
        if n_dev % (args.pipeline_stages * tp):
            raise SystemExit(
                f"{n_dev} devices not divisible by pipeline-stages x "
                f"tensor-parallel = {args.pipeline_stages} x {tp}"
            )
        mesh = make_mesh(plan=MeshPlan(
            data=n_dev // (args.pipeline_stages * tp),
            model=tp,
            pipe=args.pipeline_stages,
        ))
    else:
        mesh = make_mesh()
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))} "
          f"on {jax.default_backend()}")
    rng = jax.random.PRNGKey(0)
    optimizer = make_optimizer(
        args.learning_rate,
        warmup_steps=args.warmup_steps,
        decay_steps=args.decay_steps,
    )
    if args.ema_decay:
        from ..parallel import with_ema

        optimizer = with_ema(optimizer, args.ema_decay)
    lora_init = lora_abstract = None
    if args.lora_rank > 0:
        if (args.pipeline_stages > 1 or args.zero1 or args.fsdp
                or args.accum_steps > 1):
            raise SystemExit(
                "--lora-rank composes with the plain trainer only "
                "(the adapter state is tiny; zero1/fsdp/accum/pipeline "
                "solve problems LoRA doesn't have)"
            )
        from ..models.transformer import init_params
        from ..parallel import make_lora_train_step, restore_params
        from ..parallel.sharding import shard_params

        if args.base_checkpoint_dir:
            from ..parallel import abstract_train_state

            restored_base = restore_params(
                args.base_checkpoint_dir,
                abstract_train_state(rng, cfg, mesh, args.learning_rate),
            )
            if restored_base is None:
                raise SystemExit(
                    f"no checkpoint in {args.base_checkpoint_dir}"
                )
            base_params, base_step = restored_base
            print(f"lora: frozen base from checkpoint step {int(base_step)}")
        else:
            base_params = shard_params(init_params(rng, cfg), mesh, cfg)
            print("lora: fresh-init frozen base (demo mode)")
        lora_init, lora_step, lora_abstract = make_lora_train_step(
            cfg, mesh, args.lora_rank, args.learning_rate,
            optimizer=optimizer,
        )
        print(f"lora: rank {args.lora_rank} adapters on attention q/v")

        def train_step(state, tokens):
            return lora_step(state, base_params, tokens)

    elif args.pipeline_stages > 1:
        from ..parallel import pipeline_sharding_rules

        if args.accum_steps > 1:
            raise SystemExit(
                "--accum-steps composes with the plain trainer only; "
                "pipeline microbatching already bounds activations"
            )
        if args.zero1 or args.fsdp:
            raise SystemExit(
                "--zero1/--fsdp compose with the plain trainer only "
                "(pipeline sharding rules already partition state over "
                "stages)"
            )
        rules = pipeline_sharding_rules(cfg, mesh)
        train_step = make_pipeline_train_step(
            cfg, mesh, args.learning_rate, args.microbatches,
            optimizer=optimizer,
        )
    else:
        if args.batch % args.accum_steps:
            raise SystemExit(
                f"--batch {args.batch} not divisible by --accum-steps "
                f"{args.accum_steps}"
            )
        if args.fsdp:
            from ..parallel import fsdp_sharding_rules

            rules = fsdp_sharding_rules(cfg, mesh)
        train_step = make_train_step(
            cfg, mesh, args.learning_rate, optimizer=optimizer,
            accum_steps=args.accum_steps, zero1=args.zero1,
            rules=rules,
        )

    state = None
    start_step = 0
    if args.checkpoint_dir:
        from ..parallel import (
            abstract_train_state,
            restore_checkpoint,
            save_checkpoint,
        )

        # restore into the eval_shape skeleton: no throwaway init, no
        # double residency of model + optimizer state during resume
        abstract = (
            lora_abstract
            if lora_abstract is not None
            else abstract_train_state(
                rng, cfg, mesh, args.learning_rate, rules=rules,
                optimizer=optimizer, zero1=args.zero1,
            )
        )
        state = restore_checkpoint(args.checkpoint_dir, abstract)
        if state is not None:
            start_step = int(state.step)
            print(f"resumed from checkpoint at step {start_step}")
    if state is None:
        state = (
            lora_init(rng)
            if lora_init is not None
            else init_train_state(
                rng, cfg, mesh, args.learning_rate, rules=rules,
                optimizer=optimizer, zero1=args.zero1,
            )
        )

    client = None
    if args.control_socket:
        from ..client import ControlClient

        client = ControlClient(args.control_socket)

    if args.eval_every > 0 and not (args.data_dir and args.eval_holdout):
        # validated before any dataset/prefetcher exists so a bad flag
        # combination can't leak the staging thread
        raise SystemExit(
            "--eval-every requires --data-dir and --eval-holdout"
        )

    # graceful preemption: the handler only sets a flag; the train
    # loop checks it at the step boundary. Installed BEFORE any
    # resource (prefetcher thread, device buffers) exists so a
    # non-main-thread caller fails here, with nothing yet to leak;
    # the train loop's finally restores the previous disposition.
    import signal as signal_mod
    import threading

    preempted = threading.Event()
    prev_term = signal_mod.signal(
        signal_mod.SIGTERM, lambda s, f: preempted.set()
    )

    prefetcher = None
    if args.data_dir:
        from jax.sharding import NamedSharding

        from ..parallel.sharding import batch_spec
        from .data import DevicePrefetcher, TokenShardDataset

        dataset = TokenShardDataset(
            args.data_dir, args.seq_len, args.batch,
            vocab_size=cfg.vocab_size,  # fail loudly on id/vocab mismatch
            holdout_windows=args.eval_holdout,
        )
        # batches stage onto the mesh from a background thread; the
        # window order is a pure function of the step, so a restarted
        # trainer replays the exact stream from its checkpoint step
        prefetcher = DevicePrefetcher(
            dataset,
            start_step=start_step,
            sharding=NamedSharding(mesh, batch_spec()),
        )
        print(f"data: {dataset.n_windows} train windows "
              f"(+{dataset.holdout_windows} held out) from {args.data_dir}")

    eval_enabled = args.eval_every > 0

    def run_eval(params) -> float:
        # the ONE eval-loss computation, shared with the standalone
        # evaluate CLI (workload/modelcfg.py) so their numbers are
        # comparable by construction
        from .modelcfg import average_eval_loss

        return average_eval_loss(
            params, cfg, dataset.n_eval_batches, dataset.eval_batch
        )

    # profiler window: skip step 1 (compile) and capture a few steady
    # steps — the standard "pick a mesh, profile, iterate" loop
    if args.profile_dir and args.profile_steps < 1:
        raise SystemExit("--profile-steps must be >= 1")
    profile_start = start_step + 1 if args.profile_dir else -1
    profile_stop = profile_start + args.profile_steps
    if args.profile_dir and profile_start >= args.steps:
        print(
            f"warning: --profile-dir needs at least "
            f"{profile_start - start_step + 1} steps after resume to "
            "capture a steady-state window; nothing will be profiled"
        )
    profiling = False

    # throughput accounting: tokens/s from wall clock, MFU against the
    # chip generation's bf16 peak (workload/flops.py) — the numbers an
    # operator watches on the supervisor's Prometheus endpoint
    from .flops import count_params, peak_flops, train_flops_per_token

    if args.lora_rank > 0:
        # the frozen base forwards + carries grads but trains nothing
        n_base = count_params(base_params)
        n_params = n_base + count_params(state.params)
        flops_per_token = train_flops_per_token(
            cfg, n_params, args.seq_len, n_frozen=n_base
        )
    else:
        n_params = count_params(state.params)
        flops_per_token = train_flops_per_token(
            cfg, n_params, args.seq_len
        )
    chip_peak = peak_flops(jax.devices()[0].device_kind) * len(
        jax.devices()
    )

    data_rng = jax.random.PRNGKey(1)
    t0 = time.monotonic()
    try:
        for step in range(start_step, args.steps):
            if preempted.is_set():
                if args.checkpoint_dir and jax.process_count() == 1:
                    from ..parallel import wait_for_checkpoints

                    wait_for_checkpoints()  # drain async saves first
                    save_checkpoint(args.checkpoint_dir, step, state)
                    print(f"preempted: checkpoint saved at step {step}; "
                          "exiting for the supervisor to resume",
                          flush=True)
                else:
                    # a multi-process pod can't checkpoint from one
                    # signal (orbax barriers span processes): exit
                    # clean, resume from the last periodic save
                    print("preempted: exiting (resume from last "
                          "periodic checkpoint)", flush=True)
                return 0
            if step == profile_start:
                jax.profiler.start_trace(args.profile_dir)
                profiling = True
            if prefetcher is not None:
                _pstep, tokens = prefetcher.next()
            else:
                # stateless per-step key: a resumed run continues the
                # data stream exactly where the crashed run left off
                k = jax.random.fold_in(data_rng, step)
                tokens = jax.random.randint(
                    k, (args.batch, args.seq_len + 1), 0, cfg.vocab_size,
                    jnp.int32,
                )
            state, loss = train_step(state, tokens)
            if step + 1 == profile_stop and profiling:
                loss.block_until_ready()  # close the window on real work
                jax.profiler.stop_trace()
                profiling = False
                print(f"profiler trace written to {args.profile_dir}")
            if args.checkpoint_dir and (step + 1) % args.checkpoint_every == 0:
                save_checkpoint(args.checkpoint_dir, step + 1, state,
                                wait=not args.checkpoint_async)
            if args.progress_file:
                tmp = args.progress_file + ".tmp"
                with open(tmp, "w") as f:
                    json.dump({"step": step + 1, "loss": float(loss),
                               "time": time.time()}, f)
                os.replace(tmp, args.progress_file)
            if (step + 1) % 10 == 0 or step == start_step:
                # one throughput computation feeds BOTH the metric
                # export and the log line, so they can never disagree
                rate = (step + 1 - start_step) / (time.monotonic() - t0)
                tokens_s = rate * args.batch * args.seq_len
                mfu = tokens_s * flops_per_token / chip_peak
                if client is not None and (step + 1) % 10 == 0:
                    try:
                        client.put_metric({
                            "training_steps_total": 10,
                            "training_loss": float(loss),
                            "training_tokens_per_sec": tokens_s,
                            "training_mfu": mfu,
                        })
                    except Exception:  # cpcheck: disable=CP-SWALLOW supervisor may be reloading; never die
                        pass
                print(f"step {step + 1}: loss={float(loss):.4f} "
                      f"({rate:.1f} steps/s, {tokens_s:.0f} tok/s, "
                      f"mfu={mfu:.3f})")
            if eval_enabled and (step + 1) % args.eval_every == 0:
                if args.lora_rank > 0:
                    from ..models.lora import apply_lora
                    from ..parallel import ema_params

                    adapters = (
                        ema_params(state) if args.ema_decay
                        else state.params
                    )
                    eval_loss = run_eval(
                        apply_lora(base_params, adapters, cfg)
                    )
                elif args.ema_decay:
                    from ..parallel import ema_params

                    eval_loss = run_eval(ema_params(state))
                else:
                    eval_loss = run_eval(state.params)
                print(f"step {step + 1}: eval_loss={eval_loss:.4f}")
                if client is not None:
                    try:
                        client.put_metric({"training_eval_loss": eval_loss})
                    except Exception:  # cpcheck: disable=CP-SWALLOW supervisor may be reloading; never die
                        pass
    finally:
        # a failed step must not leak the staging thread (in-process
        # callers would otherwise keep a live worker + device buffers),
        # a dangling profiler window must be closed, and in-process
        # callers (tests) must get their SIGTERM disposition back
        signal_mod.signal(signal_mod.SIGTERM, prev_term)
        if prefetcher is not None:
            prefetcher.stop()
        if profiling:
            try:
                jax.profiler.stop_trace()
            except Exception:  # cpcheck: disable=CP-SWALLOW profiler may never have started
                pass
        if args.checkpoint_async and args.checkpoint_dir:
            # an in-flight background save must commit before exit —
            # but a deferred write error must not mask an exception
            # already propagating out of the train loop. On a CLEAN
            # exit the failure must surface (a swallowed commit error
            # would return 0 with the final checkpoint silently lost).
            import sys as _sys

            from ..parallel import wait_for_checkpoints

            propagating = _sys.exc_info()[0] is not None
            try:
                wait_for_checkpoints()
            except Exception:
                if not propagating:
                    raise
                logging.getLogger("containerpilot.train").exception(
                    "async checkpoint commit failed"
                )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
