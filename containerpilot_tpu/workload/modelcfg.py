"""Shared CLI plumbing for the workload triad (train/evaluate/serve):
the flag->config derivations and the checkpoint-restore/LoRA-merge
sequence must be ONE implementation, or the three entry points drift
apart and score/serve a differently-shaped model than was trained.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import jax


def enable_compile_cache() -> Optional[str]:
    """Opt-in persistent XLA compilation cache, shared by every
    workload CLI (env: ``CONTAINERPILOT_COMPILE_CACHE=<dir>``).

    The supervisor's whole failure story is crash→restart→resume; the
    dominant cost of a reincarnation is recompiling the exact
    programs the dead process already compiled. With the cache on
    shared storage a restarted trainer or pod member re-warms from
    cached executables, directly shrinking the restart window the
    supervisor's budgets (and a serving pod's downtime) pay for.
    Returns the cache dir when enabled, else None."""
    import os

    path = os.environ.get("CONTAINERPILOT_COMPILE_CACHE", "")
    if not path:
        return None
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # default min-compile-time gate (1s) would skip most of a tiny
    # model's programs; anything over half a second is worth a disk hit
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return path


def derive_d_ff(d_model: int) -> int:
    """The triad's shared SwiGLU width rule: ~3x d_model, floored to
    a 128 multiple (MXU tile), never 0."""
    return d_model * 3 // 128 * 128 or 128


def restore_params_only(
    cfg: Any, mesh: Any, checkpoint_dir: str, use_ema: bool = False
) -> Optional[Tuple[Any, int]]:
    """Params-only restore (optionally the EMA shadow) landing on
    ``mesh`` — optimizer moments stay PLACEHOLDERs on disk. Returns
    (params, checkpoint_step) — with ``.ema`` recording whether the
    shadow is what actually came back — or None when no checkpoint
    exists."""
    from ..parallel import abstract_train_state, restore_params
    from ..parallel.checkpoint import RestoredParams

    restored = restore_params(
        checkpoint_dir,
        abstract_train_state(jax.random.PRNGKey(0), cfg, mesh),
        prefer_ema=use_ema,
    )
    if restored is None:
        return None
    params, step = restored
    return RestoredParams(params, int(step), restored.ema)


def score_logprobs_fn(cfg: Any):
    """The ONE teacher-forced scoring function: per-token logprobs of
    toks[1:] from a forward over toks[:-1]. The single-host
    /v1/score and the pod frontend's twin both jit exactly this, so
    their numbers cannot drift."""
    import jax.numpy as jnp

    from ..models.transformer import forward

    def score(params, toks):
        logits = forward(params, toks[:, :-1], cfg)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return jnp.take_along_axis(
            logp, toks[:, 1:, None], axis=-1
        )[..., 0]

    return score


def parse_logit_bias(raw: Any, vocab_size: int):
    """The ONE HTTP-facing ``logit_bias`` parser (single-host server
    and pod frontend both call it — the bounds must not diverge):
    OpenAI's {token_id: bias} with string or int keys; ``{}`` and
    None are a no-op (OpenAI accepts an empty map). Raises ValueError
    for the 422 path; the model-side normalize_logit_bias re-checks
    the same bounds."""
    if raw is None:
        return None
    from ..models.decode import BIAS_SLOTS_MAX

    if not isinstance(raw, dict):
        raise ValueError(
            "'logit_bias' must be a {token_id: bias} object"
        )
    if not raw:
        return None  # OpenAI semantics: an empty map is a no-op
    if len(raw) > BIAS_SLOTS_MAX:
        raise ValueError(
            f"'logit_bias' is capped at {BIAS_SLOTS_MAX} tokens"
        )
    out = {}
    for k, v in raw.items():
        try:
            tok = int(k)
            bias = float(v)
        except (TypeError, ValueError):
            raise ValueError(
                "'logit_bias' keys must be token ids and values "
                "numbers"
            ) from None
        if not 0 <= tok < vocab_size:
            raise ValueError(
                f"'logit_bias' token ids must be in [0, {vocab_size})"
            )
        if not abs(bias) <= 100:
            raise ValueError(
                "'logit_bias' values must be in [-100, 100]"
            )
        out[tok] = bias
    return out


def parse_stop_ids(raw: Any, vocab_size: int):
    """The ONE token-level ``stop`` parser (single-host server and pod
    frontend — the bounds must not diverge): a list of non-empty id
    rows (text surfaces encode strings before calling). Bounded so a
    request can't smuggle in an O(stops*len) trim bill. Raises
    ValueError for the 422 path."""
    if raw is None:
        return []
    if not isinstance(raw, list) or len(raw) > 8 or not all(
        isinstance(s, list)
        and 1 <= len(s) <= 32
        and all(
            isinstance(t, int)
            and not isinstance(t, bool)
            and 0 <= t < vocab_size
            for t in s
        )
        for s in raw
    ):
        raise ValueError(
            "'stop' must be a list of at most 8 sequences, each "
            f"1..32 token ids in [0, {vocab_size})"
        )
    return raw


def parse_stop_strings(raw: Any):
    """The string-level half of the ``stop`` contract, shared by both
    text surfaces (single-host and pod /v1/completions): one string or
    a list of at most 8, each 1..32 UTF-8 bytes. Validated BEFORE
    encoding so the 422 speaks the text endpoint's language (the
    id-level bounds in parse_stop_ids would otherwise leak through).
    Returns the list of strings (None -> None)."""
    if raw is None:
        return None
    if isinstance(raw, str):
        raw = [raw]
    if (
        not isinstance(raw, list)
        or len(raw) > 8
        or not all(
            isinstance(s, str) and 1 <= len(s.encode()) <= 32
            for s in raw
        )
    ):
        raise ValueError(
            "'stop' must be a non-empty string (or a list of at "
            "most 8), each at most 32 UTF-8 bytes"
        )
    return raw


def validate_lora_flags(lora_dir: str, lora_rank: int) -> None:
    """Clean SystemExit for the flag-misuse cases every CLI shares."""
    if lora_rank > 0 and not lora_dir:
        raise SystemExit("--lora-rank without --lora-dir does nothing; "
                         "pass the adapter checkpoint dir")
    if lora_dir and lora_rank < 1:
        raise SystemExit("--lora-dir requires --lora-rank")


def merge_lora(
    params: Any, cfg: Any, mesh: Any, lora_dir: str, lora_rank: int
) -> Tuple[Any, int]:
    """Restore a trained adapter from ``lora_dir`` (on the SAME mesh
    the base lives on — a mismatched device set makes the merge add
    uncompilable) and fold it into the base weights. Merge BEFORE any
    quantization: int8 bases aren't adaptable."""
    from ..models.lora import apply_lora
    from ..parallel import lora_abstract_state, restore_params

    adapter = restore_params(
        lora_dir, lora_abstract_state(cfg, lora_rank, mesh)
    )
    if adapter is None:
        raise SystemExit(f"no adapter checkpoint in {lora_dir}")
    return apply_lora(params, adapter[0], cfg), int(adapter[1])


def restore_merged_params(
    cfg: Any,
    mesh: Any,
    checkpoint_dir: str,
    use_ema: bool = False,
    lora_dir: str = "",
    lora_rank: int = 0,
) -> Optional[Tuple[Any, int]]:
    """restore_params_only + optional merge_lora, the composition the
    evaluate CLI scores. Returns (params, checkpoint_step) — with
    ``.ema`` from the base restore — or None when no checkpoint
    exists."""
    from ..parallel.checkpoint import RestoredParams

    validate_lora_flags(lora_dir, lora_rank)
    restored = restore_params_only(cfg, mesh, checkpoint_dir, use_ema)
    if restored is None:
        return None
    params, step = restored
    if lora_dir:
        params, _ = merge_lora(params, cfg, mesh, lora_dir, lora_rank)
    return RestoredParams(params, step, restored.ema)


def average_eval_loss(params, cfg, n: int, batch_at) -> float:
    """The one eval-loss computation (jitted loss_fn averaged over n
    batches) shared by the trainer's in-loop eval and the standalone
    evaluate CLI — the comparability of their numbers is structural,
    not a convention."""
    import jax.numpy as jnp

    from ..models.transformer import loss_fn

    step = jax.jit(lambda p, t: loss_fn(p, t, cfg))
    total = 0.0
    for i in range(n):
        total += float(step(params, jnp.asarray(batch_at(i))))
    return total / n
