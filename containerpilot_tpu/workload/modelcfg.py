"""Shared CLI plumbing for the workload triad (train/evaluate/serve):
the flag->config derivations and the checkpoint-restore/LoRA-merge
sequence must be ONE implementation, or the three entry points drift
apart and score/serve a differently-shaped model than was trained.
"""
from __future__ import annotations

from typing import Any, Iterable, Optional, Tuple

import jax


def enable_compile_cache(path: str = "") -> Optional[str]:
    """Opt-in persistent XLA compilation cache, shared by every
    workload CLI (env: ``CONTAINERPILOT_COMPILE_CACHE=<dir>``, or an
    explicit ``path`` — e.g. one adopted from a fleet peer's
    heartbeat advertisement, see ``adopt_fleet_compile_cache``).

    The supervisor's whole failure story is crash→restart→resume; the
    dominant cost of a reincarnation is recompiling the exact
    programs the dead process already compiled. With the cache on
    shared storage a restarted trainer or pod member re-warms from
    cached executables, directly shrinking the restart window the
    supervisor's budgets (and a serving pod's downtime) pay for.
    Returns the cache dir when enabled, else None."""
    import os

    path = path or os.environ.get("CONTAINERPILOT_COMPILE_CACHE", "")
    if not path:
        return None
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # default min-compile-time gate (1s) would skip most of a tiny
    # model's programs; anything over half a second is worth a disk hit
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return path


# -- warm-bucket markers (the compile cache as a fleet artifact) ------
#
# The XLA disk cache makes a RE-compile cheap; nothing tells a fresh
# replica it can skip driving the warmup compiles at all. The marker
# file records, per warmup fingerprint (model/engine shape), which
# warmup buckets a previous process on this cache dir already pushed
# through XLA — a launch that finds its buckets marked skips those
# warmup requests entirely and flips /health 200 in milliseconds,
# which is the compile_warmup collapse the cold-start work needs.
# All helpers are blocking (file I/O): executor-wrap them on serving
# loops.

WARM_MARKER = "cp_warm_buckets.json"


def warmup_fingerprint(
    cfg: Any,
    max_len: int,
    slots: int = 0,
    slot_chunk: int = 0,
    slot_window: int = 0,
    draft_layers: int = 0,
    speculate: int = 0,
) -> str:
    """Stable hash of everything that shapes the warmup program set:
    a marker written under one fingerprint must never skip warmup for
    a differently-shaped server sharing the cache dir."""
    import hashlib
    import json as json_mod

    key = json_mod.dumps(
        {
            # platform identity: XLA's disk cache keys include the
            # backend, and the marker must too — a cpu process's
            # marker must never skip a tpu launch's warmup (shared
            # NFS cache dirs make this a real shape)
            "backend": jax.default_backend(),
            "jax": getattr(jax, "__version__", ""),
            "vocab": getattr(cfg, "vocab_size", 0),
            "d_model": getattr(cfg, "d_model", 0),
            "n_heads": getattr(cfg, "n_heads", 0),
            "kv_heads": getattr(cfg, "kv_heads", 0),
            "n_layers": getattr(cfg, "n_layers", 0),
            "d_ff": getattr(cfg, "d_ff", 0),
            "window": getattr(cfg, "window", 0),
            "moe_experts": getattr(cfg, "moe_experts", 0),
            "kv_int8": bool(getattr(cfg, "kv_int8", False)),
            "max_len": max_len,
            "slots": slots,
            "slot_chunk": slot_chunk,
            # fused decode rounds per dispatch: the (S, chunk, K)
            # window program is part of the engine's compiled set, so
            # K is part of the marker identity — a K=1 process's
            # marker must never skip the fused program a K=4 launch
            # needs
            "slot_window": slot_window,
            "draft_layers": draft_layers,
            "speculate": speculate,
        },
        sort_keys=True,
    )
    return hashlib.blake2b(key.encode(), digest_size=8).hexdigest()


def load_warm_buckets(cache_dir: str, fingerprint: str) -> set:
    """Warmup buckets already marked warm for this fingerprint in
    this cache dir; tolerant of a missing/torn marker (empty set —
    worst case the launch warms up fully, never a crash)."""
    import json as json_mod
    import os

    if not cache_dir:
        return set()
    try:
        with open(os.path.join(cache_dir, WARM_MARKER)) as fh:
            marker = json_mod.load(fh)
        buckets = marker.get(fingerprint, [])
        return {b for b in buckets if isinstance(b, str)}
    except (OSError, ValueError, AttributeError):
        return set()


def mark_warm_buckets(
    cache_dir: str, fingerprint: str, buckets: Iterable[str]
) -> None:
    """Merge ``buckets`` into the marker under ``fingerprint``
    (atomic tmp+rename write; concurrent markers last-write-win,
    which only costs a redundant warmup, never a wrong skip)."""
    import json as json_mod
    import os

    if not cache_dir:
        return
    path = os.path.join(cache_dir, WARM_MARKER)
    try:
        with open(path) as fh:
            marker = json_mod.load(fh)
        if not isinstance(marker, dict):
            marker = {}
    except (OSError, ValueError):
        marker = {}
    merged = set(marker.get(fingerprint, [])) | set(buckets)
    marker[fingerprint] = sorted(merged)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json_mod.dump(marker, fh, sort_keys=True)
    os.replace(tmp, path)


def compile_cache_note(cache_dir: str) -> str:
    """The heartbeat advertisement VALUE (``<digest>:<quoted dir>``,
    carried as the ``cc=`` field by ``fleet/notes.py``) a FleetMember
    appends for a replica serving with a compile cache: peers on the
    same host adopt the dir, and the digest (over the warm-bucket
    marker) tells readers when the warm set moved. Empty when no
    cache dir is configured."""
    import hashlib
    import json as json_mod
    import os

    from ..fleet.notes import encode_compile_cache

    if not cache_dir:
        return ""
    try:
        with open(os.path.join(cache_dir, WARM_MARKER)) as fh:
            marker_blob = json_mod.dumps(json_mod.load(fh), sort_keys=True)
    except (OSError, ValueError):
        marker_blob = ""
    digest = hashlib.blake2b(
        marker_blob.encode(), digest_size=4
    ).hexdigest()
    return encode_compile_cache(digest, cache_dir)


def parse_compile_cache_note(raw: object) -> Tuple[str, str]:
    """Tolerant reader for the ``cc=`` field's value: (digest, dir);
    both empty on garbage — never an exception on the routing path.
    Thin alias for the registry codec in ``fleet/notes.py``."""
    from ..fleet.notes import parse_compile_cache

    return parse_compile_cache(raw)


def _local_addresses() -> set:
    """Addresses that mean "this host" for cache adoption."""
    import socket

    local = {"127.0.0.1", "localhost", "0.0.0.0", "::1", ""}
    try:
        hostname = socket.gethostname()
        local.add(hostname)
        local.update(
            info[4][0]
            for info in socket.getaddrinfo(hostname, None)
        )
    except OSError:
        # a host that can't resolve itself still adopts loopback
        # advertisements; remote ones are skipped either way
        return local
    return local


def adopt_fleet_compile_cache(
    backend: Any, service_name: str
) -> Optional[str]:
    """Scan the catalog for a peer replica advertising a compile
    cache dir on THIS host (``cc=`` heartbeat field) and enable it
    for this process. Returns the adopted dir, or None when nobody
    advertises one that exists locally — a launch that shares a
    host with a warm peer reuses its compiled executables (and its
    warm-bucket marker) instead of compiling from scratch. Only
    SAME-HOST advertisements are considered: a remote peer's path
    that happens to exist locally is a different host's cache (the
    warmup fingerprint's platform field is the second guard, for
    genuinely shared NFS dirs)."""
    import os

    from ..fleet import notes as notes_mod

    try:
        instances = backend.instances(service_name)
    except Exception:
        return None
    local = _local_addresses()
    for inst in instances:
        if getattr(inst, "address", "") not in local:
            continue
        fields = notes_mod.split_note(getattr(inst, "notes", ""))
        _digest, cache_dir = notes_mod.parse_field(
            "cc", fields.get("cc", "")
        )
        if cache_dir and os.path.isdir(cache_dir):
            return enable_compile_cache(cache_dir)
    return None


def derive_d_ff(d_model: int) -> int:
    """The triad's shared SwiGLU width rule: ~3x d_model, floored to
    a 128 multiple (MXU tile), never 0."""
    return d_model * 3 // 128 * 128 or 128


def restore_params_only(
    cfg: Any, mesh: Any, checkpoint_dir: str, use_ema: bool = False
) -> Optional[Tuple[Any, int]]:
    """Params-only restore (optionally the EMA shadow) landing on
    ``mesh`` — optimizer moments stay PLACEHOLDERs on disk. Returns
    (params, checkpoint_step) — with ``.ema`` recording whether the
    shadow is what actually came back — or None when no checkpoint
    exists."""
    from ..parallel import abstract_train_state, restore_params
    from ..parallel.checkpoint import RestoredParams

    restored = restore_params(
        checkpoint_dir,
        abstract_train_state(jax.random.PRNGKey(0), cfg, mesh),
        prefer_ema=use_ema,
    )
    if restored is None:
        return None
    params, step = restored
    return RestoredParams(params, int(step), restored.ema)


def score_logprobs_fn(cfg: Any):
    """The ONE teacher-forced scoring function: per-token logprobs of
    toks[1:] from a forward over toks[:-1]. The single-host
    /v1/score and the pod frontend's twin both jit exactly this, so
    their numbers cannot drift."""
    import jax.numpy as jnp

    from ..models.transformer import forward

    def score(params, toks):
        logits = forward(params, toks[:, :-1], cfg)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return jnp.take_along_axis(
            logp, toks[:, 1:, None], axis=-1
        )[..., 0]

    return score


def parse_logit_bias(raw: Any, vocab_size: int):
    """The ONE HTTP-facing ``logit_bias`` parser (single-host server
    and pod frontend both call it — the bounds must not diverge):
    OpenAI's {token_id: bias} with string or int keys; ``{}`` and
    None are a no-op (OpenAI accepts an empty map). Raises ValueError
    for the 422 path; the model-side normalize_logit_bias re-checks
    the same bounds."""
    if raw is None:
        return None
    from ..models.decode import BIAS_SLOTS_MAX

    if not isinstance(raw, dict):
        raise ValueError(
            "'logit_bias' must be a {token_id: bias} object"
        )
    if not raw:
        return None  # OpenAI semantics: an empty map is a no-op
    if len(raw) > BIAS_SLOTS_MAX:
        raise ValueError(
            f"'logit_bias' is capped at {BIAS_SLOTS_MAX} tokens"
        )
    out = {}
    for k, v in raw.items():
        try:
            tok = int(k)
            bias = float(v)
        except (TypeError, ValueError):
            raise ValueError(
                "'logit_bias' keys must be token ids and values "
                "numbers"
            ) from None
        if not 0 <= tok < vocab_size:
            raise ValueError(
                f"'logit_bias' token ids must be in [0, {vocab_size})"
            )
        if not abs(bias) <= 100:
            raise ValueError(
                "'logit_bias' values must be in [-100, 100]"
            )
        out[tok] = bias
    return out


def parse_stop_ids(raw: Any, vocab_size: int):
    """The ONE token-level ``stop`` parser (single-host server and pod
    frontend — the bounds must not diverge): a list of non-empty id
    rows (text surfaces encode strings before calling). Bounded so a
    request can't smuggle in an O(stops*len) trim bill. Raises
    ValueError for the 422 path."""
    if raw is None:
        return []
    if not isinstance(raw, list) or len(raw) > 8 or not all(
        isinstance(s, list)
        and 1 <= len(s) <= 32
        and all(
            isinstance(t, int)
            and not isinstance(t, bool)
            and 0 <= t < vocab_size
            for t in s
        )
        for s in raw
    ):
        raise ValueError(
            "'stop' must be a list of at most 8 sequences, each "
            f"1..32 token ids in [0, {vocab_size})"
        )
    return raw


def parse_stop_strings(raw: Any):
    """The string-level half of the ``stop`` contract, shared by both
    text surfaces (single-host and pod /v1/completions): one string or
    a list of at most 8, each 1..32 UTF-8 bytes. Validated BEFORE
    encoding so the 422 speaks the text endpoint's language (the
    id-level bounds in parse_stop_ids would otherwise leak through).
    Returns the list of strings (None -> None)."""
    if raw is None:
        return None
    if isinstance(raw, str):
        raw = [raw]
    if (
        not isinstance(raw, list)
        or len(raw) > 8
        or not all(
            isinstance(s, str) and 1 <= len(s.encode()) <= 32
            for s in raw
        )
    ):
        raise ValueError(
            "'stop' must be a non-empty string (or a list of at "
            "most 8), each at most 32 UTF-8 bytes"
        )
    return raw


def validate_lora_flags(lora_dir: str, lora_rank: int) -> None:
    """Clean SystemExit for the flag-misuse cases every CLI shares."""
    if lora_rank > 0 and not lora_dir:
        raise SystemExit("--lora-rank without --lora-dir does nothing; "
                         "pass the adapter checkpoint dir")
    if lora_dir and lora_rank < 1:
        raise SystemExit("--lora-dir requires --lora-rank")


def merge_lora(
    params: Any, cfg: Any, mesh: Any, lora_dir: str, lora_rank: int
) -> Tuple[Any, int]:
    """Restore a trained adapter from ``lora_dir`` (on the SAME mesh
    the base lives on — a mismatched device set makes the merge add
    uncompilable) and fold it into the base weights. Merge BEFORE any
    quantization: int8 bases aren't adaptable."""
    from ..models.lora import apply_lora
    from ..parallel import lora_abstract_state, restore_params

    adapter = restore_params(
        lora_dir, lora_abstract_state(cfg, lora_rank, mesh)
    )
    if adapter is None:
        raise SystemExit(f"no adapter checkpoint in {lora_dir}")
    return apply_lora(params, adapter[0], cfg), int(adapter[1])


def restore_merged_params(
    cfg: Any,
    mesh: Any,
    checkpoint_dir: str,
    use_ema: bool = False,
    lora_dir: str = "",
    lora_rank: int = 0,
) -> Optional[Tuple[Any, int]]:
    """restore_params_only + optional merge_lora, the composition the
    evaluate CLI scores. Returns (params, checkpoint_step) — with
    ``.ema`` from the base restore — or None when no checkpoint
    exists."""
    from ..parallel.checkpoint import RestoredParams

    validate_lora_flags(lora_dir, lora_rank)
    restored = restore_params_only(cfg, mesh, checkpoint_dir, use_ema)
    if restored is None:
        return None
    params, step = restored
    if lora_dir:
        params, _ = merge_lora(params, cfg, mesh, lora_dir, lora_rank)
    return RestoredParams(params, step, restored.ema)


def average_eval_loss(params, cfg, n: int, batch_at) -> float:
    """The one eval-loss computation (jitted loss_fn averaged over n
    batches) shared by the trainer's in-loop eval and the standalone
    evaluate CLI — the comparability of their numbers is structural,
    not a convention."""
    import jax.numpy as jnp

    from ..models.transformer import loss_fn

    step = jax.jit(lambda p, t: loss_fn(p, t, cfg))
    total = 0.0
    for i in range(n):
        total += float(step(params, jnp.asarray(batch_at(i))))
    return total / n
