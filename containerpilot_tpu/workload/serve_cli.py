"""CLI for the supervised inference server: argument surface, model
loading (checkpoint / EMA / LoRA merge / int8), and the serve loop.

``python -m containerpilot_tpu.workload.serve`` lands here via
serve.main (kept there so supervisor job configs and docs keep one
import path).
"""
from __future__ import annotations

import argparse
import asyncio

import jax

from ..models.transformer import TransformerConfig, init_params
from .modelcfg import (
    derive_d_ff,
    merge_lora,
    restore_params_only,
    validate_lora_flags,
)


def build_arg_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description="supervised inference server"
    )
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument(
        "--mux", default=True, action=argparse.BooleanOptionalAction,
        help="accept cp-mux/1 upgrades (the fleet gateway's "
        "multiplexed transport); --no-mux keeps this replica plain "
        "HTTP/1.1 and gateways fall back per-replica",
    )
    parser.add_argument("--max-len", type=int, default=512)
    parser.add_argument("--d-model", type=int, default=256)
    parser.add_argument("--n-layers", type=int, default=2)
    parser.add_argument("--n-heads", type=int, default=4)
    parser.add_argument("--n-kv-heads", type=int, default=0,
                        help="GQA kv heads (0 = full multi-head); must "
                        "match the checkpoint being served")
    parser.add_argument("--moe-experts", type=int, default=0,
                        help="switch-MoE experts; must match the "
                        "checkpoint being served")
    parser.add_argument("--window", type=int, default=0,
                        help="sliding-window attention; must match the "
                        "checkpoint being served. Decode KV memory "
                        "becomes a ring of `window` slots")
    parser.add_argument("--vocab", type=int, default=1024)
    parser.add_argument(
        "--checkpoint-dir", default="",
        help="load trained params from the latest checkpoint",
    )
    parser.add_argument(
        "--use-ema", action="store_true",
        help="serve the EMA shadow weights from the checkpoint "
        "(trained with --ema-decay) instead of the raw params",
    )
    parser.add_argument(
        "--int8", action="store_true",
        help="weight-only int8: ~4x smaller resident params",
    )
    parser.add_argument(
        "--kv-int8", action="store_true",
        help="int8 KV cache: halves decode KV memory vs bf16 "
        "(per-token-per-head scales; composes with GQA and --window)",
    )
    parser.add_argument(
        "--lora-dir", default="",
        help="merge a trained LoRA adapter checkpoint into the base "
        "weights at startup (zero runtime overhead); requires "
        "--lora-rank to match the adapter",
    )
    parser.add_argument(
        "--lora-rank", type=int, default=0,
        help="rank of the adapter in --lora-dir",
    )
    parser.add_argument(
        "--draft-layers", type=int, default=0,
        help="self-speculative decoding: draft with the model's first "
        "N layers; greedy single-sequence requests decode several "
        "tokens per target pass with identical output (0 = off)",
    )
    parser.add_argument(
        "--speculate", type=int, default=4,
        help="draft tokens proposed per verify round",
    )
    parser.add_argument(
        "--max-batch-rows", type=int, default=16,
        help="continuous batching: max sequences coalesced into one "
        "device call",
    )
    parser.add_argument(
        "--prefill-chunk", type=int, default=0,
        help="stream prompts longer than N through chunked prefill "
        "(peak prefill activations O(N) instead of O(prompt)); 0 = "
        "one-shot prefill",
    )
    parser.add_argument(
        "--prefix-cache", type=int, default=0,
        help="prefix KV reuse: keep the KV caches of the last N "
        "prompts and re-prefill only the unseen suffix of single-row "
        "requests sharing a prefix (the chat/agent regime); 0 = off",
    )
    parser.add_argument(
        "--kv-spill-mb", type=float, default=0.0,
        help="host-RAM KV spill tier budget in MiB: prefix-cache LRU "
        "evictions spill to host memory and readmit on a later match "
        "(device_put roundtrip instead of re-prefill); requires "
        "--prefix-cache; 0 = off",
    )
    parser.add_argument(
        "--text", action="store_true",
        help="enable the text surface: POST /v1/completions encodes "
        "prompts with the built-in byte-level tokenizer (requires "
        "--vocab >= 259)",
    )
    parser.add_argument(
        "--slots", type=int, default=0,
        help="continuous decode admission: single-row requests join a "
        "running chunked decode over a pool of N slots instead of "
        "queueing behind whole generations; 0 = off. Composes "
        "with --window (per-slot ring caches), --cp (admissions "
        "ring long prompts), --prefill-chunk (piecewise "
        "admission), and --prefix-cache (admissions rewind+extend "
        "cached prefixes)",
    )
    parser.add_argument(
        "--slot-chunk", type=int, default=8,
        help="tokens decoded per slot-engine chunk between admissions",
    )
    parser.add_argument(
        "--slot-window", type=int, default=4,
        help="decode chunk-rounds fused into ONE device dispatch (a "
        "device-side loop with early exit): the host re-enters at "
        "chunk granularity only when an admission/cancel/stop "
        "decision is pending, so steady-state dispatches/token falls "
        "~K-fold; 1 = the classic one-dispatch-per-chunk loop. "
        "Trade-off: a request arriving mid-window waits up to "
        "window*slot-chunk tokens for a freed slot",
    )
    parser.add_argument(
        "--tp", type=int, default=1,
        help="tensor-parallel ways: shard the model over the first N "
        "local devices (heads/ffn/vocab partitioned, XLA inserts the "
        "collectives); 1 = single device",
    )
    parser.add_argument(
        "--cp", type=int, default=1,
        help="context-parallel prefill ways: long single-row prompts "
        "ring their prefill over a seq axis of N local devices "
        "(parallel.cp_generate); 1 = off. Composes with --tp (a "
        "seq x model mesh over cp*tp devices) and --slots (engine "
        "admissions ring long prompts); rejects "
        "--draft-layers/--prefix-cache/--window",
    )
    parser.add_argument(
        "--cp-min-len", type=int, default=0,
        help="prompts at least this long take the --cp ring "
        "(default 8x the seq axis)",
    )
    # fleet membership: register this replica in the discovery
    # catalog with a TTL heartbeat so a FleetGateway
    # (python -m containerpilot_tpu.fleet) routes to it; deregisters
    # on SIGTERM, and a crash expires critical by TTL
    parser.add_argument(
        "--fleet-catalog", default="",
        help="join an inference fleet: discovery backend URI "
        "('file:/shared/catalog' or 'consul:8500'); empty = lone "
        "replica (no registration)",
    )
    parser.add_argument(
        "--fleet-service", default="inference",
        help="service name to register under",
    )
    parser.add_argument(
        "--fleet-ttl", type=int, default=10,
        help="TTL seconds on the catalog health check",
    )
    parser.add_argument(
        "--fleet-address", default="127.0.0.1",
        help="address to advertise in the catalog",
    )
    parser.add_argument(
        "--fleet-id", default="",
        help="instance id in the catalog (default: "
        "<service>-<random>)",
    )
    parser.add_argument(
        "--migrate-window", type=float, default=5.0,
        help="seconds a drain spends migrating this replica's live "
        "KV prefixes to the digest-coldest healthy survivors (the "
        "handoff wire in reverse) before deregistering; sessions "
        "reconnect warm instead of re-prefilling cold. 0 disables "
        "migration (plain drain). Timeouts, dead targets and "
        "poisoned chunks fall back to re-prefill, counted, never a "
        "client error",
    )
    # cold-start collapse knobs (fleet/standby.py, docs/60): boot as
    # promotable warm capacity, fetch weights from a warm peer, and
    # adopt a same-host peer's XLA compile cache
    parser.add_argument(
        "--standby", action="store_true",
        help="boot as a warm STANDBY: load weights, warmup-compile, "
        "register under role=standby (heartbeating, never routed "
        "to); POST /v3/standby/promote flips it active in "
        "milliseconds — the autoscaler's fast scale-up path",
    )
    parser.add_argument(
        "--role", default="mixed",
        choices=("mixed", "prefill", "decode"),
        help="phase specialization for a disaggregated fleet "
        "(docs/60): 'prefill' replicas take fresh prompts and ship "
        "the resulting KV prefix to a decode peer over cp-mux/1; "
        "'decode' replicas run token generation off handed-off "
        "prefixes; 'mixed' (default) serves both phases — existing "
        "fleets are untouched. Routing advice only: every role "
        "serves any request it receives. --standby wins over this",
    )
    parser.add_argument(
        "--weights-from", default="",
        help="fetch model weights from an already-warm peer replica "
        "(host:port) over cp-mux/1 instead of reading a checkpoint "
        "— digest-verified chunks with one resume redial; ANY "
        "failure falls back to the normal --checkpoint-dir/init "
        "load",
    )
    parser.add_argument(
        "--adopt-compile-cache", default=True,
        action=argparse.BooleanOptionalAction,
        help="when joining a fleet without "
        "CONTAINERPILOT_COMPILE_CACHE set, adopt a same-host peer's "
        "advertised compile-cache dir (its cc= heartbeat field) so "
        "this launch skips already-compiled warmup buckets",
    )
    return parser


def _serving_mesh(tp: int, cp: int = 1):
    """The mesh model loading/sharding lands on: an explicit --tp N
    builds a pure tensor-parallel mesh over the first N local
    devices; --cp adds a seq axis for context-parallel prefill
    (params shard over model and replicate over seq, so the SAME
    mesh serves both the ring prefill and the tp decode); otherwise
    the default factoring over all local devices."""
    from ..parallel import MeshPlan, make_mesh

    tp, cp = max(tp, 1), max(cp, 1)
    if tp == 1 and cp == 1:
        return make_mesh()
    devices = jax.devices()
    if tp * cp > len(devices):
        raise SystemExit(
            f"--tp {tp} x --cp {cp} exceeds the {len(devices)} "
            "local devices"
        )
    if cp > 1:
        return make_mesh(
            devices[: tp * cp],
            plan=MeshPlan(data=1, model=tp, seq=cp),
        )
    return make_mesh(devices[:tp], plan=MeshPlan(data=1, model=tp))


def _validate_tp(cfg: TransformerConfig, tp: int) -> None:
    """Every axis the partition rules put on the model axis must
    divide by tp — fail with a clean message at startup, not a raw
    ValueError deep inside device_put/orbax (sharding.py
    param_sharding_rules: heads, d_ff, vocab, and MoE experts are
    model-sharded; GQA KV replicates when tp does not divide it)."""
    for name, size in (
        ("n_heads", cfg.n_heads),
        ("d_ff", cfg.d_ff),
        ("vocab", cfg.vocab_size),
    ):
        if size % tp:
            raise SystemExit(f"--tp {tp} must divide {name} ({size})")
    if cfg.moe_experts > 1 and cfg.moe_experts % tp:
        raise SystemExit(
            f"--tp {tp} must divide moe_experts ({cfg.moe_experts})"
        )


def load_model(args: argparse.Namespace):
    """Build the config and load/transform params per the flags.
    Returns (cfg, params, mesh) — the ONE mesh everything landed on
    (checkpoint restore, shard, LoRA merge, and the --cp ring must
    share a device set or cross-mesh ops are uncompilable)."""
    cfg = TransformerConfig(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads,
        n_layers=args.n_layers,
        d_ff=derive_d_ff(args.d_model),
        max_seq_len=args.max_len,
        moe_experts=args.moe_experts,
        window=args.window,
        kv_int8=args.kv_int8,
    )
    tp = getattr(args, "tp", 1) or 1
    cp = getattr(args, "cp", 1) or 1
    if tp > 1:
        _validate_tp(cfg, tp)
    # ONE mesh for everything loaded here: checkpoint restore, the
    # fresh-init shard, the LoRA adapter, AND the --cp ring must
    # share a device set or cross-mesh ops are uncompilable
    mesh = _serving_mesh(tp, cp)
    params = None
    if args.checkpoint_dir:
        # shared with the evaluate CLI (workload/modelcfg.py):
        # params-only restore, so the server never pays train-state
        # memory
        restored = restore_params_only(
            cfg, mesh, args.checkpoint_dir, use_ema=args.use_ema
        )
        if restored is not None:
            params, step = restored
            print(f"serving checkpoint step {step}"
                  + (" (EMA weights)" if args.use_ema else ""))
    if params is None:
        params = init_params(jax.random.PRNGKey(0), cfg)
        if tp > 1:
            from ..parallel import shard_params

            params = shard_params(params, mesh, cfg)
    validate_lora_flags(args.lora_dir, args.lora_rank)
    if args.lora_dir:
        params, lora_step_n = merge_lora(
            params, cfg, mesh, args.lora_dir, args.lora_rank
        )
        print(f"merged lora adapter (rank {args.lora_rank}, "
              f"step {lora_step_n})")
    if args.int8:
        from ..models.quantized import param_bytes, quantize_model_params

        before = param_bytes(params)
        params = quantize_model_params(params)
        print(
            f"int8: params {before} -> {param_bytes(params)} bytes "
            f"({before / param_bytes(params):.1f}x smaller)"
        )
    return cfg, params, mesh


def main() -> int:
    import logging

    from .modelcfg import (
        adopt_fleet_compile_cache,
        enable_compile_cache,
    )
    from .serve import InferenceServer

    # the server's operational lines (listening, warm/accepting
    # traffic, slot frees) exist for the SUPERVISOR's log collection;
    # without a handler they vanish
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(message)s",
    )
    args = build_arg_parser().parse_args()
    backend = None
    if getattr(args, "fleet_catalog", ""):
        from ..discovery.factory import new_backend

        backend = new_backend(args.fleet_catalog)
        if backend is None:
            raise SystemExit(
                "--fleet-catalog resolved to no discovery backend"
            )
    # compile cache: the env knob first; failing that, adopt a
    # same-host fleet peer's advertised dir (cc= heartbeat field) so
    # this launch re-warms from its compiled executables — BEFORE
    # model load, so every compile this process does lands in it
    cache_dir = enable_compile_cache()
    if (
        cache_dir is None and backend is not None
        and getattr(args, "adopt_compile_cache", True)
    ):
        cache_dir = adopt_fleet_compile_cache(
            backend, args.fleet_service
        )
        if cache_dir:
            print(f"adopted fleet compile cache {cache_dir}")
    # peer weight transfer (fleet/standby.py): fetch the params from
    # a warm peer over cp-mux/1 — digest-verified, one resume redial
    # — INSTEAD of paying the checkpoint restore; the init-only tree
    # (same shapes/shardings/transforms, cheap) is the template the
    # fetch lands on. Fallback chain: peer -> checkpoint -> init —
    # a failed transfer re-runs the full disk load, so the fast path
    # is never a new way to fail a boot.
    weights_from = getattr(args, "weights_from", "")
    checkpoint_dir = args.checkpoint_dir
    if weights_from:
        host, _, port_s = weights_from.rpartition(":")
        if not port_s.isdigit():
            raise SystemExit(
                f"--weights-from wants host:port, got {weights_from!r}"
            )
        args.checkpoint_dir = ""  # skip the restore the peer replaces
    cfg, params, mesh = load_model(args)
    cp = getattr(args, "cp", 1) or 1
    if weights_from:
        from ..fleet.standby import fetch_params

        fetched = asyncio.run(
            fetch_params(host or "127.0.0.1", int(port_s), params)
        )
        if fetched is not None:
            params = fetched
            print(f"weights fetched from peer {weights_from}")
        elif checkpoint_dir:
            print(
                "peer weight transfer failed; falling back to the "
                "checkpoint restore"
            )
            args.checkpoint_dir = checkpoint_dir
            cfg, params, mesh = load_model(args)
        else:
            print(
                "peer weight transfer failed; serving freshly "
                "initialized weights"
            )
    # the EXACT mesh the params loaded onto: the ring and the params
    # must share one device set (and do, structurally)
    cp_mesh = mesh if cp > 1 else None
    # role resolution: --standby wins (a standby is promotable warm
    # capacity regardless of what it will specialize into); "mixed"
    # maps to the internal "active" so fleets that never pass --role
    # emit the exact notes/registrations they always did
    if getattr(args, "standby", False):
        role = "standby"
    else:
        role = getattr(args, "role", "mixed")
        if role == "mixed":
            role = "active"
    server = InferenceServer(
        cfg, params, args.host, args.port, args.max_len,
        draft_layers=args.draft_layers, speculate=args.speculate,
        max_batch_rows=args.max_batch_rows,
        prefix_cache_entries=args.prefix_cache,
        kv_spill_bytes=int(args.kv_spill_mb * 1024 * 1024),
        prefill_chunk=args.prefill_chunk,
        slots=args.slots, slot_chunk=args.slot_chunk,
        slot_window=args.slot_window,
        text=args.text,
        cp_mesh=cp_mesh, cp_min_len=getattr(args, "cp_min_len", 0),
        mux=args.mux,
        role=role,
        compile_cache_dir=cache_dir or "",
    )
    member = None
    if backend is not None:
        from ..fleet import FleetMember

        member = FleetMember(
            server, backend, args.fleet_service,
            ttl=args.fleet_ttl, address=args.fleet_address,
            instance_id=args.fleet_id,
            migrate_window=args.migrate_window,
        )

    async def serve() -> None:
        import signal as signal_mod

        await server.run()
        if member is not None:
            # after run(): a --port 0 bind has resolved, and the
            # heartbeat only fires once warmup flips ready
            await member.start()
        stop = asyncio.Event()
        loop = asyncio.get_event_loop()
        for sig in (signal_mod.SIGTERM, signal_mod.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        if member is not None:
            # SIGTERM is a DRAIN, not an eviction: migrate live KV
            # to the survivors inside --migrate-window, flush the
            # mg= landings, deregister, finish in-flight — the same
            # path an autoscaler retire takes. Any migration failure
            # inside drain() degrades to the plain deregister this
            # branch used to be.
            await member.drain(timeout=30.0)
            await member.stop(deregister=False)
        await server.stop()

    asyncio.run(serve())
    return 0
