"""Multi-host serving: one HTTP frontend, a decode spanning the pod.

Models too large for one host's devices serve across hosts the same
way they train: every process joins the pod through the supervisor's
catalog (``parallel.distributed.initialize_from_catalog`` — the exact
rendezvous the training capstone uses), params shard over a GLOBAL
mesh with the training partition rules, and XLA's collectives carry
the decode over ICI within a host and DCN between hosts.

Process 0 is the frontend: it serves ``/health`` and
``POST /v1/generate`` (token-level, same request shape as the
single-host server's core knobs) and turns each request into a
fixed-shape operand bundle broadcast to the pod
(``multihost_utils.broadcast_one_to_all``). Every process — frontend
included — then runs the SAME jitted ``generate`` on the same
operands in the same order, which is all SPMD needs; process 0
fetches the replicated result and responds. Followers run the
broadcast-follow loop with no HTTP surface (their supervisor job
health-checks process liveness, e.g. ``kill -0
$CONTAINERPILOT_<JOB>_PID``).

Shutdown: SIGTERM on process 0 broadcasts a shutdown op so followers
exit cleanly.

Failure detection (``--watchdog``): serving gets the same
decode-progress deadline training has (parallel/watchdog.py). The
frontend broadcasts OP_HEARTBEAT whenever the pod is idle, so every
process — frontend and followers alike — completes a broadcast(+
decode) cycle at least every watchdog/4 seconds and beat()s its
StepWatchdog. A follower that wedges mid-decode (or dies) stalls the
NEXT cycle pod-wide: every peer's watchdog turns its silent
collective hang into a hard exit (code 86) the supervisor's restart
budgets absorb, and the reincarnated pod re-rendezvouses through the
catalog — a wedged-but-alive follower can no longer hang the
frontend indefinitely.

Parallelism: ``--dp`` splits the global device count into a
(data, model) mesh — ``--dp 2`` over 4 processes serves on a 2x2
dp x tp mesh (params sharded over model, replicated over data), so
tensor parallelism crosses process boundaries exactly as a real pod's
does.

    python -m containerpilot_tpu.workload.serve_dist \
        --process-id 0 --num-processes 2 --catalog 127.0.0.1:8500 \
        --port 8000 --d-model 1024 ...

Request sampling reproduces the single-host server's key convention
(fold_in(PRNGKey(seed), 0)), so answers are byte-identical to a
single-host server of the same config (tested with two real OS
processes on the CPU backend).
"""
from __future__ import annotations

import argparse
import functools
import json
import logging
import os
import queue
import threading
import time
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger("containerpilot.serve_dist")

from ..models.decode import BIAS_SLOTS_MAX

OP_SHUTDOWN = 0
OP_GENERATE = 1
OP_HEARTBEAT = 2  # idle liveness tick: bounds every broadcast wait
OP_SCORE = 3      # teacher-forced logprobs over the broadcast row

WATCHDOG_EXIT = 86  # parallel.watchdog.EXIT_CODE — same semantics


def _payload_zeros(max_len: int) -> Dict[str, np.ndarray]:
    return {
        "op": np.zeros((), np.int32),
        "prompt": np.zeros((max_len,), np.int32),
        "plen": np.zeros((), np.int32),
        "max_new": np.zeros((), np.int32),
        "temperature": np.zeros((), np.float32),
        "top_k": np.zeros((), np.int32),
        "top_p": np.zeros((), np.float32),
        "eos_id": np.full((), -1, np.int32),
        "seed": np.zeros((), np.int32),
        "min_new": np.zeros((), np.int32),
        "presence": np.zeros((), np.float32),
        "frequency": np.zeros((), np.float32),
        "bias_idx": np.full((BIAS_SLOTS_MAX,), -1, np.int32),
        "bias_val": np.zeros((BIAS_SLOTS_MAX,), np.float32),
        # > 0: stream the decode in K-token lockstep chunks (one tiny
        # per-chunk 'go' broadcast lets the frontend cancel mid-way)
        "chunk": np.zeros((), np.int32),
        # the UNbucketed request length: chunked emission caps here,
        # and it must be broadcast so every process derives the same
        # done decision (the chunk program's done mask is an operand)
        "max_new_req": np.zeros((), np.int32),
    }


def _payload_for(req: Dict[str, Any], max_len: int) -> Dict[str, np.ndarray]:
    p = _payload_zeros(max_len)
    tokens = req["tokens"]
    p["op"] = np.asarray(OP_GENERATE, np.int32)
    p["prompt"][: len(tokens)] = np.asarray(tokens, np.int32)
    p["plen"] = np.asarray(len(tokens), np.int32)
    # bucket the compiled decode length to multiples of 16 (the
    # single-host server's convention) — per-request max_new variation
    # must not recompile generate on EVERY host in the pod; the
    # frontend trims the response to the requested length
    bucketed = min(-(-req["max_new"] // 16) * 16, max_len - len(tokens))
    p["max_new"] = np.asarray(bucketed, np.int32)
    p["temperature"] = np.asarray(req.get("temperature", 0.0), np.float32)
    p["top_k"] = np.asarray(req.get("top_k", 0), np.int32)
    p["top_p"] = np.asarray(req.get("top_p", 0.0), np.float32)
    p["eos_id"] = np.asarray(req.get("eos_id", -1), np.int32)
    p["seed"] = np.asarray(req.get("seed", 0), np.int32)
    p["min_new"] = np.asarray(req.get("min_new", 0), np.int32)
    p["presence"] = np.asarray(req.get("presence", 0.0), np.float32)
    p["frequency"] = np.asarray(req.get("frequency", 0.0), np.float32)
    # int-coerce before sorting (str keys are OpenAI's wire form) and
    # bound at the static table size: parse_logit_bias upstream 422s
    # anything over it, so the slice is a defensive bound that can
    # never raise inside the pod loop (an IndexError here would be
    # pod-fatal — the loop deliberately re-raises)
    items = sorted(
        (int(t), float(v))
        for t, v in (req.get("logit_bias") or {}).items()
    )[:BIAS_SLOTS_MAX]
    for j, (tok_id, bias) in enumerate(items):
        p["bias_idx"][j] = tok_id
        p["bias_val"][j] = bias
    p["chunk"] = np.asarray(req.get("chunk", 0), np.int32)
    p["max_new_req"] = np.asarray(req["max_new"], np.int32)
    return p


def shard_params_global(params: Any, mesh, cfg) -> Any:
    """Place identically-initialized host params onto a multi-host
    mesh: each process contributes exactly the shards it addresses
    (``make_array_from_callback`` slices the host copy), so no data
    moves over DCN at load time."""
    from jax.sharding import NamedSharding

    from ..parallel.sharding import param_sharding_rules

    rules = param_sharding_rules(cfg, mesh)

    def put(leaf, spec):
        host = np.asarray(leaf)
        return jax.make_array_from_callback(
            host.shape, NamedSharding(mesh, spec),
            lambda idx: host[idx],
        )

    return jax.tree_util.tree_map(put, params, rules)


@functools.lru_cache(maxsize=8)
def _jitted_score_fn(cfg):
    from .modelcfg import score_logprobs_fn

    return jax.jit(score_logprobs_fn(cfg))


def _score_pod(params, cfg, payload, max_len: int):
    """Teacher-forced per-token logprobs of the broadcast row — the
    pod twin of the single-host /v1/score (the SAME jitted function,
    modelcfg.score_logprobs_fn); every process runs it in lockstep
    like a decode. Rows pad to a 16-multiple width (capped at
    max_len) so per-request length variation can't compile a fresh
    pod-wide program inside the watchdog deadline — causal attention
    makes the pad positions free, and the result slices back."""
    plen = int(payload["plen"])
    width = min(-(-plen // 16) * 16, max_len)
    toks = jnp.asarray(payload["prompt"][None, :width], jnp.int32)
    out = _jitted_score_fn(cfg)(params, toks)
    return out[:, : plen - 1]


def _stream_generate_pod(
    params, cfg, payload, max_len: int, multihost_utils, dog=None,
    emit=None, cancelled=None,
):
    """Chunked lockstep generation for SSE streaming: the slot
    engine's building blocks (1-slot pool, first_sample, K-token
    chunk program) run identically on every process, so emissions are
    byte-identical to the slot engine's — which is byte-identical to
    generate. Between chunks the frontend broadcasts a tiny ``go``
    scalar: a client disconnect (``cancelled``) stops the pod
    mid-generation with ONE more round-trip, and every round beats
    the watchdog. ``emit`` (frontend only) receives each delta."""
    from ..models.decode import _jitted_prefill
    from ..models.slots import (
        append_chunk,
        decode_slots_chunk,
        first_sample,
        insert_row,
        seed_counts,
        slot_cache,
    )

    plen = int(payload["plen"])
    max_new = int(payload["max_new_req"])
    chunk = int(payload["chunk"])
    eos_id = int(payload["eos_id"])
    prompt = jnp.asarray(payload["prompt"][None, :plen], jnp.int32)
    row_key = jax.random.fold_in(
        jax.random.PRNGKey(int(payload["seed"])), 0
    )
    logits, row_cache = _jitted_prefill(cfg, max_len)(params, prompt)
    first = first_sample(
        logits, row_key,
        float(payload["temperature"]), int(payload["top_k"]),
        float(payload["top_p"]), cfg, eos_id=eos_id,
        min_new=int(payload["min_new"]),
        bias_idx=jnp.asarray(payload["bias_idx"], jnp.int32),
        bias_val=jnp.asarray(payload["bias_val"], jnp.float32),
    )
    first_host = int(jax.device_get(first))
    emitted = [first_host]
    if emit is not None:
        emit(list(emitted))
    if dog is not None:
        dog.beat()

    pool = insert_row(slot_cache(cfg, 1, max_len), row_cache, 0, cfg)
    last = jnp.asarray([first_host], jnp.int32)
    keys = row_key[None]
    step_idx = np.asarray([1], np.int32)
    counts = seed_counts(cfg.vocab_size, first_host, eos_id)[None]
    done = first_host == eos_id or max_new <= 1

    def frontend_go() -> int:
        if emit is None:
            return 0  # followers' value is ignored by the broadcast
        if done or len(emitted) >= max_new:
            return 0
        if cancelled is not None and cancelled.is_set():
            return 0
        return 1

    while True:
        go = int(multihost_utils.broadcast_one_to_all(
            {"go": np.asarray(frontend_go(), np.int32)}
        )["go"])
        if not go:
            break
        (pool, last, done_dev, counts, toks) = decode_slots_chunk(
            params, pool, last, keys, jnp.asarray(step_idx),
            jnp.asarray([float(payload["temperature"])], jnp.float32),
            jnp.asarray([int(payload["top_k"])], jnp.int32),
            jnp.asarray([float(payload["top_p"])], jnp.float32),
            jnp.asarray([eos_id], jnp.int32),
            jnp.asarray([0], jnp.int32),
            jnp.asarray([int(payload["min_new"])], jnp.int32),
            jnp.asarray([float(payload["presence"])], jnp.float32),
            jnp.asarray([float(payload["frequency"])], jnp.float32),
            jnp.asarray(payload["bias_idx"][None], jnp.int32),
            jnp.asarray(payload["bias_val"][None], jnp.float32),
            counts,
            jnp.asarray([done], bool),
            cfg, chunk,
        )
        step_idx = step_idx + chunk
        toks_host = np.asarray(jax.device_get(toks))[0]
        # the slot engine's SHARED append rules (models/slots.py) —
        # every process derives the same ``done``
        before = len(emitted)
        done = append_chunk(emitted, toks_host, max_new, eos_id)
        if emit is not None and len(emitted) > before:
            emit(list(emitted[before:]))
        if dog is not None:
            dog.beat()
    return emitted


def _decode_pod(params, cfg, payload, max_len: int):
    """The SPMD part every process runs identically: one generate call
    shaped purely by broadcast scalars (so every host traces and
    executes the same program in the same order)."""
    from ..models.decode import generate

    plen = int(payload["plen"])
    max_new = int(payload["max_new"])
    prompt = jnp.asarray(payload["prompt"][None, :plen], jnp.int32)
    row_key = jax.random.fold_in(
        jax.random.PRNGKey(int(payload["seed"])), 0
    )
    # rebuild the dict form generate expects; every host derives the
    # identical dict from the identical broadcast arrays
    bias = {
        int(i): float(v)
        for i, v in zip(payload["bias_idx"], payload["bias_val"])
        if int(i) >= 0
    }
    return generate(
        params, prompt, cfg, max_new_tokens=max_new, max_len=max_len,
        temperature=float(payload["temperature"]),
        rng=jnp.stack([row_key]),
        top_k=int(payload["top_k"]),
        top_p=float(payload["top_p"]),
        eos_id=int(payload["eos_id"]),
        min_new_tokens=int(payload["min_new"]),
        presence_penalty=float(payload["presence"]),
        frequency_penalty=float(payload["frequency"]),
        logit_bias=bias or None,
    )


class _Frontend:
    """Process 0's HTTP surface: requests land in a queue the pod
    loop drains; the loop owns all device work."""

    def __init__(self, host: str, port: int, max_len: int,
                 vocab: int, pod_info: Optional[Dict[str, Any]] = None,
                 text: bool = False, stream_chunk: int = 8,
                 ) -> None:
        from prometheus_client import (
            CollectorRegistry,
            Counter,
            Histogram,
        )

        from ..utils.http import HTTPServer, Response

        self.max_len = max_len
        self.vocab = vocab
        self.ready = False
        # /v1/model payload: model config + pod topology, set by main()
        self.pod_info = pod_info or {}
        self.stream_chunk = max(int(stream_chunk), 1)
        self.requests: "queue.Queue[Tuple[dict, queue.Queue]]" = (
            queue.Queue()
        )
        # observability parity with the single-host server: a private
        # registry (an in-process supervisor's metrics never collide)
        self._registry = CollectorRegistry()
        self._m_requests = Counter(
            "containerpilot_pod_requests",
            "pod frontend requests by endpoint and status",
            ["endpoint", "status"], registry=self._registry,
        )
        self._m_latency = Histogram(
            "containerpilot_pod_request_seconds",
            "pod request latency (broadcast + lockstep decode)",
            registry=self._registry,
            buckets=(.05, .1, .25, .5, 1, 2.5, 5, 10, 30, 60, 120),
        )
        self._m_tokens = Counter(
            "containerpilot_pod_generated_tokens",
            "tokens returned by the pod frontend (post-trim)",
            registry=self._registry,
        )
        self._server = HTTPServer()
        self._server.route("GET", "/health", self._health)
        self._server.route("GET", "/metrics", self._metrics)
        self._server.route("GET", "/v1/model", self._model)
        self._server.route("POST", "/v1/generate", self._generate)
        self._server.route("POST", "/v1/score", self._score)
        # text surface: byte-level tokenizer, zero external assets —
        # the single-host server's --text, pod-shaped
        self.tokenizer = None
        if text:
            from .text import ByteTokenizer

            self.tokenizer = ByteTokenizer(vocab)
            self._server.route(
                "POST", "/v1/completions", self._completions
            )
        self._host, self._port = host, port
        self._Response = Response
        self._loop = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.bound_port or self._port

    async def _dispatch(self, endpoint: str, work: Dict[str, Any]):
        """queue → pod loop → result, with the latency/500 accounting
        every endpoint shares. Returns (result, None) on success or
        (None, 500 Response) on a pod-side failure."""
        import asyncio

        t0 = time.perf_counter()
        done: "queue.Queue" = queue.Queue()
        self.requests.put((work, done))
        result = await asyncio.get_event_loop().run_in_executor(
            None, done.get
        )
        self._m_latency.observe(time.perf_counter() - t0)
        if isinstance(result, Exception):
            self._m_requests.labels(endpoint, "500").inc()
            return None, self._Response(500, f"{result}\n".encode())
        self._m_requests.labels(endpoint, "200").inc()
        return result, None

    async def _health(self, _req):
        if not self.ready:
            return self._Response(503, b"warming\n")
        return self._Response(200, b"ok\n")

    async def _metrics(self, _req):
        from ..utils.prom import exposition

        body, content_type = exposition(self._registry)
        return self._Response(200, body, content_type=content_type)

    async def _model(self, _req):
        self._m_requests.labels("model", "200").inc()
        return self._Response(
            200, json.dumps(self.pod_info).encode(),
            content_type="application/json",
        )

    def _parse_work(self, body, tokens, default_eos: int = -1):
        """Validate the sampling knobs shared by /v1/generate and the
        --text surface into a broadcastable work dict. Full knob
        validation HERE: a malformed value that only failed inside
        _decode_pod would be pod-fatal (the loop deliberately
        re-raises collective-path errors), and an out-of-int32 value
        would crash payload packing. Raises ValueError for a 422."""
        if int(body.get("n", 1)) != 1:
            # loud 422, not a silent one-sample 200 the client
            # would mis-index (the single-host server supports n)
            raise ValueError(
                "the pod frontend serves single-sample requests; "
                "n > 1 is a single-host server feature"
            )
        for knob in ("stop", "logprobs", "beam_width"):
            # same rule: single-host features the broadcast payload
            # does not carry must fail loudly, never silently drop
            if body.get(knob):
                raise ValueError(
                    f"the pod frontend does not support {knob!r}; "
                    "it is a single-host server feature"
                )
        max_new = int(body.get("max_new_tokens", 16))
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(tokens) + max_new > self.max_len:
            raise ValueError(
                f"prompt + max_new_tokens exceeds max_len "
                f"{self.max_len}"
            )
        top_k = int(body.get("top_k", 0))
        top_p = float(body.get("top_p", 0.0))
        eos_id = int(body.get("eos_id", default_eos))
        seed = int(body.get("seed", 0))
        if not 0 <= top_k <= self.vocab:
            raise ValueError(f"top_k must be in [0, {self.vocab}]")
        if not 0.0 <= top_p <= 1.0:
            raise ValueError("top_p must be in [0, 1]")
        if eos_id >= self.vocab:
            raise ValueError(f"eos_id must be < {self.vocab}")
        if not -(2**31) <= seed < 2**31:
            raise ValueError("seed must fit in int32")
        min_new = int(body.get("min_new_tokens", 0))
        if not 0 <= min_new <= max_new:
            raise ValueError(
                "min_new_tokens must be in [0, max_new_tokens]"
            )
        presence = float(body.get("presence_penalty", 0.0))
        frequency = float(body.get("frequency_penalty", 0.0))
        if not (abs(presence) <= 100 and abs(frequency) <= 100):
            raise ValueError(
                "presence/frequency penalties must be in "
                "[-100, 100]"
            )
        from .modelcfg import parse_logit_bias

        bias = parse_logit_bias(
            body.get("logit_bias"), self.vocab
        ) or {}
        return {
            "tokens": tokens, "max_new": max_new,
            "temperature": float(body.get("temperature", 0.0)),
            "top_k": top_k,
            "top_p": top_p,
            "eos_id": max(eos_id, -1),
            "seed": seed,
            "min_new": min_new,
            "presence": presence,
            "frequency": frequency,
            "logit_bias": bias,
        }

    def _parse_single_row(self, body, min_len: int = 1):
        rows = body.get("tokens")
        if (
            not isinstance(rows, list) or len(rows) != 1
            or not isinstance(rows[0], list)
            or len(rows[0]) < min_len
        ):
            raise ValueError(
                f"'tokens' must be one row of at least {min_len} "
                "ids (the pod frontend serves single-row requests)"
            )
        tokens = rows[0]
        if any(
            not isinstance(t, int) or isinstance(t, bool)
            or t < 0 or t >= self.vocab
            for t in tokens
        ):
            raise ValueError(
                f"token ids must be integers in [0, {self.vocab})"
            )
        return tokens

    async def _generate(self, req):
        try:
            body = json.loads(req.body.decode() or "{}")
            work = self._parse_work(body, self._parse_single_row(body))
        except (ValueError, KeyError, TypeError, OverflowError) as exc:
            self._m_requests.labels("generate", "422").inc()
            return self._Response(422, f"{exc}\n".encode())
        if bool(body.get("stream", False)):
            return self._generate_stream(work)
        result, err = await self._dispatch("generate", work)
        if err is not None:
            return err
        self._m_tokens.inc(len(result))
        return self._Response(
            200, json.dumps({"tokens": [result]}).encode(),
            content_type="application/json",
        )

    async def _completions(self, req):
        """Text in/out around the same broadcast decode /v1/generate
        uses: encode the prompt through the byte tokenizer, default
        eos to the tokenizer's EOS, decode the generated ids back —
        the single-host /v1/completions contract, pod-shaped."""
        tok = self.tokenizer
        try:
            body = json.loads(req.body.decode() or "{}")
            prompt = body.get("prompt")
            if not isinstance(prompt, str) or not prompt:
                raise ValueError("'prompt' must be a non-empty string")
            row = tok.encode(prompt)
            if len(row) >= self.max_len:
                raise ValueError(
                    f"prompt encodes to {len(row)} ids; max_len is "
                    f"{self.max_len}"
                )
            if bool(body.get("stream", False)):
                raise ValueError(
                    "the pod text surface does not stream; use "
                    "/v1/generate with \"stream\": true"
                )
            work = self._parse_work(body, row, default_eos=tok.EOS)
        except (ValueError, KeyError, TypeError, OverflowError) as exc:
            self._m_requests.labels("completions", "422").inc()
            return self._Response(422, f"{exc}\n".encode())
        result, err = await self._dispatch("completions", work)
        if err is not None:
            return err
        self._m_tokens.inc(len(result))
        return self._Response(
            200,
            json.dumps(
                {"text": tok.decode(result), "tokens": result}
            ).encode(),
            content_type="application/json",
        )

    def _generate_stream(self, work):
        """SSE over the pod's chunked lockstep decode: each K-token
        delta becomes a ``data:`` event as its broadcast round lands;
        concatenated deltas equal the non-streamed pod answer. A
        client disconnect sets the cancel event — the frontend stops
        broadcasting ``go`` and the whole pod abandons the request at
        the next chunk boundary."""
        import asyncio
        import threading as threading_mod

        from ..utils.http import StreamingResponse

        cancel = threading_mod.Event()
        work = dict(work, chunk=self.stream_chunk, _cancel=cancel)
        done: "queue.Queue" = queue.Queue()
        t0 = time.perf_counter()
        self.requests.put((work, done))
        sent = [0]
        status = ["200"]
        finished = [False]

        def finish() -> None:
            if finished[0]:
                return
            finished[0] = True
            cancel.set()
            self._m_latency.observe(time.perf_counter() - t0)
            self._m_tokens.inc(sent[0])
            self._m_requests.labels("generate", status[0]).inc()

        def sse(payload) -> bytes:
            return b"data: " + json.dumps(payload).encode() + b"\n\n"

        async def events():
            loop = asyncio.get_event_loop()
            try:
                while True:
                    item = await loop.run_in_executor(None, done.get)
                    if isinstance(item, Exception):
                        status[0] = "500"
                        yield sse({"error": str(item)})
                        break
                    kind, val = item
                    if kind == "delta":
                        sent[0] += len(val)
                        yield sse({"tokens": val})
                    else:
                        yield sse({"done": True, "count": sent[0]})
                        break
            finally:
                finish()

        return StreamingResponse(events(), close=finish)

    async def _score(self, req):
        import asyncio

        try:
            body = json.loads(req.body.decode() or "{}")
            tokens = self._parse_single_row(body, min_len=2)
            if len(tokens) > self.max_len:
                raise ValueError(
                    f"row length exceeds max_len {self.max_len}"
                )
        except (ValueError, KeyError, TypeError) as exc:
            self._m_requests.labels("score", "422").inc()
            return self._Response(422, f"{exc}\n".encode())
        result, err = await self._dispatch("score", {"score": tokens})
        if err is not None:
            return err
        return self._Response(
            200,
            json.dumps(
                {
                    "logprobs": [[round(float(x), 6) for x in row]
                                 for row in result],
                    "sums": [round(float(sum(row)), 6)
                             for row in result],
                }
            ).encode(),
            content_type="application/json",
        )

    def start(self) -> None:
        import asyncio

        started = threading.Event()

        def run() -> None:
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            loop.run_until_complete(
                self._server.start_tcp(self._host, self._port)
            )
            started.set()
            loop.run_forever()

        self._thread = threading.Thread(
            target=run, name="serve-dist-http", daemon=True
        )
        self._thread.start()
        if not started.wait(timeout=30):
            raise RuntimeError("frontend never bound")

    def stop(self) -> None:
        import asyncio

        if self._loop is not None:
            async def shutdown() -> None:
                await self._server.stop()
                asyncio.get_event_loop().stop()

            self._loop.call_soon_threadsafe(
                lambda: asyncio.ensure_future(shutdown())
            )
        if self._thread is not None:
            self._thread.join(timeout=10)


def main() -> int:
    from jax.experimental import multihost_utils

    from ..discovery.consul import ConsulBackend
    from ..models.transformer import TransformerConfig, init_params
    from ..parallel import MeshPlan, initialize_from_catalog, make_mesh
    from .modelcfg import derive_d_ff, enable_compile_cache

    enable_compile_cache()

    parser = argparse.ArgumentParser(
        description="multi-host pod inference server"
    )
    parser.add_argument("--process-id", type=int, required=True)
    parser.add_argument("--num-processes", type=int, required=True)
    parser.add_argument("--catalog", required=True)
    parser.add_argument("--coordinator-port", type=int, default=0)
    parser.add_argument("--advertise-address", default="")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--max-len", type=int, default=512)
    parser.add_argument("--d-model", type=int, default=256)
    parser.add_argument("--n-layers", type=int, default=2)
    parser.add_argument("--n-heads", type=int, default=4)
    parser.add_argument("--n-kv-heads", type=int, default=0)
    parser.add_argument("--vocab", type=int, default=1024)
    parser.add_argument("--checkpoint-dir", default="",
                        help="shared-storage checkpoint the WHOLE pod "
                        "restores in lockstep (orbax is a global "
                        "checkpointer)")
    parser.add_argument("--use-ema", action="store_true")
    parser.add_argument("--stream-chunk", type=int, default=8,
                        help="tokens per SSE delta when a request "
                        "sets \"stream\": true (one lockstep "
                        "broadcast round per chunk)")
    parser.add_argument("--text", action="store_true",
                        help="byte-tokenizer /v1/completions on the "
                        "frontend (vocab must be >= 259)")
    parser.add_argument("--dp", type=int, default=1,
                        help="data-parallel axis size: the global "
                        "device count factors as (dp, devices/dp) — "
                        "model shards over the inner axis")
    parser.add_argument("--watchdog", type=float, default=0.0,
                        help="decode-progress deadline in seconds "
                        "(0 = off): every process hard-exits %d when "
                        "a broadcast+decode cycle stalls past it, so "
                        "a wedged peer becomes a supervisor restart "
                        "instead of a silent pod hang"
                        % WATCHDOG_EXIT)
    parser.add_argument("--startup-grace", type=float, default=300.0,
                        help="first-beat grace covering rendezvous + "
                        "restore + warmup compile")
    parser.add_argument("--wedge-file", default="",
                        help="fault injection (tests): when this file "
                        "exists, a follower consumes it and wedges — "
                        "stops making progress without exiting — to "
                        "prove the watchdog path")
    args = parser.parse_args()

    # armed BEFORE rendezvous (the trainer's pattern): a peer that
    # died between catalog registration and its first collective
    # wedges our rendezvous/warmup just as silently as a mid-serve
    # death, and the grace window covers the startup compile
    dog = None
    if args.watchdog > 0:
        from ..parallel import StepWatchdog

        dog = StepWatchdog(
            args.watchdog, exit_code=WATCHDOG_EXIT
        ).start(grace_s=max(args.startup_grace, args.watchdog))

    kw = {}
    if args.coordinator_port:
        kw["coordinator_port"] = args.coordinator_port
    initialize_from_catalog(
        ConsulBackend(address=args.catalog),
        args.process_id,
        args.num_processes,
        advertise_address=args.advertise_address,
        **kw,
    )
    cfg = TransformerConfig(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads,
        n_layers=args.n_layers,
        d_ff=derive_d_ff(args.d_model),
        max_seq_len=args.max_len,
    )
    if args.text:
        from .text import ByteTokenizer

        if args.vocab < ByteTokenizer.N_IDS:
            # EVERY process must fail here, not just the frontend:
            # a frontend dying after rendezvous would strand the
            # followers in their first broadcast
            raise SystemExit(
                f"--text needs vocab >= {ByteTokenizer.N_IDS}, got "
                f"{args.vocab}"
            )
    n_global = jax.device_count()
    if args.dp < 1 or n_global % args.dp:
        raise SystemExit(
            f"--dp {args.dp} must divide the {n_global} global devices"
        )
    n_model = n_global // args.dp
    if cfg.n_heads % n_model:
        raise SystemExit(
            f"model axis {n_model} must divide n_heads {cfg.n_heads}"
        )
    mesh = make_mesh(
        jax.devices(), plan=MeshPlan(data=args.dp, model=n_model)
    )
    if args.checkpoint_dir:
        from .modelcfg import restore_params_only

        restored = restore_params_only(
            cfg, mesh, args.checkpoint_dir, use_ema=args.use_ema
        )
        if restored is None:
            raise SystemExit(f"no checkpoint in {args.checkpoint_dir}")
        params, step = restored
        if args.process_id == 0:
            print(f"pod serving checkpoint step {step}", flush=True)
    else:
        host_params = jax.tree.map(
            np.asarray, init_params(jax.random.PRNGKey(0), cfg)
        )
        params = shard_params_global(host_params, mesh, cfg)

    frontend = None
    if args.process_id == 0:
        frontend = _Frontend(
            args.host, args.port, args.max_len, cfg.vocab_size,
            text=args.text, stream_chunk=args.stream_chunk,
            pod_info={
                "vocab_size": cfg.vocab_size,
                "d_model": cfg.d_model,
                "n_heads": cfg.n_heads,
                "n_kv_heads": cfg.kv_heads,
                "n_layers": cfg.n_layers,
                "max_len": args.max_len,
                "text": args.text,
                "stream": True,
                "pod": {
                    "num_processes": args.num_processes,
                    "devices": n_global,
                    "mesh": {"data": args.dp, "model": n_model},
                    "watchdog_s": args.watchdog or None,
                },
            },
        )
        frontend.start()
        print(f"pod frontend on {args.host}:{frontend.port} "
              f"({n_global} global devices, data={args.dp} "
              f"model={n_model})",
              flush=True)

    # warmup in lockstep before /health goes 200: same dummy payload
    # everywhere, so the pod's first live request doesn't compile
    warm = _payload_for(
        {"tokens": [0, 0, 0, 0], "max_new": 8}, args.max_len
    )
    np.asarray(_decode_pod(params, cfg, warm, args.max_len))
    # the stream path's programs (prefill, first-sample, the 1-slot
    # chunk) must compile inside the SAME startup grace — a cold
    # first streamed request would otherwise hold a broadcast round
    # open past the tightened watchdog deadline, pod-wide. Every
    # process derives the identical warm payload from its own flags.
    warm_stream = _payload_for(
        {"tokens": [0, 0, 0, 0], "max_new": args.stream_chunk + 1,
         "chunk": args.stream_chunk},
        args.max_len,
    )
    _stream_generate_pod(
        params, cfg, warm_stream, args.max_len, multihost_utils
    )
    if dog is not None:
        dog.beat()  # startup done: tighten to the serve deadline
    if frontend is not None:
        frontend.ready = True
        print("pod warm; accepting traffic", flush=True)

    # graceful pod shutdown: TERM on the FRONTEND broadcasts
    # OP_SHUTDOWN so followers exit cleanly. Followers keep the
    # default TERM disposition — a follower can't exit mid-collective
    # anyway, so its supervisor's TERM-then-KILL handles it.
    stopping = threading.Event()
    if frontend is not None:
        import signal as signal_mod

        signal_mod.signal(
            signal_mod.SIGTERM, lambda s, f: stopping.set()
        )

    from .serve import InferenceServer

    # the pod must tick at least this often for followers' broadcast
    # waits to be bounded (the watchdog can only see completed cycles)
    heartbeat_every = args.watchdog / 4 if args.watchdog > 0 else None

    while True:
        work = done_q = None
        if frontend is not None:
            idle_since = time.monotonic()
            while work is None and not stopping.is_set():
                try:
                    work, done_q = frontend.requests.get(timeout=0.25)
                except queue.Empty:
                    if (
                        heartbeat_every is not None
                        and time.monotonic() - idle_since
                        >= heartbeat_every
                    ):
                        break  # tick the pod, then resume waiting
                    continue
            if stopping.is_set():
                payload = _payload_zeros(args.max_len)
            elif work is None:
                payload = _payload_zeros(args.max_len)
                payload["op"] = np.asarray(OP_HEARTBEAT, np.int32)
            elif "score" in work:
                payload = _payload_zeros(args.max_len)
                payload["op"] = np.asarray(OP_SCORE, np.int32)
                row = work["score"]
                payload["prompt"][: len(row)] = np.asarray(
                    row, np.int32
                )
                payload["plen"] = np.asarray(len(row), np.int32)
            else:
                payload = _payload_for(work, args.max_len)
        else:
            payload = _payload_zeros(args.max_len)
            if args.wedge_file and os.path.exists(args.wedge_file):
                # fault injection: consume the trigger (wedge ONCE, so
                # the reincarnation comes back healthy) and stop
                # making progress without exiting — exactly what a
                # stuck decode looks like to the rest of the pod
                try:
                    os.remove(args.wedge_file)
                except OSError:
                    pass
                print("follower: injected wedge", flush=True)
                while True:
                    time.sleep(3600)
        payload = multihost_utils.broadcast_one_to_all(payload)
        op = int(payload["op"])
        if op == OP_HEARTBEAT:
            if dog is not None:
                dog.beat()
            continue
        if op == OP_SHUTDOWN:
            # SIGTERM may have raced an in-flight dequeue (and more
            # requests may still be queued): every waiting handler
            # must get an answer or its executor thread blocks
            # forever and the interpreter can't exit
            if frontend is not None:
                leftovers = [done_q] if done_q is not None else []
                while True:
                    try:
                        _w, dq = frontend.requests.get_nowait()
                        leftovers.append(dq)
                    except queue.Empty:
                        break
                for dq in leftovers:
                    dq.put(RuntimeError("pod is shutting down"))
            break
        try:
            if op == OP_SCORE:
                out = _score_pod(params, cfg, payload, args.max_len)
                if dog is not None:
                    dog.beat()
                if done_q is not None:
                    done_q.put(np.asarray(out).tolist())
                continue
            if op == OP_GENERATE and int(payload["chunk"]) > 0:
                emit = cancelled = None
                if done_q is not None:
                    emit = lambda d: done_q.put(("delta", d))  # noqa: E731
                    cancelled = work.get("_cancel")
                _stream_generate_pod(
                    params, cfg, payload, args.max_len,
                    multihost_utils, dog=dog, emit=emit,
                    cancelled=cancelled,
                )
                if done_q is not None:
                    done_q.put(("end", None))
                continue
            out = _decode_pod(params, cfg, payload, args.max_len)
            if dog is not None:
                dog.beat()
            if done_q is not None:
                # one trim convention pod-wide: the single-host
                # server's (slice to the REQUESTED length, then cut
                # at eos inclusive)
                row = [int(t) for t in np.asarray(out)[0]]
                done_q.put(InferenceServer._trim(
                    [row], work["max_new"], int(payload["eos_id"])
                )[0])
        except Exception as exc:  # noqa: BLE001 — pod-fatal
            if done_q is not None:
                done_q.put(exc)
            raise
    if dog is not None:
        dog.stop()
    if frontend is not None:
        frontend.stop()
        print("pod frontend stopped", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
