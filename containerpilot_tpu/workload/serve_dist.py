"""Multi-host serving: one HTTP frontend, a slot-pool decode spanning
the pod.

Models too large for one host's devices serve across hosts the same
way they train: every process joins the pod through the supervisor's
catalog (``parallel.distributed.initialize_from_catalog`` — the exact
rendezvous the training capstone uses), params shard over a GLOBAL
mesh with the training partition rules, and XLA's collectives carry
the decode over ICI within a host and DCN between hosts.

The pod runs the SAME continuous-batching slot engine the single-host
server does (``models/slots.py``), made SPMD: a fixed pool of
``--slots`` per-request cache rows decodes in ``--stream-chunk``-token
lockstep chunks, and between chunks process 0 broadcasts one
fixed-shape ROUND payload (``multihost_utils.broadcast_one_to_all``)
carrying this round's admission (at most one new request row: prompt,
knobs, key), the per-slot active mask, and whether to run a chunk.
Every process — frontend included — replays the identical device ops
(`_SlotMirror`: prefill+insert for the admission, then the one
compiled chunk program); process 0 alone keeps the HTTP bookkeeping
(emitted tokens, retirement, SSE deltas). Requests therefore JOIN a
running decode at the next chunk boundary instead of queueing behind
another request's whole generation — N concurrent requests, streamed
and non-streamed, with per-request output byte-identical to a solo
single-host ``generate`` (the engine's tested invariant).

Frontend surface (process 0): ``/health``, ``/metrics``, ``/v1/model``,
``POST /v1/generate`` (token-level; the single-host server's knobs
including ``n``, ``stop``, ``logprobs``, ``beam_width``, ``stream``),
``POST /v1/score``, and behind ``--text`` ``POST /v1/completions``
(byte tokenizer, streamed or not, with UTF-8 holdback). ``logprobs``
echoes ride extra lockstep score rounds after a request retires; beams
run as a one-shot lockstep round. Followers run the broadcast-follow
loop with no HTTP surface (their supervisor job health-checks process
liveness, e.g. ``kill -0 $CONTAINERPILOT_<JOB>_PID``).

Shutdown: SIGTERM on process 0 broadcasts a shutdown op so followers
exit cleanly.

Failure detection (``--watchdog``): serving gets the same
decode-progress deadline training has (parallel/watchdog.py). The
frontend broadcasts OP_HEARTBEAT whenever the pod is idle, and every
ROUND is bounded by one chunk of decode — so every process completes
a broadcast(+device) cycle at least every watchdog/4 seconds and
beat()s its StepWatchdog. A follower that wedges mid-decode (or dies)
stalls the NEXT cycle pod-wide: every peer's watchdog turns its silent
collective hang into a hard exit (code 86) the supervisor's restart
budgets absorb, and the reincarnated pod re-rendezvouses through the
catalog. Because ALL generation (streamed or not) now rides chunked
rounds, no legitimate long request can outlast the deadline — only
one-shot ops (a beam round, a score round, an unwarmed-shape compile)
must individually finish inside it; size ``--watchdog`` above the
slowest of those.

Parallelism: ``--dp`` splits the global device count into a
(data, model) mesh — ``--dp 2`` over 4 processes serves on a 2x2
dp x tp mesh (params sharded over model, replicated over data), so
tensor parallelism crosses process boundaries exactly as a real pod's
does. ``--kv-int8`` serves with the int8 KV cache (half the KV bytes;
identical quantized numerics on every process); ``--window`` serves
sliding-window attention over per-slot ring caches (KV memory bounded
by the window, not --max-len) — both are static model config, so
every process's lockstep dispatch is unchanged. ``--sp`` adds a seq
axis (dp x sp x tp mesh): prompts at least ``--cp-min-len`` long ring
their prefill over it (parallel/context.py — per-device activation
memory bounded by prompt/sp), then decode on the replicated slot
pool; the cp decision reads only static flags plus the broadcast
plen, so it is lockstep by construction.

    python -m containerpilot_tpu.workload.serve_dist \
        --process-id 0 --num-processes 2 --catalog 127.0.0.1:8500 \
        --port 8000 --d-model 1024 ...

Request sampling reproduces the single-host server's key convention
(row i of a request draws from fold_in(PRNGKey(seed), i)), so answers
are byte-identical to a single-host server of the same config (tested
with real OS processes on the CPU backend, including co-batched
traffic).
"""
from __future__ import annotations

import argparse
import functools
import json
import logging
import os
import queue
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger("containerpilot.serve_dist")

from ..models.decode import BIAS_SLOTS_MAX

OP_SHUTDOWN = 0
OP_ROUND = 1      # slot-engine round: optional admission + one chunk
OP_HEARTBEAT = 2  # idle liveness tick: bounds every broadcast wait
OP_SCORE = 3      # teacher-forced logprobs over the broadcast row
OP_BEAM = 4       # one-shot lockstep beam search
OP_SPEC = 5       # one-shot lockstep speculative (draft-and-verify)

WATCHDOG_EXIT = 86  # parallel.watchdog.EXIT_CODE — same semantics


def _payload_zeros(max_len: int, slots: int) -> Dict[str, np.ndarray]:
    """The ONE broadcast structure every round uses (a collective
    broadcast needs identical pytrees on every process, so heartbeat,
    score, beam, shutdown, and slot rounds all ship this shape)."""
    return {
        "op": np.zeros((), np.int32),
        # the single row a round can carry: a score/beam request's
        # tokens, or this round's admission prompt
        "prompt": np.zeros((max_len,), np.int32),
        "plen": np.zeros((), np.int32),
        # admission (admit_slot -1 = none this round); row_idx is the
        # row's index within its request — the key schedule
        # fold_in(PRNGKey(seed), row_idx) is the server convention
        "admit_slot": np.full((), -1, np.int32),
        "row_idx": np.zeros((), np.int32),
        "max_new_req": np.zeros((), np.int32),
        "temperature": np.zeros((), np.float32),
        "top_k": np.zeros((), np.int32),
        "top_p": np.zeros((), np.float32),
        "eos_id": np.full((), -1, np.int32),
        "seed": np.zeros((), np.int32),
        "min_new": np.zeros((), np.int32),
        "presence": np.zeros((), np.float32),
        "frequency": np.zeros((), np.float32),
        "bias_idx": np.full((BIAS_SLOTS_MAX,), -1, np.int32),
        "bias_val": np.zeros((BIAS_SLOTS_MAX,), np.float32),
        # beam round operands
        "beam_width": np.zeros((), np.int32),
        "length_penalty": np.zeros((), np.float32),
        # chunk control: run the (slots, chunk) program this round,
        # with this pre-chunk inactive mask (1 = slot is dead; evicted
        # slots — disconnects, stop matches — flip to 1 here)
        "run_chunk": np.zeros((), np.int32),
        "done": np.ones((slots,), np.int32),
        # fused decode: run this many chunk-rounds in ONE device
        # dispatch (the (S, chunk, K) window program, early-exiting
        # when every slot is done or out of ``budget`` tokens);
        # 1 = the classic single-chunk round. The frontend fuses only
        # pure-decode rounds — admissions, queued work, cancels and
        # stop-sequence watches keep chunk granularity — so followers
        # replay the identical program by construction.
        "rounds": np.ones((), np.int32),
        "budget": np.zeros((slots,), np.int32),
    }


def _fill_admission(payload, work: Dict[str, Any], row_idx: int,
                    slot: int) -> None:
    """Pack one request row's admission into the round payload."""
    tokens = work["tokens"]
    payload["prompt"][: len(tokens)] = np.asarray(tokens, np.int32)
    payload["plen"] = np.asarray(len(tokens), np.int32)
    payload["admit_slot"] = np.asarray(slot, np.int32)
    payload["row_idx"] = np.asarray(row_idx, np.int32)
    payload["max_new_req"] = np.asarray(work["max_new"], np.int32)
    payload["temperature"] = np.asarray(work["temperature"], np.float32)
    payload["top_k"] = np.asarray(work["top_k"], np.int32)
    payload["top_p"] = np.asarray(work["top_p"], np.float32)
    payload["eos_id"] = np.asarray(work["eos_id"], np.int32)
    payload["seed"] = np.asarray(work["seed"], np.int32)
    payload["min_new"] = np.asarray(work["min_new"], np.int32)
    payload["presence"] = np.asarray(work["presence"], np.float32)
    payload["frequency"] = np.asarray(work["frequency"], np.float32)
    # parse_logit_bias upstream coerces keys and caps at
    # BIAS_SLOTS_MAX; the slice is a defensive bound that can never
    # raise inside the pod loop (an error here would be pod-fatal)
    items = sorted((work.get("logit_bias") or {}).items())[
        :BIAS_SLOTS_MAX
    ]
    for j, (tok_id, bias) in enumerate(items):
        payload["bias_idx"][j] = tok_id
        payload["bias_val"][j] = bias


class _SlotMirror:
    """The device half of the slot engine, replayed identically on
    every process: a fixed pool of single-row caches plus the host
    knob arrays the chunk program reads. All mutations are driven by
    broadcast ROUND payloads, so frontend and followers hold
    bit-identical state without ever exchanging it.

    ``mesh`` (the pod's global mesh) pins EVERY device buffer the
    mirror owns to an explicit fully-replicated sharding: without the
    pin, each jitted update leaves the pool in whatever output
    sharding GSPMD picks for that program, and a pool drifting
    between layouts across donating programs corrupted decodes
    (observed as deterministic wrong tokens in the 2-process pod).
    Replication is also the honest layout — every process must hold
    the whole pool to keep lockstep admission/retirement purely
    host-side."""

    def __init__(self, cfg, params, max_len: int, slots: int,
                 chunk: int, mesh=None, sp: int = 1,
                 cp_min_len: int = 0, prefix_entries: int = 0,
                 prefill_chunk: int = 0, window: int = 1) -> None:
        from ..models.slots import init_slot_state, slot_cache

        self.cfg = cfg
        self.params = params
        self.max_len = max_len
        self.slots = slots
        self.chunk = chunk
        # fused window size K: the frontend may broadcast rounds=K on
        # pure-decode rounds; every process compiles the same
        # (S, chunk, K) window program at warmup
        self.window = max(1, int(window))
        self.mesh = mesh
        # context-parallel admission (``--sp``): prompts at least
        # cp_min_len long ring a STARTUP-COMPILED head bucket over the
        # mesh's seq axis and extend the remainder locally
        # (parallel/context.py — ring programs are the pod's only
        # cross-process collectives outside the broadcast, and a
        # first-use collective's communicator init has a hard ~30s
        # deadline request-time compile skew can blow, so every ring
        # shape must exist before traffic; see cp_head_buckets). Both
        # knobs are static flags and plen rides the broadcast, so
        # every process picks the same path — lockstep by
        # construction.
        self.sp = sp
        self.cp_min_len = cp_min_len
        # prefix KV reuse, lockstep by construction: every process
        # keeps an IDENTICAL PrefixCache instance whose state evolves
        # only through broadcast admissions (same prompts, same order
        # -> same matches, stores, and LRU evictions everywhere).
        # Entries are standalone buffers: extend never donates its
        # cache operand and insert_row copies the row into the
        # (donated) pool. The frontend reads .stats for /v1/model.
        # chunked admission (``--prefill-chunk``): local programs
        # with a bounded piece-length set — compile skew between
        # processes only delays the slower one, unlike collectives
        self.prefill_chunk = prefill_chunk
        self.prefix_cache = None
        self._repin = None
        if prefix_entries > 0:
            from .serve_prefix import PrefixCache

            self.prefix_cache = PrefixCache(prefix_entries)
        self.cp_buckets = ()
        if sp > 1:
            from ..parallel.context import cp_head_buckets

            self.cp_buckets = tuple(
                cp_head_buckets(cp_min_len, max_len, sp)
            )
        self.rep = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            self.rep = NamedSharding(mesh, PartitionSpec())

        if self.rep is not None and self.prefix_cache is not None:
            # stored prefix entries must stay fully replicated: a
            # GSPMD-chosen layout persisting in the cache could make
            # a later extend insert cross-process collectives — the
            # exact first-use-communicator hazard the cp buckets
            # exist to avoid (and the pool-drift lesson repeated)
            self._repin = jax.jit(
                lambda t: t, out_shardings=self.rep
            )

        def g(x):
            if self.rep is None:
                return x
            host = np.asarray(jax.device_get(x))
            return jax.make_array_from_callback(
                host.shape, self.rep, lambda idx: host[idx]
            )

        self._g = g
        self.pool = jax.tree.map(g, slot_cache(cfg, slots, max_len))
        # per-slot sampling state, ENTIRELY device-resident
        # (models/slots.py SLOT_STATE_KEYS) and pinned replicated:
        # written only at admission (one row-write dispatch), read by
        # the chunk program every round with zero host->device
        # uploads. The old mirror kept 10 host numpy knob arrays and
        # re-uploaded them every round — and host-side numpy operands
        # were exactly the zero-copy in-place-mutation hazard class
        # behind the historical torn-state bugs (step_idx now
        # advances on device inside the chunk program).
        self.state = jax.tree.map(g, init_slot_state(cfg, slots))
        # host shadow of the LAST done value written to the device
        # state (admission writes False; run_chunk uploads the
        # broadcast mask when it differs). Device-side eos flips can
        # make the device value True where this says False, but any
        # eos flip also ends the row in the frontend's bookkeeping,
        # so the next broadcast mask carries a 1 there and the
        # (redundant-but-harmless) upload converges the two. This is
        # host BOOKKEEPING, never a program operand — no zero-copy
        # hazard.
        self._done_host = np.ones((slots,), bool)

    def admit(self, payload) -> int:
        """Prefill the broadcast prompt into the named slot with the
        server key convention; returns sample 0 (every process fetches
        the same value — the computation is SPMD)."""
        from ..models.decode import _jitted_prefill
        from ..models.slots import (
            admit_slot_state,
            first_sample,
            insert_row,
        )

        slot = int(payload["admit_slot"])
        plen = int(payload["plen"])
        logits = row_cache = None
        pc = self.prefix_cache
        # prompts shorter than MIN_REUSE skip the prefix machinery
        # (never reusable; also keeps warmup's dummy admission out of
        # the cache) — same rule as the single-host engine
        use_pc = False
        if pc is not None:
            from .serve_prefix import MIN_REUSE

            use_pc = plen >= MIN_REUSE
        if use_pc:
            from .serve_prefix import reuse_admission

            row_tokens = [int(t) for t in payload["prompt"][:plen]]
            hit = reuse_admission(
                pc, row_tokens, self.cfg, self.params,
                chunk_len=self.prefill_chunk,
            )
            if hit is not None:
                logits, row_cache = hit
        # context-parallel admission: the quadratic prefill of a long
        # prompt rings over the seq axis (each device holds head/sp
        # tokens), the cache leaves the ring replicated — exactly the
        # mirror's layout — and any non-axis-divisible remainder
        # extends it with one short chunk (parallel/context.py's
        # cp_generate recipe, minus its decode half: the slot pool IS
        # the decode half here).
        if row_cache is None:
            cp_head = 0
            if self.sp > 1 and plen >= self.cp_min_len:
                from ..parallel.context import pick_cp_head

                cp_head = pick_cp_head(plen, self.cp_buckets)
            if cp_head > 0:
                from ..parallel.context import (
                    cp_prefill_with_remainder,
                )

                logits, row_cache = cp_prefill_with_remainder(
                    self.params, payload["prompt"][None, :plen],
                    self.cfg, self.mesh, self.max_len, head=cp_head,
                    prefill_chunk=self.prefill_chunk,
                )
            elif (
                self.prefill_chunk > 0
                and plen > self.prefill_chunk
            ):
                from ..models.decode import chunked_prefill

                logits, row_cache = chunked_prefill(
                    self.params,
                    jnp.asarray(payload["prompt"][None, :plen],
                                jnp.int32),
                    self.cfg, self.max_len,
                    chunk_len=self.prefill_chunk,
                )
            else:
                prompt = jnp.asarray(
                    payload["prompt"][None, :plen], jnp.int32
                )
                logits, row_cache = _jitted_prefill(
                    self.cfg, self.max_len
                )(self.params, prompt)
        if use_pc:
            stored = (
                self._repin(row_cache)
                if self._repin is not None else row_cache
            )
            pc.store(tuple(row_tokens), stored)
        row_key = jax.random.fold_in(
            jax.random.PRNGKey(int(payload["seed"])),
            int(payload["row_idx"]),
        )
        eos_id = int(payload["eos_id"])
        first = first_sample(
            logits, row_key,
            float(payload["temperature"]), int(payload["top_k"]),
            float(payload["top_p"]), self.cfg, eos_id=eos_id,
            min_new=int(payload["min_new"]),
            bias_idx=jnp.asarray(payload["bias_idx"], jnp.int32),
            bias_val=jnp.asarray(payload["bias_val"], jnp.float32),
        )
        first_host = int(jax.device_get(first))
        self.pool = insert_row(
            self.pool, row_cache, slot, self.cfg,
            out_sharding=self.rep,
        )
        # ONE dispatch writes the whole admission row into the
        # device-resident state (incl. the counts row, seeded on
        # device from the first sample). The barrier that used to sit
        # here guarded in-flight donated updates against the host
        # mutating zero-copied numpy operands (step_idx/knob arrays);
        # with every operand device-resident that hazard class is
        # gone by construction, device dataflow orders the donated
        # pool/state into the next chunk, and the 2-process co-batch
        # parity + torn-state tests hold without it.
        self.state = admit_slot_state(
            self.state, slot, self.cfg,
            last=first, key=row_key,
            temperature=float(payload["temperature"]),
            top_k=int(payload["top_k"]),
            top_p=float(payload["top_p"]),
            eos_id=eos_id,
            pad_id=0,  # server pad: 0
            min_new=int(payload["min_new"]),
            presence=float(payload["presence"]),
            frequency=float(payload["frequency"]),
            bias_idx=np.asarray(payload["bias_idx"], np.int32),
            bias_val=np.asarray(payload["bias_val"], np.float32),
            done=False,
            out_sharding=self.rep,
        )
        self._done_host[slot] = False
        return first_host

    # cpcheck: hotpath — the pod's per-round chunk step; one annotated
    # fetch, and the mask upload only on rounds where it changed
    def run_chunk(self, done_mask, rounds: int = 1,
                  budget=None) -> np.ndarray:
        """Advance every slot ``rounds`` chunk-rounds under the
        broadcast inactive mask — ONE device dispatch either way
        (rounds > 1 takes the fused (S, chunk, K) window program of
        models/slots.py, early-exiting on done/budget); returns the
        [slots, rounds_run*chunk] sampled tokens (fetched on every
        process — the fetch is what synchronizes device work, so a
        wedged computation stalls THIS cycle, not some later one).
        ``rounds`` and ``budget`` ride the broadcast payload, so
        every process dispatches the identical program.

        The mask rides the device-resident state: it is re-uploaded
        (one [S] bool array, pinned replicated) ONLY on rounds where
        it differs from the last value written — retirements and
        evictions — so a steady decode round ships zero host->device
        transfers (a fused window adds one [S] int32 budget upload
        per K rounds). The old full block_until_ready barrier is gone
        with its root causes: there are no zero-copied numpy operands
        left to mutate in place (step_idx advances on device), and
        the donated pool/state order into the next dispatch by device
        dataflow (the 2-process co-batch parity and torn-state tests
        hold without the barrier — they decided)."""
        from ..models.slots import (
            decode_slots_chunk,
            decode_slots_window,
        )

        mask = np.asarray(done_mask, bool)  # cpcheck: disable=CP-HOTSYNC host-side numpy only, no device operand
        if not np.array_equal(mask, self._done_host):
            self.state = dict(
                self.state, done=self._g(jnp.asarray(mask))
            )
            self._done_host = mask.copy()
        if rounds > 1:
            # the broadcast budget is already a host [S] int32 array;
            # decode_slots_window's wrapper uploads it
            self.pool, self.state, toks, run = decode_slots_window(
                self.params, self.pool, self.state,
                self.cfg, self.chunk, rounds, budget,
                out_sharding=self.rep,
            )
            toks_host, run_host = jax.device_get((toks, run))  # cpcheck: disable=CP-HOTSYNC the per-window token fetch
            return toks_host[:, : int(run_host) * self.chunk]
        self.pool, self.state, toks = decode_slots_chunk(
            self.params, self.pool, self.state,
            self.cfg, self.chunk,
            out_sharding=self.rep,
        )
        return np.asarray(jax.device_get(toks))  # cpcheck: disable=CP-HOTSYNC the per-round token fetch


def _debug_round(mirror: _SlotMirror, payload, first, toks) -> None:  # cpcheck: disable=CP-HOTREACH debug-only dump behind CONTAINERPILOT_POD_DEBUG; every sync here is the point
    """Dump one round's inputs and full device state
    (CONTAINERPILOT_POD_DEBUG only). Deliberately a separate,
    non-hot function: every fetch below is a host sync."""
    print(
        "ROUND admit=%d plen=%d seed=%d row=%d mask=%s first=%s "
        "toks=%s step=%s last=%s keys=%s"
        % (
            int(payload["admit_slot"]), int(payload["plen"]),
            int(payload["seed"]), int(payload["row_idx"]),
            np.asarray(payload["done"]).tolist(), first,
            None if toks is None else toks.tolist(),
            np.asarray(
                jax.device_get(mirror.state["step_idx"])
            ).tolist(),
            np.asarray(
                jax.device_get(mirror.state["last"])
            ).tolist(),
            np.asarray(
                jax.device_get(mirror.state["keys"])
            ).tolist(),
        ),
        flush=True,
    )


# cpcheck: hotpath — the device ops of one pod round
def _apply_round(mirror: _SlotMirror, payload):
    """The device ops of one ROUND, identical on every process:
    optional admission, then optionally one chunk. Returns (first
    token or None, [slots, chunk] tokens or None)."""
    first = toks = None
    if int(payload["admit_slot"]) >= 0:
        first = mirror.admit(payload)
    if int(payload["run_chunk"]):
        toks = mirror.run_chunk(
            payload["done"], rounds=int(payload["rounds"]),
            budget=payload["budget"],
        )
    if os.environ.get("CONTAINERPILOT_POD_DEBUG"):
        _debug_round(mirror, payload, first, toks)
    return first, toks


def shard_params_global(params: Any, mesh, cfg) -> Any:
    """Place identically-initialized host params onto a multi-host
    mesh: each process contributes exactly the shards it addresses
    (``make_array_from_callback`` slices the host copy), so no data
    moves over DCN at load time."""
    from jax.sharding import NamedSharding

    from ..parallel.sharding import param_sharding_rules

    rules = param_sharding_rules(cfg, mesh)

    def put(leaf, spec):
        host = np.asarray(leaf)
        return jax.make_array_from_callback(
            host.shape, NamedSharding(mesh, spec),
            lambda idx: host[idx],
        )

    return jax.tree_util.tree_map(put, params, rules)


@functools.lru_cache(maxsize=8)
def _jitted_score_fn(cfg):
    from .modelcfg import score_logprobs_fn

    return jax.jit(score_logprobs_fn(cfg))


def _score_pod(params, cfg, payload, max_len: int):
    """Teacher-forced per-token logprobs of the broadcast row — the
    pod twin of the single-host /v1/score (the SAME jitted function,
    modelcfg.score_logprobs_fn); every process runs it in lockstep
    like a decode. Rows pad to a 16-multiple width (capped at
    max_len) so per-request length variation can't compile a fresh
    pod-wide program inside the watchdog deadline — causal attention
    makes the pad positions free, and the result slices back.
    Returns a HOST [1, plen-1] ndarray (the device fetch lives here;
    see the slice comment below)."""
    plen = int(payload["plen"])
    width = min(-(-plen // 16) * 16, max_len)
    toks = jnp.asarray(payload["prompt"][None, :width], jnp.int32)
    out = _jitted_score_fn(cfg)(params, toks)
    if os.environ.get("CONTAINERPILOT_POD_DEBUG"):
        print("SCORE plen=%d" % plen, flush=True)
    # slice on the HOST: a device-side `out[:, :plen-1]` compiles a
    # tiny jit(dynamic_slice) per distinct plen — a post-grace
    # compile the warmup invariant forbids (the fetch is 16 floats
    # either way)
    return np.asarray(jax.device_get(out))[:, : plen - 1]


def _beam_pod(params, cfg, payload, max_len: int) -> List[int]:
    """One-shot lockstep beam search over the broadcast row: the same
    deterministic ``models.beam.beam_search`` program the single-host
    server runs, traced from broadcast scalars so every process
    executes it identically. One-shot by nature — it does not beat the
    watchdog mid-run, so the deadline must exceed the slowest beam."""
    from ..models.beam import beam_search

    plen = int(payload["plen"])
    prompt = jnp.asarray(payload["prompt"][None, :plen], jnp.int32)
    out, _score = beam_search(
        params, prompt, cfg,
        max_new_tokens=int(payload["max_new_req"]),
        max_len=max_len,
        beam_width=int(payload["beam_width"]),
        eos_id=int(payload["eos_id"]),
        length_penalty=float(payload["length_penalty"]),
    )
    if os.environ.get("CONTAINERPILOT_POD_DEBUG"):
        print("BEAM plen=%d width=%d"
              % (plen, int(payload["beam_width"])), flush=True)
    return [int(t) for t in np.asarray(jax.device_get(out))]


def _spec_pod(params, draft, cfg, payload, max_len: int) -> List[int]:
    """One-shot lockstep speculative generation: the single-host
    draft-and-verify (models/speculative.py — greedy, output
    IDENTICAL to plain generate) run identically on every process.
    The host loop's data-dependent acceptance decisions derive from
    replicated device values, so every process takes the same
    branches in the same order — all SPMD needs. Like beams, a spec
    round beats the watchdog only on completion; the deadline must
    exceed the slowest full generation."""
    from ..models.speculative import speculative_generate

    draft_params, draft_cfg, speculate = draft
    plen = int(payload["plen"])
    prompt = jnp.asarray(payload["prompt"][None, :plen], jnp.int32)
    out, stats = speculative_generate(
        params, draft_params, prompt, cfg, draft_cfg,
        max_new_tokens=int(payload["max_new_req"]), max_len=max_len,
        speculate=speculate, eos_id=int(payload["eos_id"]),
    )
    if os.environ.get("CONTAINERPILOT_POD_DEBUG"):
        print("SPEC plen=%d stats=%s" % (plen, stats), flush=True)
    return [int(t) for t in np.asarray(jax.device_get(out))[0]]


def _hit_stop(emitted: List[int], stops: List[List[int]]) -> bool:
    """Whether any stop sequence occurs anywhere in the emission —
    the frontend's early-eviction check (the stop-EXCLUSIVE trim
    happens at answer time via InferenceServer._trim_stops, so the
    response is identical to the single-host server's; the eviction
    just stops paying for tokens the trim would discard)."""
    for stop in stops:
        n = len(stop)
        for i in range(len(emitted) - n + 1):
            if emitted[i:i + n] == stop:
                return True
    return False


class _Row:
    """One decode row of a request (n > 1 fans a request into n)."""

    __slots__ = ("emitted", "finished")

    def __init__(self) -> None:
        self.emitted: List[int] = []
        self.finished = False


class _GenReq:
    """Frontend bookkeeping for one /v1/generate|completions request
    riding the slot pool."""

    def __init__(self, work: Dict[str, Any], done_q) -> None:
        self.work = work
        self.done_q = done_q
        self.rows = [_Row() for _ in range(work["n"])]
        self.stream = bool(work.get("_stream"))
        self.cancel = work.get("_cancel")
        self.answered = False

    def cancelled(self) -> bool:
        return self.cancel is not None and self.cancel.is_set()


class _Frontend:
    """Process 0's HTTP surface: requests land in a queue the pod
    loop drains; the loop owns all device work."""

    def __init__(self, host: str, port: int, max_len: int,
                 vocab: int, pod_info: Optional[Dict[str, Any]] = None,
                 text: bool = False, stream_chunk: int = 8,
                 slots: int = 4, cfg: Any = None,
                 prefix_entries: int = 0,
                 ) -> None:
        from prometheus_client import (
            CollectorRegistry,
            Counter,
            Histogram,
        )

        from ..utils.http import HTTPServer, Response

        self.max_len = max_len
        self.vocab = vocab
        self.slots = slots
        self.cfg = cfg  # model config (beam validation); optional
        self.ready = False
        # /v1/model prefix_cache schema stability: the mirror's live
        # PrefixCache is assigned only after warm_pod, but a client
        # polling during the boot window must see the SAME keys —
        # until the live cache lands, a configured cache reports
        # zeroed stats (the true counts: nothing served yet)
        self.prefix_entries = prefix_entries
        self.prefix_cache = None
        # /v1/model payload: model config + pod topology, set by main()
        self.pod_info = pod_info or {}
        self.stream_chunk = max(int(stream_chunk), 1)
        self.requests: "queue.Queue[Tuple[dict, queue.Queue]]" = (
            queue.Queue()
        )
        # observability parity with the single-host server: a private
        # registry (an in-process supervisor's metrics never collide)
        self._registry = CollectorRegistry()
        self._m_requests = Counter(
            "containerpilot_pod_requests",
            "pod frontend requests by endpoint and status",
            ["endpoint", "status"], registry=self._registry,
        )
        self._m_latency = Histogram(
            "containerpilot_pod_request_seconds",
            "pod request latency (broadcast + lockstep decode)",
            registry=self._registry,
            buckets=(.05, .1, .25, .5, 1, 2.5, 5, 10, 30, 60, 120),
        )
        self._m_tokens = Counter(
            "containerpilot_pod_generated_tokens",
            "tokens returned by the pod frontend (post-trim)",
            registry=self._registry,
        )
        from ..telemetry import tracing
        from ..telemetry.goodput import DeviceTimeLedger
        from ..utils.prom import ensure_build_info, ensure_goodput_gauges

        ensure_build_info(self._registry, "pod")
        # device-time ledger, pod-shaped: process 0's round loop is
        # the single writer for prefill/decode/idle (admission
        # boundaries only — the lockstep chunk rounds in between
        # stamp nothing), main() brackets warm_pod as compile_warmup.
        # Followers replay broadcast ops in lockstep, so the
        # frontend's ledger IS the pod's device-time story.
        self.ledger = DeviceTimeLedger()
        # the dispatches/token pair: broadcast rounds that touched
        # the device vs tokens appended — bumped by the round loop
        self.dispatches = 0
        self.tokens_out = 0
        ensure_goodput_gauges(
            self._registry, self.ledger,
            lambda: (self.dispatches, self.tokens_out),
        )
        # request tracing, the single-host server's discipline
        # pod-shaped: adopt/mint a trace id per request, span the
        # queue->pod-loop dispatch, echo id + digest back (see
        # telemetry/tracing.py and docs/90-observability.md)
        self._tracing = tracing
        self._tracer = tracing.TraceRecorder("pod")
        self._server = HTTPServer()
        self._server.route("GET", "/health", self._health)
        self._server.route("GET", "/metrics", self._metrics)
        self._server.route("GET", "/v1/traces", self._traces)
        self._server.route("GET", "/v1/goodput", self._goodput)
        self._server.route("GET", "/v1/model", self._model)
        self._server.route(
            "POST", "/v1/generate", self._traced("generate", self._generate)
        )
        self._server.route(
            "POST", "/v1/score", self._traced("score", self._score)
        )
        # text surface: byte-level tokenizer, zero external assets —
        # the single-host server's --text, pod-shaped
        self.tokenizer = None
        if text:
            from .text import ByteTokenizer

            self.tokenizer = ByteTokenizer(vocab)
            self._server.route(
                "POST", "/v1/completions",
                self._traced("completions", self._completions),
            )
        self._host, self._port = host, port
        self._Response = Response
        self._loop = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.bound_port or self._port

    def _traced(self, endpoint: str, handler):
        """Per-request trace around one API handler: adopt the
        caller's X-CP-Trace id (or mint one), echo it on EVERY
        answer — 422s included — and hand buffered responses the
        span digest header. Streams carry only the id (the pod's
        lockstep rounds are accounted by the ``pod_dispatch`` span
        the buffered path records; per-chunk stream spans are the
        single-host server's refinement)."""
        tracing = self._tracing

        async def wrapped(req):
            trace = self._tracer.start(
                tracing.safe_id(req.headers.get("x-cp-trace")),
                endpoint,
            )
            token = tracing.activate(trace)
            try:
                resp = await handler(req)
            except Exception:
                trace.finish(500)
                raise
            finally:
                tracing.deactivate(token)
            resp.headers.setdefault(
                tracing.TRACE_HEADER, trace.trace_id
            )
            if not hasattr(resp, "chunks"):  # buffered Response
                trace.finish(resp.status)
                resp.headers.setdefault(
                    tracing.DIGEST_HEADER, trace.digest()
                )
            else:
                trace.finish(resp.status)
            return resp

        return wrapped

    async def _traces(self, req):
        return self._Response(
            200,
            self._tracer.snapshot_json(req.query),
            content_type="application/json",
        )

    async def _goodput(self, _req):
        """The pod's device-time ledger — same schema as the
        single-host replica's ``/v1/goodput`` (scheduling gaps
        included: the pod's queue->loop dispatch span plays the
        slot_queue_wait role there when the ring ever records it)."""
        from ..telemetry.goodput import goodput_payload

        payload = goodput_payload(
            self.ledger, self._tracer, self.dispatches,
            self.tokens_out, role="pod", ready=self.ready,
            draining=False,
        )
        return self._Response(
            200, json.dumps(payload).encode(),
            content_type="application/json",
        )

    async def _dispatch(self, endpoint: str, work: Dict[str, Any]):
        """queue → pod loop → result, with the latency/500 accounting
        every endpoint shares. Returns (result, None) on success or
        (None, 500 Response) on a pod-side failure."""
        import asyncio

        t0 = time.perf_counter()
        done: "queue.Queue" = queue.Queue()
        self.requests.put((work, done))
        with self._tracing.span("pod_dispatch"):
            result = await asyncio.get_event_loop().run_in_executor(
                None, done.get
            )
        self._m_latency.observe(time.perf_counter() - t0)
        if isinstance(result, Exception):
            self._m_requests.labels(endpoint, "500").inc()
            return None, self._Response(500, f"{result}\n".encode())
        self._m_requests.labels(endpoint, "200").inc()
        return result, None

    async def _health(self, _req):
        if not self.ready:
            return self._Response(503, b"warming\n")
        return self._Response(200, b"ok\n")

    async def _metrics(self, _req):
        from ..utils.prom import exposition

        body, content_type = exposition(self._registry)
        return self._Response(200, body, content_type=content_type)

    async def _model(self, _req):
        self._m_requests.labels("model", "200").inc()
        info = dict(self.pod_info)
        pc = self.prefix_cache
        if pc is not None:
            # live stats, same shape as the single-host /v1/model
            info["prefix_cache"] = {"entries": pc.entries, **pc.stats}
        elif self.prefix_entries > 0:
            # boot window: same schema, zeroed counts (spill fields
            # included — the pod runs without a spill tier, so they
            # stay zero after warm too, mirroring the single-host
            # server's tier-disabled shape)
            info["prefix_cache"] = {
                "entries": self.prefix_entries,
                "hits": 0, "misses": 0, "tokens_reused": 0,
                "spilled": 0, "readmitted": 0, "spill_bytes": 0,
            }
        return self._Response(
            200, json.dumps(info).encode(),
            content_type="application/json",
        )

    def _parse_work(self, body, tokens, default_eos: int = -1):
        """Validate the decode knobs shared by /v1/generate and the
        --text surface into a broadcastable work dict — the
        single-host server's knob set (n, stop, logprobs, beam_width
        included). Full validation HERE: a malformed value that only
        failed inside the pod loop would be pod-fatal (the loop
        deliberately re-raises collective-path errors), and an
        out-of-int32 value would crash payload packing. Raises
        ValueError for a 422."""
        max_new = int(body.get("max_new_tokens", 16))
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(tokens) + max_new > self.max_len:
            raise ValueError(
                f"prompt + max_new_tokens exceeds max_len "
                f"{self.max_len}"
            )
        temperature = float(body.get("temperature", 0.0))
        top_k = int(body.get("top_k", 0))
        top_p = float(body.get("top_p", 0.0))
        eos_id = int(body.get("eos_id", default_eos))
        seed = int(body.get("seed", 0))
        if not 0 <= top_k <= self.vocab:
            raise ValueError(f"top_k must be in [0, {self.vocab}]")
        if not 0.0 <= top_p <= 1.0:
            raise ValueError("top_p must be in [0, 1]")
        if eos_id >= self.vocab:
            raise ValueError(f"eos_id must be < {self.vocab}")
        if not -(2**31) <= seed < 2**31:
            raise ValueError("seed must fit in int32")
        min_new = int(body.get("min_new_tokens", 0))
        if not 0 <= min_new <= max_new:
            raise ValueError(
                "min_new_tokens must be in [0, max_new_tokens]"
            )
        presence = float(body.get("presence_penalty", 0.0))
        frequency = float(body.get("frequency_penalty", 0.0))
        if not (abs(presence) <= 100 and abs(frequency) <= 100):
            raise ValueError(
                "presence/frequency penalties must be in "
                "[-100, 100]"
            )
        from .modelcfg import parse_logit_bias, parse_stop_ids

        bias = parse_logit_bias(
            body.get("logit_bias"), self.vocab
        ) or {}
        stop = parse_stop_ids(body.get("stop"), self.vocab)
        logprobs = bool(body.get("logprobs", False))
        n = int(body.get("n", 1))
        if not 1 <= n <= self.slots:
            raise ValueError(
                f"n must be in [1, --slots {self.slots}] on the pod "
                "frontend (each sample occupies one slot)"
            )
        beam_width = int(body.get("beam_width", 0))
        length_penalty = float(body.get("length_penalty", 0.0))
        if beam_width:
            if n != 1:
                raise ValueError(
                    "n does not compose with beam search (beams "
                    "already return one best row)"
                )
            if temperature > 0.0 or top_k or top_p:
                raise ValueError(
                    "beam search is deterministic; drop "
                    "temperature/top_k/top_p"
                )
            if min_new:
                raise ValueError(
                    "min_new_tokens does not apply to beam search"
                )
            if presence or frequency:
                raise ValueError("penalties do not apply to beam search")
            if bias:
                raise ValueError(
                    "logit_bias does not apply to beam search"
                )
            if self.cfg is not None:
                from ..models.beam import validate_beam_args

                validate_beam_args(self.cfg, 1, beam_width)
            elif not 1 <= beam_width <= self.vocab:
                raise ValueError(
                    f"beam_width must be in [1, vocab {self.vocab}]"
                )
            if beam_width > self.slots:
                # beams tile the KV cache: one request must not exceed
                # the pod's configured device-row budget (--slots, the
                # same sizing the pool uses)
                raise ValueError(
                    f"beam_width capped at --slots ({self.slots}) "
                    "on the pod frontend"
                )
        return {
            "kind": "beam" if beam_width else "gen",
            "tokens": tokens, "max_new": max_new,
            "temperature": temperature,
            "top_k": top_k,
            "top_p": top_p,
            "eos_id": max(eos_id, -1),
            "seed": seed,
            "min_new": min_new,
            "presence": presence,
            "frequency": frequency,
            "logit_bias": bias,
            "stop": stop,
            "logprobs": logprobs,
            "n": n,
            "beam_width": beam_width,
            "length_penalty": length_penalty,
        }

    @staticmethod
    def _check_stream_composes(work) -> None:
        if work["kind"] == "beam":
            raise ValueError(
                "stream does not compose with beam_width (beams "
                "have no incremental prefix)"
            )
        if work["n"] != 1:
            raise ValueError(
                "n does not compose with stream (one SSE stream "
                "carries one row)"
            )
        for knob, why in (
            ("logprobs", "echo logprobs need the full row"),
            ("stop", "stop sequences need whole-row trimming"),
        ):
            if work[knob]:
                raise ValueError(
                    f"stream does not compose with {knob} ({why})"
                )

    def _parse_single_row(self, body, min_len: int = 1):
        rows = body.get("tokens")
        if (
            not isinstance(rows, list) or len(rows) != 1
            or not isinstance(rows[0], list)
            or len(rows[0]) < min_len
        ):
            raise ValueError(
                f"'tokens' must be one row of at least {min_len} "
                "ids (the pod frontend serves single-row requests; "
                "n is the row multiplier)"
            )
        tokens = rows[0]
        if any(
            not isinstance(t, int) or isinstance(t, bool)
            or t < 0 or t >= self.vocab
            for t in tokens
        ):
            raise ValueError(
                f"token ids must be integers in [0, {self.vocab})"
            )
        return tokens

    async def _generate(self, req):
        try:
            body = json.loads(req.body.decode() or "{}")
            work = self._parse_work(body, self._parse_single_row(body))
            if bool(body.get("stream", False)):
                self._check_stream_composes(work)
                return self._stream_request("generate", work)
        except (ValueError, KeyError, TypeError, OverflowError) as exc:
            self._m_requests.labels("generate", "422").inc()
            return self._Response(422, f"{exc}\n".encode())
        result, err = await self._dispatch("generate", work)
        if err is not None:
            return err
        rows = result["tokens"]
        self._m_tokens.inc(sum(len(r) for r in rows))
        payload: Dict[str, Any] = {"tokens": rows}
        if result.get("logprobs") is not None:
            payload["logprobs"] = result["logprobs"]
        return self._Response(
            200, json.dumps(payload).encode(),
            content_type="application/json",
        )

    async def _completions(self, req):
        """Text in/out around the same slot-pool decode /v1/generate
        uses: encode the prompt through the byte tokenizer, default
        eos to the tokenizer's EOS, decode the generated ids back —
        the single-host /v1/completions contract, pod-shaped.
        ``stop`` takes strings here (encoded to token rows before the
        shared parser); ``stream`` emits text deltas with UTF-8
        partial-byte holdback (text.stream_decoder)."""
        tok = self.tokenizer
        try:
            body = json.loads(req.body.decode() or "{}")
            prompt = body.get("prompt")
            if not isinstance(prompt, str) or not prompt:
                raise ValueError("'prompt' must be a non-empty string")
            row = tok.encode(prompt)
            if len(row) >= self.max_len:
                raise ValueError(
                    f"prompt encodes to {len(row)} ids; max_len is "
                    f"{self.max_len}"
                )
            from .modelcfg import parse_stop_strings

            stop_raw = parse_stop_strings(body.pop("stop", None))
            if stop_raw is not None:
                body["stop"] = [
                    tok.encode(s, bos=False) for s in stop_raw
                ]
            work = self._parse_work(body, row, default_eos=tok.EOS)
            # the single-host text surface ignores the logprobs knob
            # (its response carries text+ids only); mirror that
            # instead of paying echo score rounds nobody reads
            work["logprobs"] = False
            if work["n"] > 1:
                raise ValueError(
                    "n returns token rows; use /v1/generate"
                )
            if bool(body.get("stream", False)):
                self._check_stream_composes(work)
                from .text import stream_decoder

                delta_event, tail_events = stream_decoder(tok)
                return self._stream_request(
                    "completions", work, delta_event=delta_event,
                    tail_events=tail_events,
                )
        except (ValueError, KeyError, TypeError, OverflowError) as exc:
            self._m_requests.labels("completions", "422").inc()
            return self._Response(422, f"{exc}\n".encode())
        result, err = await self._dispatch("completions", work)
        if err is not None:
            return err
        row_out = result["tokens"][0]
        self._m_tokens.inc(len(row_out))
        return self._Response(
            200,
            json.dumps(
                {"text": tok.decode(row_out), "tokens": row_out}
            ).encode(),
            content_type="application/json",
        )

    def _stream_request(self, endpoint: str, work,
                        delta_event=None, tail_events=None):
        """SSE over the pod's chunked lockstep rounds: each chunk's
        delta becomes a ``data:`` event as its round lands;
        concatenated deltas equal the non-streamed answer. A client
        disconnect sets the cancel event — the frontend evicts the
        slot at the next round and the pool keeps serving everyone
        else. ``delta_event``/``tail_events`` shape events for the
        text surface (UTF-8 holdback), mirroring the single-host
        server's streaming plumbing."""
        import asyncio
        import threading as threading_mod

        from ..utils.http import StreamingResponse

        if delta_event is None:
            delta_event = lambda d: {"tokens": d}  # noqa: E731
        if tail_events is None:
            tail_events = list  # noqa: E731 — no tail

        cancel = threading_mod.Event()
        work = dict(work, _cancel=cancel, _stream=True)
        done: "queue.Queue" = queue.Queue()
        t0 = time.perf_counter()
        self.requests.put((work, done))
        sent = [0]
        status = ["200"]
        finished = [False]

        def finish() -> None:
            if finished[0]:
                return
            finished[0] = True
            cancel.set()
            self._m_latency.observe(time.perf_counter() - t0)
            self._m_tokens.inc(sent[0])
            self._m_requests.labels(endpoint, status[0]).inc()

        def sse(payload) -> bytes:
            return b"data: " + json.dumps(payload).encode() + b"\n\n"

        async def events():
            loop = asyncio.get_event_loop()
            try:
                while True:
                    item = await loop.run_in_executor(None, done.get)
                    if isinstance(item, Exception):
                        status[0] = "500"
                        yield sse({"error": str(item)})
                        break
                    kind, val = item
                    if kind == "delta":
                        sent[0] += len(val)
                        yield sse(delta_event(val))
                    else:
                        for extra in tail_events():
                            yield sse(extra)
                        yield sse({"done": True, "count": sent[0]})
                        break
            finally:
                finish()

        return StreamingResponse(events(), close=finish)

    async def _score(self, req):
        try:
            body = json.loads(req.body.decode() or "{}")
            tokens = self._parse_single_row(body, min_len=2)
            if len(tokens) > self.max_len:
                raise ValueError(
                    f"row length exceeds max_len {self.max_len}"
                )
        except (ValueError, KeyError, TypeError) as exc:
            self._m_requests.labels("score", "422").inc()
            return self._Response(422, f"{exc}\n".encode())
        result, err = await self._dispatch(
            "score", {"kind": "score", "score": tokens}
        )
        if err is not None:
            return err
        return self._Response(
            200,
            json.dumps(
                {
                    "logprobs": [[round(float(x), 6) for x in row]
                                 for row in result],
                    "sums": [round(float(sum(row)), 6)
                             for row in result],
                }
            ).encode(),
            content_type="application/json",
        )

    def start(self) -> None:
        import asyncio

        started = threading.Event()

        def run() -> None:
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            loop.run_until_complete(
                self._server.start_tcp(self._host, self._port)
            )
            started.set()
            loop.run_forever()

        self._thread = threading.Thread(
            target=run, name="serve-dist-http", daemon=True
        )
        self._thread.start()
        if not started.wait(timeout=30):
            raise RuntimeError("frontend never bound")

    def stop(self) -> None:
        import asyncio

        if self._loop is not None:
            async def shutdown() -> None:
                await self._server.stop()
                asyncio.get_event_loop().stop()

            self._loop.call_soon_threadsafe(
                lambda: asyncio.ensure_future(shutdown())
            )
        if self._thread is not None:
            self._thread.join(timeout=10)


def warm_pod(mirror: _SlotMirror) -> None:
    """Compile the pool's whole serve-path program set before traffic:
    prefill (plen 4), first-sample, insert, the (slots, chunk) chunk
    program, and the width-16 scorer. Every process derives the
    IDENTICAL warm payloads from its own flags (no broadcast needed —
    broadcasting identical data is identity). Requests at these shapes
    compile NOTHING afterwards (the invariant
    tests/test_serve_dist.py::test_pod_warmup_covers_serve_path holds);
    new prompt lengths, beam shapes, and wider score rows still
    compile on first use — the watchdog deadline must absorb exactly
    those."""
    warm = _payload_zeros(mirror.max_len, mirror.slots)
    warm["op"] = np.asarray(OP_ROUND, np.int32)
    _fill_admission(
        warm,
        {
            "tokens": [0, 0, 0, 0],
            "max_new": mirror.chunk + 1,
            "temperature": 0.0, "top_k": 0, "top_p": 0.0,
            "eos_id": -1, "seed": 0, "min_new": 0,
            "presence": 0.0, "frequency": 0.0, "logit_bias": {},
        },
        row_idx=0, slot=0,
    )
    warm["run_chunk"] = np.asarray(1, np.int32)
    warm["done"][0] = 0
    _apply_round(mirror, warm)
    if mirror.window > 1:
        # compile the fused (S, chunk, K) window program inside the
        # same grace: one pure-decode window over the still-admitted
        # warm slot, budget 1 so the device loop runs exactly one
        # round and exits
        warm_w = _payload_zeros(mirror.max_len, mirror.slots)
        warm_w["op"] = np.asarray(OP_ROUND, np.int32)
        warm_w["run_chunk"] = np.asarray(1, np.int32)
        warm_w["done"][0] = 0
        warm_w["rounds"] = np.asarray(mirror.window, np.int32)
        warm_w["budget"][0] = 1
        _apply_round(mirror, warm_w)
    warm_score = _payload_zeros(mirror.max_len, mirror.slots)
    warm_score["plen"] = np.asarray(5, np.int32)
    _score_pod(mirror.params, mirror.cfg, warm_score, mirror.max_len)
    # EVERY cp ring program compiles here, inside the startup grace
    # where the pod is freshly rendezvous-synchronized: ring prefills
    # are the pod's only cross-process collectives outside the
    # broadcast, and a first-use collective program's communicator
    # init has a hard ~30s deadline that request-time compile skew
    # between processes blows (observed killing a live pod). The
    # remainder extend and plain prefill stay per-length request-time
    # compiles — they are local programs, where skew only delays.
    if mirror.cp_buckets:
        from ..parallel.context import cp_prefill_with_remainder

        for head in mirror.cp_buckets:
            warm_prompt = np.zeros((1, head), np.int32)
            logits_cp, cache_cp = cp_prefill_with_remainder(
                mirror.params, warm_prompt, mirror.cfg, mirror.mesh,
                mirror.max_len, head=head,
            )
            jax.block_until_ready((logits_cp, cache_cp))


def _run_frontend_loop(args, frontend: _Frontend, mirror: _SlotMirror,
                       dog, multihost_utils, stopping,
                       draft=None) -> None:
    """Process 0's round loop: drain HTTP work, drive admissions and
    chunks via broadcast ROUNDs, keep the per-request emission
    bookkeeping, answer handlers. Every completed round beat()s the
    watchdog; idle gaps are bounded by heartbeat rounds."""
    from .serve import InferenceServer

    S = args.slots
    heartbeat_every = args.watchdog / 4 if args.watchdog > 0 else None
    pending: "deque[Tuple[_GenReq, int]]" = deque()
    owners: List[Optional[Tuple[_GenReq, int]]] = [None] * S
    open_reqs: List[_GenReq] = []

    def beat() -> None:
        if dog is not None:
            dog.beat()

    def bcast(payload):
        return multihost_utils.broadcast_one_to_all(payload)

    def run_score_round(row: List[int]) -> np.ndarray:
        """One lockstep score op; returns the [1, plen-1] logprobs."""
        p = _payload_zeros(args.max_len, S)
        p["op"] = np.asarray(OP_SCORE, np.int32)
        p["prompt"][: len(row)] = np.asarray(row, np.int32)
        p["plen"] = np.asarray(len(row), np.int32)
        bcast(p)
        out = _score_pod(mirror.params, mirror.cfg, p, args.max_len)
        beat()
        return out

    def echo_logprobs(prompt: List[int],
                      rows_out: List[List[int]]) -> List[List[float]]:
        """Per-token logprobs of the TRIMMED generated rows via
        lockstep score rounds — numerically the single-host
        _echo_logprobs (same jitted scorer, causal attention makes
        pad-width differences free)."""
        lps: List[List[float]] = []
        start = len(prompt) - 1
        for gen in rows_out:
            if not gen:
                lps.append([])
                continue
            picked = run_score_round(prompt + gen)[0]
            lps.append([
                round(float(x), 6)
                for x in picked[start:start + len(gen)]
            ])
        return lps

    def finish_req(req: _GenReq) -> None:
        req.answered = True
        w = req.work
        if req.stream:
            req.done_q.put(("end", None))
            return
        rows_out = [
            InferenceServer._trim(
                [r.emitted], w["max_new"], w["eos_id"]
            )[0]
            for r in req.rows
        ]
        rows_out = InferenceServer._trim_stops(rows_out, w["stop"])
        result: Dict[str, Any] = {"tokens": rows_out}
        if w["logprobs"]:
            result["logprobs"] = echo_logprobs(w["tokens"], rows_out)
        req.done_q.put(result)

    def row_append(req: _GenReq, row: _Row, toks) -> None:
        from ..models.slots import append_chunk

        w = req.work
        before = len(row.emitted)
        ended = append_chunk(
            row.emitted, toks, w["max_new"], w["eos_id"]
        )
        frontend.tokens_out += len(row.emitted) - before
        if w["stop"] and not ended and _hit_stop(
            row.emitted, w["stop"]
        ):
            # the whole-row trim at answer time will cut BEFORE the
            # stop; decoding past it would be paying for discarded
            # tokens — evict at this boundary
            ended = True
        if req.stream and len(row.emitted) > before:
            req.done_q.put(("delta", list(row.emitted[before:])))
        if ended:
            row.finished = True

    def run_one_shot(work, done_q, op, fill_extra, run_op) -> None:
        """The shared answer path for one-shot lockstep ops (beam,
        spec): fill the row payload, broadcast, run, trim, echo
        logprobs if asked, answer — failing pod-fatally like every
        collective path."""
        p = _payload_zeros(args.max_len, S)
        p["op"] = np.asarray(op, np.int32)
        tokens = work["tokens"]
        p["prompt"][: len(tokens)] = np.asarray(tokens, np.int32)
        p["plen"] = np.asarray(len(tokens), np.int32)
        p["max_new_req"] = np.asarray(work["max_new"], np.int32)
        p["eos_id"] = np.asarray(work["eos_id"], np.int32)
        fill_extra(p)
        bcast(p)
        # ledger: a one-shot op is a whole generation in one lockstep
        # program — coarse-attributed to decode (the slot pool's
        # admission rounds get the finer prefill/decode split)
        frontend.ledger.enter("decode")
        frontend.dispatches += 1
        try:
            row = run_op(p)
            beat()
            rows_out = InferenceServer._trim(
                [row], work["max_new"], work["eos_id"]
            )
            rows_out = InferenceServer._trim_stops(
                rows_out, work["stop"]
            )
            # one-shot rows bypass row_append: count their tokens
            # here or the dispatches/token series overstates on
            # beam/spec traffic
            frontend.tokens_out += sum(len(r) for r in rows_out)
            result: Dict[str, Any] = {"tokens": rows_out}
            if work["logprobs"]:
                result["logprobs"] = echo_logprobs(
                    work["tokens"], rows_out
                )
        except Exception as exc:  # noqa: BLE001 — pod-fatal
            done_q.put(exc)
            fail_open(exc)
            raise
        if not any(owners) and not pending:
            # only flip back when the slot pool is truly empty: a
            # beam answered between chunk rounds must not mark a
            # busy pool idle (chunk-only rounds stamp nothing)
            frontend.ledger.engine_idle()
        done_q.put(result)

    def classify(work, done_q) -> None:
        kind = work.get("kind", "gen")
        if kind == "score":
            try:
                out = run_score_round(work["score"])
            except Exception as exc:  # noqa: BLE001 — pod-fatal
                done_q.put(exc)
                fail_open(exc)
                raise
            done_q.put(out.tolist())
            return
        if kind == "beam":
            def fill_beam(p) -> None:
                p["beam_width"] = np.asarray(
                    work["beam_width"], np.int32
                )
                p["length_penalty"] = np.asarray(
                    work["length_penalty"], np.float32
                )

            run_one_shot(
                work, done_q, OP_BEAM, fill_beam,
                lambda p: _beam_pod(
                    mirror.params, mirror.cfg, p, args.max_len
                ),
            )
            return
        if (
            draft is not None
            and not work.get("_stream")
            and not any(owners) and not pending
            and work["n"] == 1
            and work["temperature"] <= 0.0
            and work["min_new"] == 0
            and not work["presence"] and not work["frequency"]
            and not work["logit_bias"]
        ):
            # greedy single request against an IDLE pool: draft-and-
            # verify, identical output, fewer target passes (the
            # single-host routing rule plus the idle condition —
            # under concurrency the slot pool already wins, and a
            # one-shot spec round would stall co-batched streams)
            run_one_shot(
                work, done_q, OP_SPEC, lambda p: None,
                lambda p: _spec_pod(
                    mirror.params, draft, mirror.cfg, p, args.max_len
                ),
            )
            return
        req = _GenReq(work, done_q)
        open_reqs.append(req)
        for i in range(work["n"]):
            pending.append((req, i))

    def fail_open(exc: Exception) -> None:
        """A collective-path failure is pod-fatal: every waiting
        handler must get an answer before the raise, or its executor
        thread blocks forever."""
        for req in open_reqs:
            if not req.answered:
                req.answered = True
                req.done_q.put(exc)
        while True:
            try:
                _w, dq = frontend.requests.get_nowait()
            except queue.Empty:
                break
            dq.put(exc)

    def do_shutdown(leftover=None) -> None:
        """``leftover``: a (work, done_q) item already dequeued when
        SIGTERM landed — it is in neither open_reqs nor the queue, so
        it must be answered explicitly or its handler thread blocks
        forever and the interpreter can't exit."""
        p = _payload_zeros(args.max_len, S)
        p["op"] = np.asarray(OP_SHUTDOWN, np.int32)
        bcast(p)
        err = RuntimeError("pod is shutting down")
        if leftover is not None:
            leftover[1].put(err)
        fail_open(err)

    while True:
        if stopping.is_set():
            do_shutdown()
            return
        if not any(owners) and not pending:
            # fully idle: block for work, heartbeating on cadence so
            # followers' broadcast waits stay bounded
            frontend.ledger.engine_idle()
            got = None
            idle_since = time.monotonic()
            while got is None and not stopping.is_set():
                try:
                    got = frontend.requests.get(timeout=0.25)
                except queue.Empty:
                    if (
                        heartbeat_every is not None
                        and time.monotonic() - idle_since
                        >= heartbeat_every
                    ):
                        break
            if stopping.is_set():
                do_shutdown(leftover=got)
                return
            if got is None:
                p = _payload_zeros(args.max_len, S)
                p["op"] = np.asarray(OP_HEARTBEAT, np.int32)
                bcast(p)
                beat()
                continue
            classify(*got)
            continue
        # busy: drain whatever queued without blocking (scores and
        # beams run as their own lockstep ops between chunk rounds)
        while True:
            try:
                classify(*frontend.requests.get_nowait())
            except queue.Empty:
                break
        # sweep cancelled streams: their rows finish NOW, their slots
        # drop out of the next mask, the pool keeps serving the rest
        for req in open_reqs:
            if req.cancelled() and not req.answered:
                for r in req.rows:
                    r.finished = True
                finish_req(req)
        open_reqs[:] = [r for r in open_reqs if not r.answered]
        for i, o in enumerate(owners):
            if o is not None and o[0].rows[o[1]].finished:
                owners[i] = None
        # admission: at most one row per round (the payload carries
        # one prompt) — a fresh request reaches the pool within one
        # chunk of arriving
        payload = _payload_zeros(args.max_len, S)
        payload["op"] = np.asarray(OP_ROUND, np.int32)
        admit: Optional[Tuple[_GenReq, int, int]] = None
        free = [i for i, o in enumerate(owners) if o is None]
        while pending and free and admit is None:
            req, ridx = pending.popleft()
            if req.answered or req.cancelled():
                continue
            slot = free[0]
            _fill_admission(payload, req.work, ridx, slot)
            owners[slot] = (req, ridx)
            admit = (req, ridx, slot)
        mask = np.ones(S, np.int32)
        for i, o in enumerate(owners):
            if o is not None and not o[0].rows[o[1]].finished:
                mask[i] = 0
        run_chunk = int((mask == 0).any())
        if admit is None and not run_chunk:
            continue  # e.g. everything was just cancelled
        payload["run_chunk"] = np.asarray(run_chunk, np.int32)
        payload["done"] = mask
        # fuse K chunk-rounds into one dispatch on pure-decode rounds
        # (the single-host engine's host-re-entry rule, pod-shaped):
        # an admission round, queued HTTP work, a PENDING row waiting
        # for a free slot, or an active row watching stop sequences
        # keeps chunk granularity — stop eviction saves real decode,
        # and a waiting request must grab the next freed slot within
        # one chunk, not one window. Budget = each row's remaining
        # max_new, the window's early-exit gate.
        rounds = 1
        budget = np.zeros(S, np.int32)
        if (
            mirror.window > 1 and run_chunk and admit is None
            and not pending
            and frontend.requests.empty()
            and not any(
                o is not None and o[0].work["stop"]
                for o in owners
            )
        ):
            rounds = mirror.window
            for i, o in enumerate(owners):
                if o is not None and not mask[i]:
                    req_o, ridx_o = o
                    budget[i] = max(
                        req_o.work["max_new"]
                        - len(req_o.rows[ridx_o].emitted), 0,
                    )
        payload["rounds"] = np.asarray(rounds, np.int32)
        payload["budget"] = budget
        # ledger stamps at ADMISSION boundaries only (the single-host
        # engine's discipline): an admission round is prefill, the
        # rounds after it decode; chunk-only rounds stamp nothing
        if admit is not None:
            frontend.ledger.enter("prefill")
        bcast(payload)
        try:
            first, toks = _apply_round(mirror, payload)
        except Exception as exc:  # noqa: BLE001 — pod-fatal
            fail_open(exc)
            raise
        frontend.dispatches += 1
        if admit is not None:
            frontend.ledger.enter("decode")
        if admit is not None:
            req, ridx, _slot = admit
            row_append(req, req.rows[ridx], [first])
        if toks is not None:
            for i, o in enumerate(owners):
                if o is None or mask[i]:
                    continue
                req, ridx = o
                row = req.rows[ridx]
                if not row.finished:
                    row_append(req, row, toks[i])
        for i, o in enumerate(owners):
            if o is not None and o[0].rows[o[1]].finished:
                owners[i] = None
        for req in open_reqs:
            if not req.answered and all(
                r.finished for r in req.rows
            ):
                finish_req(req)
        open_reqs[:] = [r for r in open_reqs if not r.answered]
        beat()


def _run_follower_loop(args, mirror: _SlotMirror, dog,
                       multihost_utils, draft=None) -> None:
    """Followers replay whatever op the frontend broadcast; their
    device state stays bit-identical to process 0's because both run
    exactly `_apply_round` on exactly the broadcast operands."""
    while True:
        if args.wedge_file and os.path.exists(args.wedge_file):
            # fault injection: consume the trigger (wedge ONCE, so
            # the reincarnation comes back healthy) and stop making
            # progress without exiting — exactly what a stuck decode
            # looks like to the rest of the pod
            try:
                os.remove(args.wedge_file)
            except OSError:
                pass
            print("follower: injected wedge", flush=True)
            while True:
                time.sleep(3600)
        payload = multihost_utils.broadcast_one_to_all(
            _payload_zeros(args.max_len, args.slots)
        )
        op = int(payload["op"])
        if op == OP_SHUTDOWN:
            return
        if op == OP_HEARTBEAT:
            pass
        elif op == OP_SCORE:
            _score_pod(
                mirror.params, mirror.cfg, payload, args.max_len
            )
        elif op == OP_BEAM:
            _beam_pod(mirror.params, mirror.cfg, payload, args.max_len)
        elif op == OP_SPEC:
            _spec_pod(
                mirror.params, draft, mirror.cfg, payload,
                args.max_len,
            )
        elif op == OP_ROUND:
            _apply_round(mirror, payload)
        if dog is not None:
            dog.beat()


def main() -> int:
    from jax.experimental import multihost_utils

    from ..discovery.consul import ConsulBackend
    from ..models.transformer import TransformerConfig, init_params
    from ..parallel import MeshPlan, initialize_from_catalog, make_mesh
    from .modelcfg import derive_d_ff, enable_compile_cache

    enable_compile_cache()

    parser = argparse.ArgumentParser(
        description="multi-host pod inference server"
    )
    parser.add_argument("--process-id", type=int, required=True)
    parser.add_argument("--num-processes", type=int, required=True)
    parser.add_argument("--catalog", required=True)
    parser.add_argument("--coordinator-port", type=int, default=0)
    parser.add_argument("--advertise-address", default="")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--max-len", type=int, default=512)
    parser.add_argument("--d-model", type=int, default=256)
    parser.add_argument("--n-layers", type=int, default=2)
    parser.add_argument("--n-heads", type=int, default=4)
    parser.add_argument("--n-kv-heads", type=int, default=0)
    parser.add_argument("--vocab", type=int, default=1024)
    parser.add_argument("--checkpoint-dir", default="",
                        help="shared-storage checkpoint the WHOLE pod "
                        "restores in lockstep (orbax is a global "
                        "checkpointer)")
    parser.add_argument("--use-ema", action="store_true")
    parser.add_argument("--slots", type=int, default=4,
                        help="slot-pool size: how many requests decode "
                        "concurrently in lockstep (also the n / "
                        "beam_width budget); KV memory scales with it")
    parser.add_argument("--stream-chunk", type=int, default=8,
                        help="tokens per lockstep chunk round — the "
                        "admission latency, the SSE delta "
                        "granularity, and the watchdog's progress "
                        "quantum")
    parser.add_argument("--slot-window", type=int, default=4,
                        help="chunk-rounds fused into one device "
                        "dispatch on pure-decode rounds (device-side "
                        "loop, early exit on done/budget); "
                        "admissions, queued work and stop-sequence "
                        "watches keep chunk granularity. 1 = off. "
                        "The watchdog quantum grows to "
                        "window*stream-chunk tokens on fused rounds")
    parser.add_argument("--draft-layers", type=int, default=0,
                        help="self-speculative decoding: greedy "
                        "single requests against an idle pool draft "
                        "with the model's first N layers and verify "
                        "in chunks — identical output, fewer target "
                        "passes (0 = off)")
    parser.add_argument("--speculate", type=int, default=4,
                        help="draft tokens per speculative round")
    parser.add_argument("--kv-int8", action="store_true",
                        help="serve with the int8 KV cache (half the "
                        "KV bytes; every process quantizes "
                        "identically, so lockstep answers are still "
                        "deterministic)")
    parser.add_argument("--prefill-chunk", type=int, default=0,
                        help="admissions longer than N prefill in "
                        "fixed-size pieces (O(N) peak activations, "
                        "bounded piece-length set; local programs, "
                        "so compile skew between processes only "
                        "delays). 0 = one-shot admission prefill; "
                        "prompts taking the --sp ring skip this")
    parser.add_argument("--prefix-cache", type=int, default=0,
                        help="prefix KV reuse on the pod: every "
                        "process keeps an IDENTICAL LRU of the last "
                        "N admitted prompts' KV rows (admissions are "
                        "broadcast, so cache state stays lockstep by "
                        "construction); admissions sharing a cached "
                        "prefix rewind+extend instead of full "
                        "prefill. 0 = off; rejects --sp and --window")
    parser.add_argument("--window", type=int, default=0,
                        help="sliding-window attention: each slot's "
                        "KV cache is a ring of min(window, max_len) "
                        "entries, bounding decode KV memory by the "
                        "window instead of max_len (0 = full "
                        "attention). Static config, so lockstep "
                        "dispatch is unchanged; composes with "
                        "--kv-int8 but not --draft-layers")
    parser.add_argument("--moe-experts", type=int, default=0,
                        help="switch-MoE experts; must match the "
                        "checkpoint being served and divide by the "
                        "model-parallel axis (experts shard over it)")
    parser.add_argument("--int8", action="store_true",
                        help="weight-only int8: ~4x smaller resident "
                        "params on every host (each process quantizes "
                        "its shards identically in lockstep)")
    parser.add_argument("--lora-dir", default="",
                        help="merge a trained LoRA adapter checkpoint "
                        "into the base weights at load — restored "
                        "through the same orbax global barriers as "
                        "--checkpoint-dir, before any --int8")
    parser.add_argument("--lora-rank", type=int, default=0,
                        help="rank of the adapter in --lora-dir")
    parser.add_argument("--text", action="store_true",
                        help="byte-tokenizer /v1/completions on the "
                        "frontend (vocab must be >= 259)")
    parser.add_argument("--sp", type=int, default=1,
                        help="context-parallel admission: a seq axis "
                        "of this many devices rings long-prompt "
                        "prefills (ops/ring_attention.py) so prefill "
                        "activation memory is bounded by prompt/sp "
                        "per device; decode stays on the replicated "
                        "slot pool. Composes with --dp and tensor "
                        "parallelism (dp x sp x tp mesh); not with "
                        "--window or --draft-layers")
    parser.add_argument("--cp-min-len", type=int, default=0,
                        help="minimum prompt length that rings over "
                        "the seq axis (shorter prompts prefill "
                        "replicated); 0 derives the seq axis size")
    parser.add_argument("--dp", type=int, default=1,
                        help="data-parallel axis size: the global "
                        "device count factors as (dp, devices/dp) — "
                        "model shards over the inner axis")
    parser.add_argument("--watchdog", type=float, default=0.0,
                        help="decode-progress deadline in seconds "
                        "(0 = off): every process hard-exits %d when "
                        "a broadcast+decode cycle stalls past it. "
                        "Generation is chunked, so size it above one "
                        "chunk round plus the slowest ONE-SHOT op "
                        "(a beam round, a score round, or an "
                        "unwarmed-shape compile)"
                        % WATCHDOG_EXIT)
    parser.add_argument("--startup-grace", type=float, default=300.0,
                        help="first-beat grace covering rendezvous + "
                        "restore + warmup compile")
    parser.add_argument("--wedge-file", default="",
                        help="fault injection (tests): when this file "
                        "exists, a follower consumes it and wedges — "
                        "stops making progress without exiting — to "
                        "prove the watchdog path")
    args = parser.parse_args()

    # armed BEFORE rendezvous (the trainer's pattern): a peer that
    # died between catalog registration and its first collective
    # wedges our rendezvous/warmup just as silently as a mid-serve
    # death, and the grace window covers the startup compile
    dog = None
    if args.watchdog > 0:
        from ..parallel import StepWatchdog

        dog = StepWatchdog(
            args.watchdog, exit_code=WATCHDOG_EXIT
        ).start(grace_s=max(args.startup_grace, args.watchdog))

    if args.slots < 1 or args.stream_chunk < 1:
        raise SystemExit("--slots and --stream-chunk must be >= 1")
    if args.window < 0:
        raise SystemExit("--window must be >= 0")
    if args.dp < 1 or args.sp < 1:
        raise SystemExit("--dp and --sp must be >= 1")
    if args.sp > 1 and args.window > 0:
        raise SystemExit(
            "--sp does not compose with --window (ring attention "
            "rejects sliding windows)"
        )
    if args.sp > 1 and args.draft_layers > 0:
        raise SystemExit(
            "--sp does not compose with --draft-layers (speculative "
            "prefill is chunk-driven)"
        )
    if args.prefix_cache < 0:
        raise SystemExit("--prefix-cache must be >= 0")
    if args.prefill_chunk < 0:
        raise SystemExit("--prefill-chunk must be >= 0")
    if args.prefix_cache > 0 and args.sp > 1:
        raise SystemExit(
            "--prefix-cache does not compose with --sp (cached "
            "prefixes bypass the ring)"
        )
    if args.prefix_cache > 0 and args.window > 0:
        raise SystemExit(
            "--prefix-cache does not compose with --window (a ring "
            "cache's stale rows are live window context)"
        )
    cp_min_len = args.cp_min_len
    if args.sp <= 1 and cp_min_len:
        raise SystemExit("--cp-min-len requires --sp > 1")
    if args.sp > 1:
        # ONE policy for deriving/clamping/refusing the threshold,
        # shared with the single-host --cp (parallel/context.py)
        from ..parallel.context import resolve_cp_min_len

        try:
            cp_min_len = resolve_cp_min_len(
                cp_min_len, args.sp, args.max_len, flag="sp"
            )
        except ValueError as exc:
            raise SystemExit(str(exc)) from None
    if args.window > 0 and args.draft_layers > 0:
        # same composition rule as the single-host server
        # (workload/serve.py): speculative rollback cannot undo
        # ring-cache writes. Checked BEFORE rendezvous so every
        # process fails at startup, not mid-collective.
        raise SystemExit(
            "--draft-layers does not compose with --window "
            "(speculative rollback cannot undo ring-cache writes)"
        )
    if 4 + args.stream_chunk + 1 > args.max_len:
        # warmup pushes a 4-id prompt + chunk+1 tokens through the
        # pool; a legal but tiny --max-len must fail loudly HERE
        raise SystemExit(
            f"--max-len {args.max_len} too small for the warmup "
            f"request (needs >= {4 + args.stream_chunk + 1})"
        )
    kw = {}
    if args.coordinator_port:
        kw["coordinator_port"] = args.coordinator_port
    initialize_from_catalog(
        ConsulBackend(address=args.catalog),
        args.process_id,
        args.num_processes,
        advertise_address=args.advertise_address,
        **kw,
    )
    cfg = TransformerConfig(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads,
        n_layers=args.n_layers,
        d_ff=derive_d_ff(args.d_model),
        max_seq_len=args.max_len,
        moe_experts=args.moe_experts,
        kv_int8=args.kv_int8,
        window=args.window,
    )
    if args.text:
        from .text import ByteTokenizer

        if args.vocab < ByteTokenizer.N_IDS:
            # EVERY process must fail here, not just the frontend:
            # a frontend dying after rendezvous would strand the
            # followers in their first broadcast
            raise SystemExit(
                f"--text needs vocab >= {ByteTokenizer.N_IDS}, got "
                f"{args.vocab}"
            )
    n_global = jax.device_count()
    if n_global % (args.dp * args.sp):
        raise SystemExit(
            f"--dp {args.dp} x --sp {args.sp} must divide the "
            f"{n_global} global devices"
        )
    n_model = n_global // (args.dp * args.sp)
    if cfg.n_heads % n_model:
        raise SystemExit(
            f"model axis {n_model} must divide n_heads {cfg.n_heads}"
        )
    if cfg.moe_experts > 1 and cfg.moe_experts % n_model:
        # experts shard over the model axis (the ep x tp layout) —
        # every process must fail here, not mid-rendezvous
        raise SystemExit(
            f"model axis {n_model} must divide moe_experts "
            f"({cfg.moe_experts})"
        )
    mesh = make_mesh(
        jax.devices(),
        plan=MeshPlan(data=args.dp, model=n_model, seq=args.sp),
    )
    if args.checkpoint_dir:
        from .modelcfg import restore_params_only

        restored = restore_params_only(
            cfg, mesh, args.checkpoint_dir, use_ema=args.use_ema
        )
        if restored is None:
            raise SystemExit(f"no checkpoint in {args.checkpoint_dir}")
        params, step = restored
        if args.process_id == 0:
            print(f"pod serving checkpoint step {step}", flush=True)
    else:
        host_params = jax.tree.map(
            np.asarray, init_params(jax.random.PRNGKey(0), cfg)
        )
        params = shard_params_global(host_params, mesh, cfg)

    from .modelcfg import validate_lora_flags

    validate_lora_flags(args.lora_dir, args.lora_rank)
    if args.lora_dir:
        # merge BEFORE any quantization (int8 bases aren't
        # adaptable); the orbax restore barriers keep it lockstep
        from .modelcfg import merge_lora

        params, lora_step = merge_lora(
            params, cfg, mesh, args.lora_dir, args.lora_rank
        )
        if args.process_id == 0:
            print(
                f"pod merged lora adapter (rank {args.lora_rank}, "
                f"step {lora_step})", flush=True,
            )
    if args.int8:
        # every process quantizes its shards with the same program
        # (scales reduce over replicated-or-sharded axes under SPMD),
        # so lockstep dispatch stays identical
        from ..models.quantized import quantize_model_params

        params = quantize_model_params(params)
        if args.process_id == 0:
            print("pod int8 weight-only params", flush=True)

    draft = None
    if args.draft_layers > 0:
        if args.speculate < 1:
            raise SystemExit("--speculate must be >= 1")
        if not 0 < args.draft_layers < cfg.n_layers:
            # every process must fail here, not mid-rendezvous
            raise SystemExit(
                f"--draft-layers must be in (0, {cfg.n_layers})"
            )
        from ..models.speculative import layer_prefix_draft

        draft_params, draft_cfg = layer_prefix_draft(
            params, cfg, args.draft_layers
        )
        draft = (draft_params, draft_cfg, args.speculate)

    frontend = None
    if args.process_id == 0:
        frontend = _Frontend(
            args.host, args.port, args.max_len, cfg.vocab_size,
            text=args.text, stream_chunk=args.stream_chunk,
            slots=args.slots, cfg=cfg,
            prefix_entries=args.prefix_cache,
            pod_info={
                "vocab_size": cfg.vocab_size,
                "d_model": cfg.d_model,
                "n_heads": cfg.n_heads,
                "n_kv_heads": cfg.kv_heads,
                "n_layers": cfg.n_layers,
                "max_len": args.max_len,
                "text": args.text,
                "stream": True,
                "kv_int8": args.kv_int8,
                "window": args.window or None,
                "prefix_cache": (
                    {"entries": args.prefix_cache}
                    if args.prefix_cache > 0 else None
                ),
                "prefill_chunk": args.prefill_chunk or None,
                "moe_experts": cfg.moe_experts,
                "int8": args.int8,
                "lora": (
                    {"rank": args.lora_rank}
                    if args.lora_dir else None
                ),
                "speculative": (
                    {
                        "draft_layers": args.draft_layers,
                        "speculate": args.speculate,
                    }
                    if draft is not None else None
                ),
                "slot_engine": {
                    "slots": args.slots,
                    "chunk": args.stream_chunk,
                    "window": max(1, args.slot_window),
                },
                "pod": {
                    "num_processes": args.num_processes,
                    "devices": n_global,
                    "mesh": {
                        "data": args.dp, "seq": args.sp,
                        "model": n_model,
                    },
                    "watchdog_s": args.watchdog or None,
                },
                # same JSON shape as the single-host /v1/model cp
                # block (workload/serve.py) so clients read one schema
                "cp": (
                    {"seq": args.sp, "min_len": cp_min_len}
                    if args.sp > 1 else None
                ),
            },
        )
        frontend.start()
        print(f"pod frontend on {args.host}:{frontend.port} "
              f"({n_global} global devices, data={args.dp} "
              f"model={n_model}, slots={args.slots})",
              flush=True)

    # warmup in lockstep before /health goes 200 (warm_pod compiles
    # the pool's whole serve-path program set; see its docstring for
    # the no-post-grace-compiles invariant)
    if frontend is not None:
        # ledger: everything until ready flips is compile_warmup —
        # stamped before /health goes 200 so the pod's first scrape
        # already shows its compile badput (the no-idle-lie rule)
        frontend.ledger.set_override("compile_warmup")
    mirror = _SlotMirror(
        cfg, params, args.max_len, args.slots, args.stream_chunk,
        mesh=mesh, sp=args.sp, cp_min_len=cp_min_len,
        prefix_entries=args.prefix_cache,
        prefill_chunk=args.prefill_chunk,
        window=max(1, args.slot_window),
    )
    warm_pod(mirror)
    if draft is not None:
        # compile the spec path's whole program set inside the grace —
        # one shared rule for both servers (models/speculative.py)
        from ..models.speculative import warm_speculative

        draft_params, draft_cfg, spec_k = draft
        warm_speculative(
            params, draft_params, cfg, draft_cfg, spec_k, args.max_len,
        )
    if dog is not None:
        dog.beat()  # startup done: tighten to the serve deadline
    if frontend is not None:
        # live prefix stats for /v1/model (the mirror owns the cache)
        frontend.prefix_cache = mirror.prefix_cache
        frontend.ledger.clear_override()
        frontend.ledger.enter("idle")
        frontend.ready = True
        print("pod warm; accepting traffic", flush=True)

    # graceful pod shutdown: TERM on the FRONTEND broadcasts
    # OP_SHUTDOWN so followers exit cleanly. Followers keep the
    # default TERM disposition — a follower can't exit mid-collective
    # anyway, so its supervisor's TERM-then-KILL handles it.
    stopping = threading.Event()
    if frontend is not None:
        import signal as signal_mod

        signal_mod.signal(
            signal_mod.SIGTERM, lambda s, f: stopping.set()
        )
        _run_frontend_loop(
            args, frontend, mirror, dog, multihost_utils, stopping,
            draft=draft,
        )
    else:
        _run_follower_loop(
            args, mirror, dog, multihost_utils, draft=draft
        )
    if dog is not None:
        dog.stop()
    if frontend is not None:
        frontend.stop()
        print("pod frontend stopped", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
