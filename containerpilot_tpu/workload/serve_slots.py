"""The slot engine: continuous decode admission for serving.

``Batcher`` (serve_batcher.py) coalesces requests that ARRIVE
together; this engine lets requests JOIN a running decode. A fixed
pool of S slots decodes in K-token chunks (models/slots.py — one
compiled program, static shapes); between chunks the engine harvests
finished rows and admits queued requests into free slots, so a short
request lands mid-flight next to a long one instead of waiting for
the whole batch generation to finish.

Per-request output is byte-identical to a solo ``generate`` call with
the same arguments (the key schedule is reproduced exactly; each
slot's draw depends only on its own key and step index) — tested
against staggered concurrent traffic.

One engine per server process; it owns a worker thread and the pool
buffers (chunk/insert donate them). ``submit`` is thread-safe and
returns a concurrent.futures.Future resolving to the generated ids
(pad-trimmed after eos, capped at the request's max_new_tokens).
"""
from __future__ import annotations

import logging
import queue
import threading
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.decode import (
    BIAS_SLOTS_MAX,
    _jitted_prefill,
    normalize_logit_bias,
)
from ..models.slots import (
    append_chunk,
    decode_slots_chunk,
    first_sample,
    insert_row,
    seed_counts,
    slot_cache,
)
from ..models.transformer import TransformerConfig
from .serve_prefix import MIN_REUSE as PREFIX_MIN_REUSE

log = logging.getLogger("containerpilot.serve.slots")


@dataclass
class _Request:
    tokens: List[int]
    max_new: int
    temperature: float
    top_k: int
    top_p: float
    eos_id: int
    pad_id: int
    seed: int
    min_new: int = 0
    presence: float = 0.0
    frequency: float = 0.0
    # [BIAS_SLOTS_MAX] logit_bias row (idx -1 = unused) — always
    # materialized at the engine's ONE static width so biased and
    # plain requests share every compiled program
    bias_idx: Optional[object] = None
    bias_val: Optional[object] = None
    # streaming: called from the worker thread with each newly emitted
    # token delta (already eos/max_new-capped — concatenation equals
    # the future's final result exactly)
    on_tokens: Optional[callable] = None
    # cooperative cancel (client disconnect): the worker frees the
    # slot at the next chunk boundary instead of decoding to the end
    cancel: Optional[threading.Event] = None
    future: Future = field(default_factory=Future)


@dataclass
class _Slot:
    req: _Request
    emitted: List[int] = field(default_factory=list)
    finished: bool = False  # eos seen (pads follow) or max_new reached


class SlotEngine:
    def __init__(
        self,
        cfg: TransformerConfig,
        params,
        max_len: int,
        slots: int = 8,
        chunk: int = 8,
        cp_mesh=None,
        cp_min_len: int = 0,
        prefill_chunk: int = 0,
        prefix_cache=None,
    ) -> None:
        if slots < 1 or chunk < 1:
            raise ValueError("slots and chunk must be >= 1")
        # context-parallel admission: prompts at least cp_min_len
        # long ring their prefill over cp_mesh's seq axis
        # (parallel/context.py cp_prefill_with_remainder — the same
        # recipe the pod's --sp path runs) before joining the pool.
        # Single-process here, so the maximal axis-divisible head
        # applies (no cross-process compile-skew hazard; see
        # cp_head_buckets for the pod's bucketed variant).
        if cp_mesh is not None and cfg.window > 0:
            raise ValueError(
                "cp does not compose with sliding windows (ring "
                "attention rejects them)"
            )
        self.cp_mesh = cp_mesh
        self.cp_min_len = cp_min_len
        if cp_mesh is not None:
            # the ONE threshold policy (derive/clamp/never-engages)
            # applies no matter who constructs the engine — a direct
            # SlotEngine(cp_mesh=...) must not silently ring every
            # prompt or accept a threshold no prompt can reach
            from ..parallel.context import resolve_cp_min_len

            self.cp_min_len = resolve_cp_min_len(
                cp_min_len, cp_mesh.shape.get("seq", 1), max_len
            )
        # chunked admission: prompts longer than prefill_chunk
        # prefill in fixed-size pieces (models/decode.chunked_prefill
        # — peak activation memory O(chunk) instead of O(prompt), a
        # bounded piece-length set so compile churn stays finite).
        # Prompts that take the cp ring skip this (the ring already
        # bounds activations; its remainder decomposes separately).
        self.prefill_chunk = prefill_chunk
        if prefill_chunk < 0:
            raise ValueError("prefill_chunk must be >= 0")
        # prefix KV reuse under continuous batching: admissions with a
        # cached prefix rewind+extend instead of full prefill, and
        # every admission's prompt cache is stored for future turns.
        # Sound because stored entries are standalone buffers: extend
        # never donates its cache operand and insert_row COPIES the
        # row into the (donated) pool, so pool churn can't touch them.
        self.prefix_cache = prefix_cache
        if prefix_cache is not None:
            if cp_mesh is not None:
                raise ValueError(
                    "prefix cache does not compose with cp (cached "
                    "prefixes bypass the ring)"
                )
            if cfg.window > 0:
                raise ValueError(
                    "prefix cache does not compose with sliding "
                    "windows (a ring cache's stale rows are live "
                    "window context)"
                )
        # sliding windows (cfg.window > 0) compose: each slot's ring
        # cache is row-local, and admission writes the freshly
        # prefilled row WHOLESALE (insert_row dynamic_update_slices
        # the entire [layers, 1, ring, kv, hd] row plus its pos), so
        # a reused slot carries zero context from its previous
        # occupant — byte parity incl. re-admission is tested in
        # tests/test_slots.py::test_window_*
        self.cfg = cfg
        self.params = params
        self.max_len = max_len
        self.slots = slots
        self.chunk = chunk
        self._pool = slot_cache(cfg, slots, max_len)
        self._last = jnp.zeros((slots,), jnp.int32)
        self._keys = jnp.zeros((slots, 2), jnp.uint32)
        self._step_idx = np.zeros((slots,), np.int32)
        self._temp = np.zeros((slots,), np.float32)
        self._top_k = np.zeros((slots,), np.int32)
        self._top_p = np.zeros((slots,), np.float32)
        self._eos = np.full((slots,), -1, np.int32)
        self._pad = np.zeros((slots,), np.int32)
        self._min_new = np.zeros((slots,), np.int32)
        self._presence = np.zeros((slots,), np.float32)
        self._frequency = np.zeros((slots,), np.float32)
        self._bias_idx = np.full((slots, BIAS_SLOTS_MAX), -1, np.int32)
        self._bias_val = np.zeros((slots, BIAS_SLOTS_MAX), np.float32)
        # generated-token counts per slot, device-resident (the chunk
        # program reads and donates it like the pool)
        self._counts = jnp.zeros((slots, cfg.vocab_size), jnp.float32)
        self._done = np.ones((slots,), bool)  # empty slots are "done"
        self._active: List[Optional[_Slot]] = [None] * slots
        self._queue: "queue.Queue[Optional[_Request]]" = queue.Queue()
        self._submit_lock = threading.Lock()
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="slot-engine", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------- API

    def submit(
        self,
        tokens: List[int],
        max_new: int,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 0.0,
        eos_id: int = -1,
        pad_id: int = 0,
        seed: int = 0,
        min_new: int = 0,
        presence_penalty: float = 0.0,
        frequency_penalty: float = 0.0,
        logit_bias=None,
        on_tokens: Optional[callable] = None,
        cancel: Optional[threading.Event] = None,
    ) -> Future:
        """Queue one sequence; resolves to its generated ids.

        ``logit_bias``: a {token_id: bias} dict (generate's contract,
        validated here so a bad request fails the submit, not the
        pool). ``on_tokens`` (worker-thread callback) streams each
        emitted delta; ``cancel`` (a threading.Event the caller sets,
        e.g. on client disconnect) frees the slot at the next chunk
        boundary — the future then resolves with whatever was
        emitted."""
        if max_new < 1:
            raise ValueError("max_new must be >= 1")
        if not 0 <= min_new <= max_new:
            raise ValueError("min_new must be in [0, max_new]")
        if not tokens or len(tokens) >= self.max_len:
            raise ValueError(
                f"prompt must be 1..{self.max_len - 1} tokens"
            )
        if len(tokens) + max_new > self.max_len:
            raise ValueError(
                f"prompt {len(tokens)} + max_new {max_new} exceeds "
                f"max_len {self.max_len}"
            )
        rows_idx, rows_val = normalize_logit_bias(
            self.cfg, 1, logit_bias or None, slots=BIAS_SLOTS_MAX
        )
        bias_idx, bias_val = rows_idx[0], rows_val[0]
        req = _Request(
            tokens=list(tokens), max_new=int(max_new),
            temperature=float(temperature), top_k=int(top_k),
            top_p=float(top_p), eos_id=int(eos_id), pad_id=int(pad_id),
            seed=int(seed), min_new=int(min_new),
            presence=float(presence_penalty),
            frequency=float(frequency_penalty),
            bias_idx=bias_idx, bias_val=bias_val,
            on_tokens=on_tokens, cancel=cancel,
        )
        # atomic with stop()'s drain: either this put lands before the
        # drain (and gets cancelled there) or the stopped check raises
        with self._submit_lock:
            if self._stopped.is_set():
                raise RuntimeError("engine is stopped")
            self._queue.put(req)
        return req.future

    def stop(self) -> None:
        with self._submit_lock:
            self._stopped.set()
        self._queue.put(None)  # wake the worker
        self._thread.join(timeout=30)
        for slot in self._active:
            if slot is not None and not slot.req.future.done():
                slot.req.future.cancel()
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not None and not req.future.done():
                req.future.cancel()

    @property
    def stats(self) -> dict:
        return {
            "slots": self.slots,
            "chunk": self.chunk,
            "active": sum(s is not None for s in self._active),
            "queued": self._queue.qsize(),
        }

    # ----------------------------------------------------------- worker

    def _admit(self, slot_id: int, req: _Request) -> None:
        """Prefill the prompt into the slot and sample token 0 with
        generate's exact key schedule."""
        cfg = self.cfg
        logits = row_cache = None
        pc = self.prefix_cache
        # prompts shorter than MIN_REUSE skip the prefix machinery
        # entirely: they can never be reused (plan_reuse requires a
        # MIN_REUSE match) so storing them only pins dead LRU entries
        # — this also keeps warmup's dummy request out of the cache
        # and its stats
        use_pc = pc is not None and len(req.tokens) >= PREFIX_MIN_REUSE
        if use_pc:
            from .serve_prefix import reuse_admission

            hit = reuse_admission(
                pc, req.tokens, cfg, self.params,
                chunk_len=self.prefill_chunk,
            )
            if hit is not None:
                logits, row_cache = hit
        if row_cache is None:
            if (
                self.cp_mesh is not None
                and len(req.tokens) >= self.cp_min_len
            ):
                import numpy as _np

                from ..parallel.context import cp_prefill_with_remainder

                logits, row_cache = cp_prefill_with_remainder(
                    self.params,
                    _np.asarray([req.tokens], _np.int32),
                    cfg, self.cp_mesh, self.max_len,
                )
            elif (
                self.prefill_chunk > 0
                and len(req.tokens) > self.prefill_chunk
            ):
                from ..models.decode import chunked_prefill

                logits, row_cache = chunked_prefill(
                    self.params, jnp.asarray([req.tokens], jnp.int32),
                    cfg, self.max_len, chunk_len=self.prefill_chunk,
                )
            else:
                # host->device transfer only on the path that uses it
                prompt = jnp.asarray([req.tokens], jnp.int32)
                logits, row_cache = _jitted_prefill(
                    cfg, self.max_len
                )(self.params, prompt)
        if use_pc:
            # store the completed prompt's cache for future turns
            # (standalone buffer — see the __init__ soundness note)
            pc.store(tuple(req.tokens), row_cache)
        # the server-wide convention: row i of a request samples from
        # fold_in(PRNGKey(seed), i) — single-row here, so i = 0
        # (serve_batcher/serve_prefix/serve_strategies do the same),
        # keeping seeded output identical across serving configs
        row_key = jax.random.fold_in(
            jax.random.PRNGKey(req.seed), 0
        )
        first = first_sample(
            logits, row_key, req.temperature, req.top_k, req.top_p,
            cfg, eos_id=req.eos_id, min_new=req.min_new,
            bias_idx=req.bias_idx, bias_val=req.bias_val,
        )
        first_host = int(jax.device_get(first))
        self._pool = insert_row(self._pool, row_cache, slot_id, cfg)
        self._last = self._last.at[slot_id].set(first)
        self._keys = self._keys.at[slot_id].set(row_key)
        self._step_idx[slot_id] = 1
        self._temp[slot_id] = req.temperature
        self._top_k[slot_id] = req.top_k
        self._top_p[slot_id] = req.top_p
        self._eos[slot_id] = req.eos_id
        self._pad[slot_id] = req.pad_id
        self._min_new[slot_id] = req.min_new
        self._presence[slot_id] = req.presence
        self._frequency[slot_id] = req.frequency
        self._bias_idx[slot_id] = req.bias_idx
        self._bias_val[slot_id] = req.bias_val
        self._counts = self._counts.at[slot_id].set(
            seed_counts(self.cfg.vocab_size, first_host, req.eos_id)
        )
        state = _Slot(req=req, emitted=[first_host])
        if first_host == req.eos_id or req.max_new <= 1:
            state.finished = True
        self._done[slot_id] = state.finished
        self._active[slot_id] = state
        self._notify(req, [first_host])

    def _harvest(self, slot_id: int) -> None:
        state = self._active[slot_id]
        req = state.req
        out = state.emitted[: req.max_new]
        if req.eos_id >= 0 and req.eos_id in out:
            # keep the eos, pad-trim what follows (generate's contract
            # after its own trim step)
            out = out[: out.index(req.eos_id) + 1]
        self._active[slot_id] = None
        self._done[slot_id] = True
        if not req.future.done():
            req.future.set_result(out)

    @staticmethod
    def _notify(req: _Request, delta: List[int]) -> None:
        """Deliver a streamed delta; a raising callback (e.g. the
        consumer's event loop already closed in a shutdown race) must
        never escape into _run — it would kill the worker thread and
        strand every in-flight future while /health stays 200."""
        if req.on_tokens is None:
            return
        try:
            req.on_tokens(list(delta))
        except Exception:  # noqa: BLE001
            log.exception("on_tokens callback failed; dropping delta")

    def _sweep_cancelled(self) -> None:
        """Free slots whose requests were cancelled (client gone):
        the slot returns to the pool at this chunk boundary and the
        future resolves with the partial emission (nobody is usually
        waiting — the disconnect is why we're here)."""
        for i, s in enumerate(self._active):
            if (
                s is not None
                and s.req.cancel is not None
                and s.req.cancel.is_set()
            ):
                self._active[i] = None
                self._done[i] = True
                if not s.req.future.done():
                    s.req.future.set_result(list(s.emitted))
                log.info(
                    "slot %d freed mid-generation (%d/%d tokens): "
                    "request cancelled", i, len(s.emitted), s.req.max_new,
                )

    def _run(self) -> None:
        while not self._stopped.is_set():
            self._sweep_cancelled()
            free = [i for i, s in enumerate(self._active) if s is None]
            any_active = any(s is not None for s in self._active)
            # block for work only when fully idle; otherwise drain
            # whatever is queued into free slots and keep decoding
            try:
                block = not any_active
                while free:
                    req = self._queue.get(block=block, timeout=None)
                    if req is None:  # stop sentinel
                        return
                    block = False
                    if req.cancel is not None and req.cancel.is_set():
                        req.future.cancel()  # left before admission
                        continue
                    try:
                        self._admit(free.pop(0), req)
                    except Exception as exc:  # noqa: BLE001
                        if not req.future.done():
                            req.future.set_exception(exc)
            except queue.Empty:
                pass
            # harvest admissions that finished at token 0
            for i, s in enumerate(self._active):
                if s is not None and s.finished:
                    self._harvest(i)
            if not any(s is not None for s in self._active):
                continue
            try:
                (self._pool, self._last, done_dev, self._counts,
                 toks) = (
                    decode_slots_chunk(
                        self.params, self._pool, self._last,
                        self._keys, jnp.asarray(self._step_idx),
                        jnp.asarray(self._temp),
                        jnp.asarray(self._top_k),
                        jnp.asarray(self._top_p),
                        jnp.asarray(self._eos),
                        jnp.asarray(self._pad),
                        jnp.asarray(self._min_new),
                        jnp.asarray(self._presence),
                        jnp.asarray(self._frequency),
                        jnp.asarray(self._bias_idx),
                        jnp.asarray(self._bias_val),
                        self._counts,
                        jnp.asarray(self._done),
                        self.cfg, self.chunk,
                    )
                )
            except Exception as exc:  # noqa: BLE001 — fail loud, once
                log.exception("slot chunk failed")
                for i, s in enumerate(self._active):
                    if s is not None and not s.req.future.done():
                        s.req.future.set_exception(exc)
                    self._active[i] = None
                    self._done[i] = True
                # the failed call DONATED the pool buffer; rebuild it
                # (all slots are free now) or every later admission
                # would die on a deleted array while /health stays 200
                self._pool = slot_cache(
                    self.cfg, self.slots, self.max_len
                )
                self._last = jnp.zeros((self.slots,), jnp.int32)
                self._keys = jnp.zeros((self.slots, 2), jnp.uint32)
                self._counts = jnp.zeros(
                    (self.slots, self.cfg.vocab_size), jnp.float32
                )
                continue
            # fetch BEFORE mutating step_idx: jnp.asarray may have
            # zero-copied the numpy buffer into the in-flight chunk,
            # and an in-place += racing the execution feeds it torn
            # step indices (the pod mirror learned this the hard way)
            toks_host = np.asarray(jax.device_get(toks))
            self._step_idx += self.chunk
            for i, state in enumerate(self._active):
                if state is None:
                    continue
                req = state.req
                before = len(state.emitted)
                ended = append_chunk(
                    state.emitted, toks_host[i], req.max_new,
                    req.eos_id,
                )
                if len(state.emitted) > before:
                    self._notify(req, state.emitted[before:])
                if ended:
                    self._harvest(i)
