"""The slot engine: continuous decode admission for serving.

``Batcher`` (serve_batcher.py) coalesces requests that ARRIVE
together; this engine lets requests JOIN a running decode. A fixed
pool of S slots decodes in fixed-size chunks (models/slots.py — one
compiled program set, static shapes); between dispatches the engine
harvests finished rows and admits queued requests into free slots, so
a short request lands mid-flight next to a long one instead of
waiting for the whole batch generation to finish.

The engine drives a **step program** (models/stepprog.py), not a
model directly: the plain transformer, quantized weights and
speculative draft/verify all implement the same
admit/dispatch/tokens/retire protocol, so every decode strategy
inherits admission, streaming, cancel, tracing and the ledger from
ONE driver. With ``window`` K > 1 the plain program fuses K
chunk-rounds into one device-side loop per host dispatch
(``decode_slots_window``): the host's per-round loop becomes a
per-K-window loop and dispatches/token falls ~K-fold on steady-state
decode. The host re-enters at chunk granularity exactly when a
decision is pending — queued admissions, a cancel flag, or stop —
the same lookahead test that already gated pipelining, generalized
from one round to one window.

Per-request output is byte-identical to a solo ``generate`` call with
the same arguments (the key schedule is reproduced exactly; each
slot's draw depends only on its own key and step index; a fused
window runs the same per-step body as K sequential chunks) — tested
against staggered concurrent traffic at K=1 and K>1.

One engine per server process; it owns a worker thread and the step
program's device buffers (chunk/insert donate them). ``submit`` is
thread-safe and returns a concurrent.futures.Future resolving to the
generated ids (pad-trimmed after eos, capped at the request's
max_new_tokens).
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..models.decode import (
    BIAS_SLOTS_MAX,
    _jitted_prefill,
    normalize_logit_bias,
)
from ..models.slots import append_chunk
from ..models.stepprog import make_step_program
from ..models.transformer import TransformerConfig
from .serve_prefix import MIN_REUSE as PREFIX_MIN_REUSE

log = logging.getLogger("containerpilot.serve.slots")


@dataclass
class _Request:
    tokens: List[int]
    max_new: int
    temperature: float
    top_k: int
    top_p: float
    eos_id: int
    pad_id: int
    seed: int
    min_new: int = 0
    presence: float = 0.0
    frequency: float = 0.0
    # [BIAS_SLOTS_MAX] logit_bias row (idx -1 = unused) — always
    # materialized at the engine's ONE static width so biased and
    # plain requests share every compiled program
    bias_idx: Optional[object] = None
    bias_val: Optional[object] = None
    # streaming: called from the worker thread with each newly emitted
    # token delta (already eos/max_new-capped — concatenation equals
    # the future's final result exactly)
    on_tokens: Optional[callable] = None
    # cooperative cancel (client disconnect): the worker frees the
    # slot at the next chunk boundary instead of decoding to the end
    cancel: Optional[threading.Event] = None
    # tracing (telemetry/tracing.py): a caller-owned dict the engine
    # stamps at REQUEST boundaries only — enqueued/admitted/
    # prefill_done/done (time.monotonic, tracing's clock) plus a
    # rounds count. Nothing is recorded per token or per round beyond
    # one int increment, so the hotpath decode loop stays
    # allocation-free; the caller converts the stamps to spans once,
    # after the future resolves (tracing.add_engine_spans).
    timings: Optional[dict] = None
    future: Future = field(default_factory=Future)


@dataclass
class _Slot:
    req: _Request
    emitted: List[int] = field(default_factory=list)
    finished: bool = False  # eos seen (pads follow) or max_new reached
    rounds: int = 0  # decode rounds this row rode (tracing metadata)


class SlotEngine:
    def __init__(
        self,
        cfg: TransformerConfig,
        params,
        max_len: int,
        slots: int = 8,
        chunk: int = 8,
        window: int = 4,
        cp_mesh=None,
        cp_min_len: int = 0,
        prefill_chunk: int = 0,
        prefix_cache=None,
        ledger=None,
        program=None,
        prefill_floor_s: float = 0.0,
    ) -> None:
        if slots < 1 or chunk < 1:
            raise ValueError("slots and chunk must be >= 1")
        if prefill_floor_s < 0:
            raise ValueError("prefill_floor_s must be >= 0")
        # synthetic cold-admission floor (chaos/bench seam, never set
        # in production): every COLD prefill of a reusable-length
        # prompt blocks the worker thread this many extra seconds —
        # standing in for a production-sized prompt's prefill compute
        # on the toy model, the way the chaos suite's ``slow`` faults
        # stand in for decode time. Reuse hits (including handed-off
        # KV) skip it entirely, which is exactly the interference the
        # disaggregation bench measures.
        self.prefill_floor_s = prefill_floor_s
        if window < 1:
            raise ValueError("window must be >= 1")
        # context-parallel admission: prompts at least cp_min_len
        # long ring their prefill over cp_mesh's seq axis
        # (parallel/context.py cp_prefill_with_remainder — the same
        # recipe the pod's --sp path runs) before joining the pool.
        # Single-process here, so the maximal axis-divisible head
        # applies (no cross-process compile-skew hazard; see
        # cp_head_buckets for the pod's bucketed variant).
        if cp_mesh is not None and cfg.window > 0:
            raise ValueError(
                "cp does not compose with sliding windows (ring "
                "attention rejects them)"
            )
        self.cp_mesh = cp_mesh
        self.cp_min_len = cp_min_len
        if cp_mesh is not None:
            # the ONE threshold policy (derive/clamp/never-engages)
            # applies no matter who constructs the engine — a direct
            # SlotEngine(cp_mesh=...) must not silently ring every
            # prompt or accept a threshold no prompt can reach
            from ..parallel.context import resolve_cp_min_len

            self.cp_min_len = resolve_cp_min_len(
                cp_min_len, cp_mesh.shape.get("seq", 1), max_len
            )
        # chunked admission: prompts longer than prefill_chunk
        # prefill in fixed-size pieces (models/decode.chunked_prefill
        # — peak activation memory O(chunk) instead of O(prompt), a
        # bounded piece-length set so compile churn stays finite).
        # Prompts that take the cp ring skip this (the ring already
        # bounds activations; its remainder decomposes separately).
        self.prefill_chunk = prefill_chunk
        if prefill_chunk < 0:
            raise ValueError("prefill_chunk must be >= 0")
        # prefix KV reuse under continuous batching: admissions with a
        # cached prefix rewind+extend instead of full prefill, and
        # every admission's prompt cache is stored for future turns.
        # Sound because stored entries are standalone buffers: extend
        # never donates its cache operand and insert_row COPIES the
        # row into the (donated) pool, so pool churn can't touch them.
        self.prefix_cache = prefix_cache
        if prefix_cache is not None:
            if cp_mesh is not None:
                raise ValueError(
                    "prefix cache does not compose with cp (cached "
                    "prefixes bypass the ring)"
                )
            if cfg.window > 0:
                raise ValueError(
                    "prefix cache does not compose with sliding "
                    "windows (a ring cache's stale rows are live "
                    "window context)"
                )
        # sliding windows (cfg.window > 0) compose: each slot's ring
        # cache is row-local, and admission writes the freshly
        # prefilled row WHOLESALE (insert_row dynamic_update_slices
        # the entire [layers, 1, ring, kv, hd] row plus its pos), so
        # a reused slot carries zero context from its previous
        # occupant — byte parity incl. re-admission is tested in
        # tests/test_slots.py::test_window_*
        # device-time ledger (telemetry/goodput.py): the engine is
        # the authority on prefill/decode/idle, stamped at the SAME
        # request boundaries the tracing timings use — admission
        # start, admission done, fully-idle — never per round or per
        # token. None (direct engine construction, benches) costs one
        # attribute load at those boundaries.
        self.ledger = ledger
        # dispatch accounting for the dispatches/token series (the
        # number the ROADMAP's megakernel item must drive down): one
        # int bump per device dispatch (prefill or chunk round), one
        # add per emitted delta — same cost class as the per-slot
        # rounds counter tracing already pays.
        self.dispatches = 0
        self.tokens_out = 0
        self.cfg = cfg
        self.params = params
        self.max_len = max_len
        # the step program (models/stepprog.py): owns the pool cache
        # and the ENTIRELY device-resident per-slot sampling state
        # (written only at admission/retirement, read every dispatch
        # with zero host->device uploads beyond the window's [S]
        # budget ints — no host numpy buffers left, so the zero-copy
        # in-place-mutation hazard class is gone by construction).
        # None builds the default for the params (plain or
        # quantized); an explicit program (e.g. speculative) brings
        # its own slots/chunk geometry, which wins.
        if program is None:
            program = make_step_program(
                cfg, params, max_len, slots, chunk, rounds=window
            )
        self.program = program
        self.slots = program.slots
        self.chunk = program.chunk
        self.window = getattr(program, "rounds", 1)
        self._active: List[Optional[_Slot]] = [None] * self.slots
        # per-round wall times for decode-only rounds (no admission),
        # seconds; bench.py's host_overhead_bench reads these through
        # round_times_ms(). _round_host_times is the same rounds with
        # the blocking token wait excluded — the engine's per-round
        # HOST cost, observed directly instead of inferred by
        # subtracting a separately-timed device loop (which a noisy
        # shared host can skew by more than the overhead itself).
        self._round_times: "deque[float]" = deque(maxlen=1024)
        self._round_host_times: "deque[float]" = deque(maxlen=1024)
        self._queue: "queue.Queue[Optional[_Request]]" = queue.Queue()
        self._submit_lock = threading.Lock()
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="slot-engine", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------- API

    def submit(
        self,
        tokens: List[int],
        max_new: int,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 0.0,
        eos_id: int = -1,
        pad_id: int = 0,
        seed: int = 0,
        min_new: int = 0,
        presence_penalty: float = 0.0,
        frequency_penalty: float = 0.0,
        logit_bias=None,
        on_tokens: Optional[callable] = None,
        cancel: Optional[threading.Event] = None,
        timings: Optional[dict] = None,
    ) -> Future:
        """Queue one sequence; resolves to its generated ids.

        ``logit_bias``: a {token_id: bias} dict (generate's contract,
        validated here so a bad request fails the submit, not the
        pool). ``on_tokens`` (worker-thread callback) streams each
        emitted delta; ``cancel`` (a threading.Event the caller sets,
        e.g. on client disconnect) frees the slot at the next chunk
        boundary — the future then resolves with whatever was
        emitted. ``timings`` (tracing) is stamped at request
        boundaries only — see _Request.timings."""
        if max_new < 1:
            raise ValueError("max_new must be >= 1")
        if not 0 <= min_new <= max_new:
            raise ValueError("min_new must be in [0, max_new]")
        if not tokens or len(tokens) >= self.max_len:
            raise ValueError(
                f"prompt must be 1..{self.max_len - 1} tokens"
            )
        if len(tokens) + max_new > self.max_len:
            raise ValueError(
                f"prompt {len(tokens)} + max_new {max_new} exceeds "
                f"max_len {self.max_len}"
            )
        rows_idx, rows_val = normalize_logit_bias(
            self.cfg, 1, logit_bias or None, slots=BIAS_SLOTS_MAX
        )
        bias_idx, bias_val = rows_idx[0], rows_val[0]
        req = _Request(
            tokens=list(tokens), max_new=int(max_new),
            temperature=float(temperature), top_k=int(top_k),
            top_p=float(top_p), eos_id=int(eos_id), pad_id=int(pad_id),
            seed=int(seed), min_new=int(min_new),
            presence=float(presence_penalty),
            frequency=float(frequency_penalty),
            bias_idx=bias_idx, bias_val=bias_val,
            on_tokens=on_tokens, cancel=cancel, timings=timings,
        )
        if timings is not None:
            timings["enqueued"] = time.monotonic()
        # atomic with stop()'s drain: either this put lands before the
        # drain (and gets cancelled there) or the stopped check raises
        with self._submit_lock:
            if self._stopped.is_set():
                raise RuntimeError("engine is stopped")
            self._queue.put(req)
        return req.future

    def stop(self) -> None:
        with self._submit_lock:
            self._stopped.set()
        self._queue.put(None)  # wake the worker
        self._thread.join(timeout=30)
        for slot in self._active:
            if slot is not None and not slot.req.future.done():
                slot.req.future.cancel()
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not None and not req.future.done():
                req.future.cancel()

    @property
    def stats(self) -> dict:
        return {
            "slots": self.slots,
            "chunk": self.chunk,
            # decode rounds fused per host dispatch (1 = the classic
            # one-dispatch-per-chunk loop)
            "window": self.window,
            "active": sum(s is not None for s in self._active),
            "queued": self._queue.qsize(),
            # the dispatches/token pair (goodput ledger + megakernel
            # yardstick): cumulative device dispatches vs tokens out
            "dispatches": self.dispatches,
            "tokens_out": self.tokens_out,
        }

    def round_times_ms(self) -> List[float]:
        """Wall time of recent decode-only rounds (ms): dispatch +
        token fetch + host bookkeeping, admission rounds excluded.
        With lookahead this reflects the overlap actually achieved."""
        return [t * 1e3 for t in list(self._round_times)]

    def round_host_ms(self) -> List[float]:
        """Host-only time of the same rounds (ms): round wall time
        minus the time spent inside the jax calls (chunk dispatches
        and the token fetch — where any device wait lands, whether
        the backend blocks in ``device_get`` or, like CPU's bounded
        in-flight queue, in the next dispatch). What remains —
        queue/cancel checks, token copy-out, append bookkeeping,
        streaming callbacks — is the host work each round pays."""
        return [t * 1e3 for t in list(self._round_host_times)]

    # ----------------------------------------------------------- worker

    def _prefill(self, req: _Request):
        """The engine's prefill POLICY, shared by every step program:
        prefix-cache rewind+extend, cp-ring, chunked, or plain — and
        the cache-seeding side effect. Returns (logits, row_cache)."""
        cfg = self.cfg
        logits = row_cache = None
        pc = self.prefix_cache
        # prompts shorter than MIN_REUSE skip the prefix machinery
        # entirely: they can never be reused (plan_reuse requires a
        # MIN_REUSE match) so storing them only pins dead LRU entries
        # — this also keeps warmup's dummy request out of the cache
        # and its stats
        use_pc = pc is not None and len(req.tokens) >= PREFIX_MIN_REUSE
        if use_pc:
            from .serve_prefix import reuse_admission

            pc.readmit_seconds = 0.0
            hit = reuse_admission(
                pc, req.tokens, cfg, self.params,
                chunk_len=self.prefill_chunk,
            )
            if hit is not None:
                logits, row_cache = hit
            if pc.readmit_seconds > 0.0:
                # time spent readmitting a spilled base from host RAM
                # (device_put roundtrip) — surfaces as the trace's
                # ``kv`` stage and the ledger's ``kv_readmit``, both
                # carved out of the prefill window
                if req.timings is not None:
                    req.timings["kv"] = pc.readmit_seconds
                if self.ledger is not None:
                    self.ledger.carve("kv_readmit", pc.readmit_seconds)
        if row_cache is None:
            if (
                self.prefill_floor_s > 0.0
                and len(req.tokens) >= PREFIX_MIN_REUSE
            ):
                # the synthetic floor: pay it on the worker thread —
                # exactly where real prefill compute would run — then
                # carve the seconds out of the ledger's prefill stage
                # so productive_fraction keeps measuring real device
                # work. The trace's prefill span (admitted ->
                # prefill_done) still carries the hit, so
                # dominant-stage attribution names it. Warmup's
                # short dummy prompt stays under the reuse floor and
                # skips this.
                time.sleep(self.prefill_floor_s)  # cpcheck: disable=CP-HOTREACH the synthetic floor IS the work; see comment above
                if self.ledger is not None:
                    self.ledger.carve("idle", self.prefill_floor_s)
            if (
                self.cp_mesh is not None
                and len(req.tokens) >= self.cp_min_len
            ):
                import numpy as _np

                from ..parallel.context import cp_prefill_with_remainder

                logits, row_cache = cp_prefill_with_remainder(
                    self.params,
                    _np.asarray([req.tokens], _np.int32),
                    cfg, self.cp_mesh, self.max_len,
                    prefill_chunk=self.prefill_chunk,
                )
            elif (
                self.prefill_chunk > 0
                and len(req.tokens) > self.prefill_chunk
            ):
                from ..models.decode import chunked_prefill

                logits, row_cache = chunked_prefill(
                    self.params, jnp.asarray([req.tokens], jnp.int32),
                    cfg, self.max_len, chunk_len=self.prefill_chunk,
                )
            else:
                # host->device transfer only on the path that uses it
                prompt = jnp.asarray([req.tokens], jnp.int32)
                logits, row_cache = _jitted_prefill(
                    cfg, self.max_len
                )(self.params, prompt)
        if use_pc:
            # store the completed prompt's cache for future turns
            # (standalone buffer — see the __init__ soundness note)
            pc.store(tuple(req.tokens), row_cache)
        return logits, row_cache

    def _admit(self, slot_id: int, req: _Request) -> None:
        """Prefill the prompt (engine policy) and hand the result to
        the step program, which samples token 0 with generate's exact
        key schedule and writes the whole admission row into its
        device-resident state in one dispatch."""
        if req.timings is not None:
            req.timings["admitted"] = time.monotonic()
        if self.ledger is not None:
            self.ledger.enter("prefill")
        logits, row_cache = self._prefill(req)
        first_host = self.program.admit(slot_id, req, logits, row_cache)
        state = _Slot(req=req, emitted=[first_host])
        if first_host == req.eos_id or req.max_new <= 1:
            state.finished = True
        self._active[slot_id] = state
        # one admission = one prefill's worth of dispatches (the
        # prefill program + first-sample/insert/admit ride together);
        # counted as ONE toward dispatches/token so the series tracks
        # the steady-state decode shape the megakernel work targets
        self.dispatches += 1
        self.tokens_out += 1
        if req.timings is not None:
            # prefill stage ends here: prompt prefilled, token 0
            # sampled, row inserted — everything after is decode
            req.timings["prefill_done"] = time.monotonic()
        if self.ledger is not None:
            self.ledger.enter("decode")
        self._notify(req, [first_host])

    def _harvest(self, slot_id: int) -> None:
        state = self._active[slot_id]
        req = state.req
        out = state.emitted[: req.max_new]
        if req.eos_id >= 0 and req.eos_id in out:
            # keep the eos, pad-trim what follows (generate's contract
            # after its own trim step)
            out = out[: out.index(req.eos_id) + 1]
        if req.timings is not None:
            req.timings["done"] = time.monotonic()
            req.timings["rounds"] = state.rounds
        self._active[slot_id] = None
        self.program.retire(slot_id)
        if not req.future.done():
            req.future.set_result(out)

    @staticmethod
    def _notify(req: _Request, delta: List[int]) -> None:
        """Deliver a streamed delta; a raising callback (e.g. the
        consumer's event loop already closed in a shutdown race) must
        never escape into _run — it would kill the worker thread and
        strand every in-flight future while /health stays 200."""
        if req.on_tokens is None:
            return
        try:
            req.on_tokens(list(delta))
        except Exception:  # noqa: BLE001
            log.exception("on_tokens callback failed; dropping delta")

    def _sweep_cancelled(self) -> None:
        """Free slots whose requests were cancelled (client gone):
        the slot returns to the pool at this window boundary — within
        ONE window of the disconnect, by the host-re-entry rule — and
        the future resolves with the partial emission (nobody is
        usually waiting — the disconnect is why we're here). The
        ``done`` stamp lands here, at the abandon instant, so decode
        is accounted up to it and no further (the tracing
        contract)."""
        for i, s in enumerate(self._active):
            if (
                s is not None
                and s.req.cancel is not None
                and s.req.cancel.is_set()
            ):
                if s.req.timings is not None:
                    s.req.timings["done"] = time.monotonic()
                    s.req.timings["rounds"] = s.rounds
                self._active[i] = None
                self.program.retire(i)
                if not s.req.future.done():
                    s.req.future.set_result(list(s.emitted))
                log.info(
                    "slot %d freed mid-generation (%d/%d tokens): "
                    "request cancelled", i, len(s.emitted), s.req.max_new,
                )

    def _fail_and_rebuild(self, exc: Exception) -> None:
        """Fail every in-flight request loudly, once, and rebuild the
        device buffers: the failed dispatch DONATED the pool and
        state, so every later admission would die on a deleted array
        while /health stays 200."""
        log.exception("slot dispatch failed")
        for i, s in enumerate(self._active):
            if s is not None and not s.req.future.done():
                s.req.future.set_exception(exc)
            self._active[i] = None
        self.program.reset()

    def _cancel_pending(self) -> bool:
        return any(
            s is not None
            and s.req.cancel is not None
            and s.req.cancel.is_set()
            for s in self._active
        )

    def _budgets(self) -> np.ndarray:
        """Per-slot remaining max_new allowance, the fused window's
        early-exit gate (models/slots.py: it never masks emission, so
        a stale-by-one-window value stays correct — budgets only
        shrink, and excess tokens are append-discarded exactly like
        the sequential engine's)."""
        budgets = np.zeros((self.slots,), np.int32)
        for i, s in enumerate(self._active):
            if s is not None:
                budgets[i] = max(s.req.max_new - len(s.emitted), 0)
        return budgets

    # cpcheck: hotpath — the continuous-batching decode loop; a steady
    # window must ship zero host syncs beyond the program's one fetch
    def _run(self) -> None:
        # one-window lookahead: the step-program handle of a window
        # already dispatched for the NEXT cycle (None = serial)
        pending = None
        program = self.program
        while not self._stopped.is_set():
            t0 = time.perf_counter()
            jax_s = 0.0  # time inside jax calls this cycle
            admitted = False
            if pending is None:
                self._sweep_cancelled()
                free = [
                    i for i, s in enumerate(self._active) if s is None
                ]
                any_active = any(
                    s is not None for s in self._active
                )
                if not any_active and self.ledger is not None:
                    # fully idle: the ledger flips to ``idle`` only
                    # out of prefill/decode (engine_idle), so this
                    # can't cut the server's boot/warmup stages short
                    self.ledger.engine_idle()
                # block for work only when fully idle; otherwise drain
                # whatever is queued into free slots and keep decoding
                try:
                    block = not any_active
                    while free:
                        req = self._queue.get(block=block, timeout=None)
                        if req is None:  # stop sentinel
                            return
                        block = False
                        t0 = time.perf_counter()  # exclude idle wait
                        admitted = True
                        if (
                            req.cancel is not None
                            and req.cancel.is_set()
                        ):
                            req.future.cancel()  # left before admission
                            continue
                        try:
                            self._admit(free.pop(0), req)
                        except Exception as exc:  # noqa: BLE001
                            if not req.future.done():
                                req.future.set_exception(exc)
                except queue.Empty:
                    pass
                # harvest admissions that finished at token 0
                for i, s in enumerate(self._active):
                    if s is not None and s.finished:
                        self._harvest(i)
                if not any(s is not None for s in self._active):
                    continue
                # fuse K rounds only when no host decision can be
                # pending: an admission just landed (more queued
                # work likely) or a non-empty queue (a waiting
                # request must grab the next freed slot at chunk
                # granularity) keeps the single-chunk program — the
                # host re-enters exactly when it has something to do
                fused = (
                    not admitted
                    and self._queue.empty()
                    and not self._cancel_pending()
                )
                tj = time.perf_counter()
                try:
                    handle = program.dispatch(self._budgets(), fused)
                except Exception as exc:  # noqa: BLE001
                    self._fail_and_rebuild(exc)
                    continue
                jax_s += time.perf_counter() - tj
                self.dispatches += program.dispatch_cost
            else:
                handle, pending = pending, None
            # one-WINDOW lookahead (the PR 1 one-round lookahead,
            # window-sized): when no admission, cancel, or stop
            # decision is pending, dispatch window N+1 BEFORE
            # fetching window N's tokens — device dataflow orders the
            # donated pool/state, so the token fetch, host
            # bookkeeping, and streaming callbacks below overlap
            # window N+1's device compute instead of serializing
            # with it. Whenever a decision IS needed the serial path
            # runs and the decision lands at the very next window
            # boundary. Budgets are stale by one window here — an
            # upper bound, see _budgets. Programs whose next dispatch
            # depends on this window's tokens (speculative
            # acceptance) opt out via supports_lookahead.
            if (
                program.supports_lookahead
                and any(s is not None for s in self._active)
                and self._queue.empty()
                and not self._cancel_pending()
            ):
                tj = time.perf_counter()
                try:
                    pending = program.dispatch(self._budgets(), True)
                except Exception as exc:  # noqa: BLE001
                    self._fail_and_rebuild(exc)
                    pending = None
                    continue
                jax_s += time.perf_counter() - tj
                self.dispatches += program.dispatch_cost
            tj = time.perf_counter()
            try:
                # the ONE deliberate sync per window lives inside
                # program.tokens; everything after it overlaps the
                # lookahead window's device compute
                toks_host, valid, rounds_run = program.tokens(handle)
            except Exception as exc:  # noqa: BLE001 — fail loud, once
                self._fail_and_rebuild(exc)
                pending = None
                continue
            jax_s += time.perf_counter() - tj
            for i, state in enumerate(self._active):
                if state is None:
                    continue
                # per-window tracing cost is ONE int bump per live
                # slot; the stamps themselves land only at admission/
                # harvest boundaries (batched per request, never per
                # token)
                state.rounds += rounds_run
                req = state.req
                before = len(state.emitted)
                ended = append_chunk(
                    state.emitted, toks_host[i][: valid[i]],
                    req.max_new, req.eos_id,
                )
                if len(state.emitted) > before:
                    self.tokens_out += len(state.emitted) - before
                    self._notify(req, state.emitted[before:])
                if ended:
                    self._harvest(i)
            if not admitted:
                wall = time.perf_counter() - t0
                self._round_times.append(wall)
                self._round_host_times.append(max(wall - jax_s, 0.0))
