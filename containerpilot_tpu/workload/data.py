"""Token-shard data loading for the supervised trainer.

TPU-first IO design:

- **Shards are memory-mapped**: each shard is a flat ``.npy`` of token
  ids; ``numpy.memmap`` reads lean on the OS page cache, so the hot
  path is a zero-copy slice — no Python-side decode loop, no
  per-example framing. (The reference supervisor has no data plane at
  all — SURVEY.md §2; this subsystem serves the workload half.)
- **Deterministic, resumable order**: the window served at step N is a
  pure function of (seed, N). A trainer that crashes at step 1000 and
  is restarted by the supervisor resumes from its checkpoint and
  replays the exact stream the dead process would have seen — the same
  property the synthetic path gets from ``fold_in(seed, step)``.
- **Background prefetch**: a thread stages the next batches and
  ``jax.device_put``s them ahead of the step, overlapping host IO with
  device compute (double buffering; the usual input-pipeline shape for
  a single host).

Shard layout: ``<dir>/shard_*.npy``, each a 1-D int array of token
ids. ``write_token_shards`` produces it; any tokenizer pipeline that
emits flat id streams can too.
"""
from __future__ import annotations

import glob
import os
import queue
import threading
from typing import Iterator, List, Optional, Sequence

import numpy as np

_SHARD_GLOB = "shard_*.npy"


def write_token_shards(
    tokens: Sequence[int] | np.ndarray,
    directory: str,
    shard_size: int = 1 << 20,
    dtype=np.int32,
) -> List[str]:
    """Split a flat token stream into memmap-able .npy shards."""
    os.makedirs(directory, exist_ok=True)
    arr = np.asarray(tokens, dtype=dtype)
    paths = []
    for i, start in enumerate(range(0, len(arr), shard_size)):
        path = os.path.join(directory, f"shard_{i:05d}.npy")
        np.save(path, arr[start : start + shard_size])
        paths.append(path)
    return paths


class TokenShardDataset:
    """Deterministic [batch, seq_len + 1] windows over memmapped
    shards (the +1 is the next-token target column)."""

    def __init__(
        self,
        directory: str,
        seq_len: int,
        batch_size: int,
        seed: int = 0,
        vocab_size: int = 0,
        holdout_windows: int = 0,
    ) -> None:
        paths = sorted(glob.glob(os.path.join(directory, _SHARD_GLOB)))
        if not paths:
            raise FileNotFoundError(
                f"no {_SHARD_GLOB} shards under {directory!r}"
            )
        self.seq_len = seq_len
        self.batch_size = batch_size
        self.seed = seed
        # >0: every served batch is range-checked (JAX clamps
        # out-of-range gathers, so a vocab mismatch would otherwise
        # train silently on garbage)
        self.vocab_size = vocab_size
        # mmap_mode keeps shards on disk; slices fault in via page cache
        self._shards = [np.load(p, mmap_mode="r") for p in paths]
        window = seq_len + 1
        # windows per shard as pure arithmetic — the index is
        # O(#shards) memory (a prefix sum), never a per-window list
        counts = np.array(
            [len(s) // window for s in self._shards], dtype=np.int64
        )
        self._window_starts = np.concatenate(
            [[0], np.cumsum(counts)]
        )  # prefix sum; window i lives in shard searchsorted(i)
        total = int(self._window_starts[-1])
        if total == 0:
            raise ValueError(
                f"shards under {directory!r} are shorter than "
                f"seq_len+1 = {window} tokens"
            )
        # the LAST holdout_windows windows are a held-out eval split:
        # the training permutation never touches them and
        # ``eval_batch`` serves them in fixed order
        if holdout_windows < 0 or holdout_windows >= total:
            raise ValueError(
                f"holdout_windows {holdout_windows} must be in "
                f"[0, {total})"
            )
        self.holdout_windows = holdout_windows
        self._total_windows = total
        self.n_windows = total - holdout_windows

    def _window(self, index: int) -> np.ndarray:
        index = index % self._total_windows
        si = int(
            np.searchsorted(self._window_starts, index, side="right") - 1
        )
        off = (index - int(self._window_starts[si])) * (self.seq_len + 1)
        return np.asarray(
            self._shards[si][off : off + self.seq_len + 1], dtype=np.int32
        )

    def batch_at(self, step: int) -> np.ndarray:
        """The [batch, seq_len+1] batch for a given global step — a
        pure function of (seed, step), which is what makes crash-resume
        replay exact. Windows are visited in a per-epoch pseudo-random
        order via a coprime stride (an affine permutation of the window
        index space), so consecutive steps don't read one shard
        sequentially forever."""
        rows = []
        stride = self._epoch_stride()
        for j in range(self.batch_size):
            flat = step * self.batch_size + j
            epoch, pos = divmod(flat, self.n_windows)
            # affine permutation: (a*pos + b) mod n, a coprime with n
            index = (stride * pos + epoch * 7919 + self.seed) % self.n_windows
            rows.append(self._window(index))
        return self._check_vocab(np.stack(rows))

    def _check_vocab(self, batch: np.ndarray) -> np.ndarray:
        if self.vocab_size:
            top = int(batch.max())
            if top >= self.vocab_size or int(batch.min()) < 0:
                raise ValueError(
                    f"shard token id {top} out of range for vocab_size "
                    f"{self.vocab_size} — wrong shards or wrong --vocab "
                    "(JAX would silently clamp the embedding gather)"
                )
        return batch

    def _epoch_stride(self) -> int:
        # largest prime-ish stride below n that is coprime with n
        n = self.n_windows
        for cand in (7919, 104729, 1299709, 15485863):
            if n > 1 and np.gcd(cand % n or 1, n) == 1:
                return cand % n or 1
        return 1

    def batches(self, start_step: int = 0) -> Iterator[np.ndarray]:
        step = start_step
        while True:
            yield self.batch_at(step)
            step += 1

    @property
    def n_eval_batches(self) -> int:
        return (
            self.holdout_windows + self.batch_size - 1
        ) // self.batch_size

    def eval_batch(self, index: int) -> np.ndarray:
        """Held-out batch ``index`` in fixed order (the tail pads by
        wrapping within the holdout split, keeping shapes static)."""
        if not self.holdout_windows:
            raise ValueError("dataset has no holdout split")
        rows = []
        for j in range(self.batch_size):
            pos = (index * self.batch_size + j) % self.holdout_windows
            rows.append(self._window(self.n_windows + pos))
        return self._check_vocab(np.stack(rows))


class DevicePrefetcher:
    """Stage upcoming batches onto the device from a background thread
    (double buffering: host IO + H2D transfer overlap the train step)."""

    def __init__(
        self,
        dataset: TokenShardDataset,
        start_step: int = 0,
        depth: int = 2,
        sharding=None,
    ) -> None:
        import jax

        self._queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None

        def worker() -> None:
            step = start_step
            try:
                while not self._stop.is_set():
                    batch = dataset.batch_at(step)
                    staged = (
                        jax.device_put(batch, sharding)
                        if sharding is not None
                        else jax.device_put(batch)
                    )
                    while not self._stop.is_set():
                        try:
                            self._queue.put((step, staged), timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    step += 1
            except BaseException as exc:  # surface it — never die silent
                self._error = exc
                while not self._stop.is_set():
                    try:
                        self._queue.put(None, timeout=0.1)  # wake next()
                        break
                    except queue.Full:
                        continue

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def next(self):
        """(step, device_batch) in order. Re-raises any exception that
        killed the background worker — a dead loader must fail the
        training step, not hang it."""
        item = self._queue.get()
        if item is None:
            raise RuntimeError("data prefetch worker died") from self._error
        return item

    def stop(self) -> None:
        self._stop.set()
        # drain so the worker's blocked put wakes and sees the flag
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)
