"""Byte-level tokenizer: text in/out for the serving API with zero
external dependencies.

The framework's API is token-level by design (tokenization is the
caller's concern — workload/serve.py); this adapter gives any model
with ``vocab_size >= 259`` a text surface: UTF-8 bytes map to ids
3..258 with pad/bos/eos at 0/1/2. Byte-level means no vocabulary
file, no external assets, and perfect reversibility — the ByT5/byte-LM
recipe. Serve exposes it as ``POST /v1/completions`` behind ``--text``.
"""
from __future__ import annotations

from typing import List


class ByteTokenizer:
    PAD = 0
    BOS = 1
    EOS = 2
    OFFSET = 3
    N_IDS = 259  # 3 specials + 256 byte values

    def __init__(self, vocab_size: int) -> None:
        if vocab_size < self.N_IDS:
            raise ValueError(
                f"byte tokenizer needs vocab_size >= {self.N_IDS}, "
                f"got {vocab_size}"
            )
        self.vocab_size = vocab_size

    def encode(self, text: str, bos: bool = True) -> List[int]:
        ids = [b + self.OFFSET for b in text.encode("utf-8")]
        return [self.BOS] + ids if bos else ids

    def to_bytes(self, ids: List[int]) -> bytes:
        """The raw bytes behind a run of ids: specials and
        out-of-byte-range ids (a model may emit any id < vocab_size)
        are dropped. The ONE id filter — decode() and the streaming
        surface both read through it, so their outputs can't drift."""
        return bytes(
            i - self.OFFSET
            for i in ids
            if self.OFFSET <= i < self.OFFSET + 256
        )

    def decode(self, ids: List[int]) -> str:
        """Ids back to text; invalid UTF-8 sequences become
        replacement characters."""
        return self.to_bytes(ids).decode("utf-8", errors="replace")


def stream_decoder(tokenizer: ByteTokenizer):
    """(delta_event, tail_events) for SSE text streaming with UTF-8
    partial-byte holdback: the byte tokenizer can split a multibyte
    character across chunk boundaries, so an incremental decoder
    buffers dangling bytes between events and the tail flush emits
    whatever remains (replacement chars — exactly what decode() does
    to the same ids). Incremental UTF-8 decoding is split-invariant,
    so concatenated event text equals decode() of the concatenated
    ids for EVERY possible chunking."""
    import codecs

    dec = codecs.getincrementaldecoder("utf-8")("replace")

    def delta_event(delta: List[int]) -> dict:
        return {
            "tokens": delta,
            "text": dec.decode(tokenizer.to_bytes(delta)),
        }

    def tail_events() -> List[dict]:
        flush = dec.decode(b"", True)
        return [{"tokens": [], "text": flush}] if flush else []

    return delta_event, tail_events
