"""Byte-level tokenizer: text in/out for the serving API with zero
external dependencies.

The framework's API is token-level by design (tokenization is the
caller's concern — workload/serve.py); this adapter gives any model
with ``vocab_size >= 259`` a text surface: UTF-8 bytes map to ids
3..258 with pad/bos/eos at 0/1/2. Byte-level means no vocabulary
file, no external assets, and perfect reversibility — the ByT5/byte-LM
recipe. Serve exposes it as ``POST /v1/completions`` behind ``--text``.
"""
from __future__ import annotations

from typing import List


class ByteTokenizer:
    PAD = 0
    BOS = 1
    EOS = 2
    OFFSET = 3
    N_IDS = 259  # 3 specials + 256 byte values

    def __init__(self, vocab_size: int) -> None:
        if vocab_size < self.N_IDS:
            raise ValueError(
                f"byte tokenizer needs vocab_size >= {self.N_IDS}, "
                f"got {vocab_size}"
            )
        self.vocab_size = vocab_size

    def encode(self, text: str, bos: bool = True) -> List[int]:
        ids = [b + self.OFFSET for b in text.encode("utf-8")]
        return [self.BOS] + ids if bos else ids

    def decode(self, ids: List[int]) -> str:
        """Ids back to text; specials and out-of-byte-range ids (a
        model may emit any id < vocab_size) are dropped, invalid UTF-8
        sequences become replacement characters."""
        raw = bytes(
            i - self.OFFSET
            for i in ids
            if self.OFFSET <= i < self.OFFSET + 256
        )
        return raw.decode("utf-8", errors="replace")
