"""Config pipeline: JSON5 loading, template rendering, validation
helpers (reference: config/ package and subpackages)."""
from .timing import DurationError, get_timeout, parse_duration
from .services import get_ip, validate_name, InterfaceIP

__all__ = [
    "parse_duration",
    "get_timeout",
    "DurationError",
    "get_ip",
    "validate_name",
    "InterfaceIP",
]
