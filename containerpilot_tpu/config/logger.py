"""Logging configuration: level, format, output, and SIGUSR1 reopen.

Capability parity with the reference's logging setup
(reference: config/logger/logging.go): level names, three formats
(default/text/json), three outputs (stdout/stderr/file), and log-file
reopen on SIGUSR1 for logrotate integration
(reference: logging.go:116-129).
"""
from __future__ import annotations

import json
import logging
import sys
import threading
from typing import Any, Dict, Optional

_LEVELS = {
    "DEBUG": logging.DEBUG,
    "INFO": logging.INFO,
    "WARN": logging.WARNING,
    "WARNING": logging.WARNING,
    "ERROR": logging.ERROR,
    "FATAL": logging.CRITICAL,
    "PANIC": logging.CRITICAL,
}


class LogConfigError(ValueError):
    pass


def _trace_fields() -> Dict[str, Any]:
    """trace_id/stream_id from the tracing contextvars, when a
    request is active on the logging task's context — the glue that
    lets replica logs and gateway logs grep together by trace id.
    Lazy import (cached on first success) keeps config.logger free of
    a package-level dependency on telemetry."""
    global _tracing
    if _tracing is None:
        try:
            from ..telemetry import tracing as _tracing_mod
        except ImportError:  # partial install; logging must not die
            return {}
        _tracing = _tracing_mod
    fields: Dict[str, Any] = {}
    trace_id = _tracing.current_trace_id()
    if trace_id:
        fields["trace_id"] = trace_id
    stream_id = _tracing.current_stream_id()
    if stream_id:
        fields["stream_id"] = stream_id
    return fields


_tracing = None


class _DefaultFormatter(logging.Formatter):
    """The reference's custom default formatter prints time, level, and
    any job/pid/check fields before the message
    (reference: logging.go:92-114)."""

    def format(self, record: logging.LogRecord) -> str:
        ts = self.formatTime(record, "%Y-%m-%dT%H:%M:%S")
        fields = ""
        for key in ("job", "check", "watch", "pid"):
            val = record.__dict__.get(key)
            if val is not None:
                fields += f" {key}={val}"
        return f"{ts} [{record.levelname}]{fields} {record.getMessage()}"


class _JSONFormatter(logging.Formatter):
    """The opt-in structured formatter (``"format": "json"``). Every
    record emitted while a traced request is active additionally
    carries ``trace_id`` (and ``stream_id`` for cp-mux streams) from
    the tracing contextvars, so one ``grep <trace_id>`` correlates a
    request's replica and gateway log lines with its /v1/traces
    timeline."""

    def format(self, record: logging.LogRecord) -> str:
        entry: Dict[str, Any] = {
            "time": self.formatTime(record, "%Y-%m-%dT%H:%M:%S%z"),
            "level": record.levelname.lower(),
            "msg": record.getMessage(),
        }
        for key in ("job", "check", "watch", "pid"):
            val = record.__dict__.get(key)
            if val is not None:
                entry[key] = val
        entry.update(_trace_fields())
        return json.dumps(entry)


class _ReopenableFileHandler(logging.FileHandler):
    """A file handler whose stream can be reopened on SIGUSR1
    (reference: client9/reopen usage, logging.go:116-129)."""

    def __init__(self, path: str) -> None:
        super().__init__(path, mode="a", encoding="utf-8", delay=False)
        self._reopen_lock = threading.Lock()

    def reopen(self) -> None:
        with self._reopen_lock:
            self.acquire()
            try:
                self.close()
                self.stream = self._open()
            finally:
                self.release()


_active_file_handler: Optional[_ReopenableFileHandler] = None


def reopen_log_file() -> None:
    """SIGUSR1 handler hook: reopen the log file for logrotate."""
    if _active_file_handler is not None:
        _active_file_handler.reopen()


class LogConfig:
    """Parsed logging section (reference: config/logger/logging.go:17-37)."""

    def __init__(self, raw: Optional[Dict[str, Any]] = None) -> None:
        raw = raw or {}
        unknown = set(raw) - {"level", "format", "output"}
        if unknown:
            raise LogConfigError(f"logging: unknown keys {sorted(unknown)}")
        self.level = (raw.get("level") or "INFO").upper()
        self.format = raw.get("format") or "default"
        self.output = raw.get("output") or "stdout"
        if self.level not in _LEVELS:
            raise LogConfigError(f"unknown log level {self.level!r}")
        if self.format not in ("default", "text", "json"):
            raise LogConfigError(f"unknown log format {self.format!r}")

    def init(self) -> None:
        """Install onto the root 'containerpilot' logger
        (reference: logging.go:39-90)."""
        global _active_file_handler
        logger = logging.getLogger("containerpilot")
        logger.setLevel(_LEVELS[self.level])
        for handler in list(logger.handlers):
            logger.removeHandler(handler)
        if self.output == "stdout":
            handler: logging.Handler = logging.StreamHandler(sys.stdout)
        elif self.output == "stderr":
            handler = logging.StreamHandler(sys.stderr)
        elif self.output:
            _active_file_handler = _ReopenableFileHandler(self.output)
            handler = _active_file_handler
        else:
            raise LogConfigError("logging.output must not be empty")
        if self.format == "json":
            handler.setFormatter(_JSONFormatter())
        elif self.format == "text":
            handler.setFormatter(
                logging.Formatter("time=%(asctime)s level=%(levelname)s msg=%(message)s")
            )
        else:
            handler.setFormatter(_DefaultFormatter())
        logger.addHandler(handler)
        logger.propagate = False
