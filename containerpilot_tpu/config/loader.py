"""Top-level config loading: file -> template -> JSON5 -> validated App
config.

Capability parity with the reference's loader
(reference: config/config.go): the path comes from the ``-config`` flag
or the ``CONTAINERPILOT`` environment variable
(reference: core/flags.go:101-103); the raw text is template-rendered
over the environment, JSON5-parsed with line/column error highlighting
(reference: config.go:198-232), unknown top-level keys are rejected
(reference: config.go:261-267), sections are decoded through each
domain package's validator, the telemetry section synthesizes its
self-advertising job (reference: config.go:172-179), and stopTimeout
defaults to 5 seconds (reference: config.go:45-48).
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import json5

from ..control.config import ControlConfig
from ..discovery import Backend, new_backend
from ..jobs import JobConfig, new_job_configs
from ..watches import WatchConfig, new_watch_configs
from .logger import LogConfig
from .template import apply_template
from .timing import DurationError, get_timeout

DEFAULT_STOP_TIMEOUT = 5.0  # seconds (reference: config/config.go:45-48)

_TOP_LEVEL_KEYS = {
    "consul",
    "logging",
    "jobs",
    "watches",
    "telemetry",
    "control",
    "stopTimeout",
}


class ConfigError(ValueError):
    pass


class AppConfig:
    """The fully-validated configuration for one App generation
    (reference: config/config.go:35-43)."""

    def __init__(self) -> None:
        self.discovery: Optional[Backend] = None
        self.jobs: List[JobConfig] = []
        self.watches: List[WatchConfig] = []
        self.telemetry = None  # telemetry.TelemetryConfig | None
        self.control: ControlConfig = ControlConfig()
        self.logging: LogConfig = LogConfig()
        self.stop_timeout: float = DEFAULT_STOP_TIMEOUT
        self.config_path: str = ""

    def init_logging(self) -> None:
        self.logging.init()


def _highlight_parse_error(text: str, exc: Exception) -> str:
    """Friendly JSON5 parse errors with the offending line marked
    (reference: config/config.go:198-232)."""
    msg = str(exc)
    import re

    # pyjson5 reports "<string>:3 ..."; other parsers say "line 3"
    m = re.search(r"line (\d+)", msg) or re.search(r"<string>:(\d+)", msg)
    if not m:
        return msg
    lineno = int(m.group(1))
    lines = text.splitlines()
    lo = max(0, lineno - 3)
    hi = min(len(lines), lineno + 2)
    context = []
    for i in range(lo, hi):
        marker = ">>> " if i + 1 == lineno else "    "
        context.append(f"{marker}{i + 1}: {lines[i]}")
    return msg + "\n" + "\n".join(context)


def render_config_template(
    template_path: str, env: Optional[Dict[str, str]] = None
) -> str:
    """Render a config file's template only (the -template/-out
    subcommand; reference: config/config.go:67-86)."""
    with open(template_path, encoding="utf-8") as f:
        text = f.read()
    return apply_template(text, env)


def parse_config(text: str) -> Dict[str, Any]:
    rendered = apply_template(text)
    try:
        raw = json5.loads(rendered)
    except Exception as exc:
        raise ConfigError(
            f"parse error in configuration: {_highlight_parse_error(rendered, exc)}"
        ) from None
    if not isinstance(raw, dict):
        raise ConfigError("configuration must be a JSON5 object")
    unknown = set(raw) - _TOP_LEVEL_KEYS
    if unknown:
        raise ConfigError(f"unknown configuration keys: {sorted(unknown)}")
    return raw


def new_config(raw: Dict[str, Any]) -> AppConfig:
    """Assemble + validate an AppConfig from parsed JSON5
    (reference: config/config.go:128-182)."""
    cfg = AppConfig()
    cfg.logging = LogConfig(raw.get("logging"))
    try:
        stop_timeout = get_timeout(raw.get("stopTimeout"))
    except DurationError as exc:
        raise ConfigError(f"unable to parse stopTimeout: {exc}") from None
    cfg.stop_timeout = stop_timeout or DEFAULT_STOP_TIMEOUT
    cfg.discovery = new_backend(raw.get("consul"))
    cfg.control = ControlConfig(raw.get("control"))

    job_raws: List[Dict[str, Any]] = list(raw.get("jobs") or [])

    telemetry_raw = raw.get("telemetry")
    if telemetry_raw is not None:
        from ..telemetry.config import TelemetryConfig

        cfg.telemetry = TelemetryConfig(telemetry_raw)
        # the telemetry server advertises itself via a synthetic job
        # (reference: config/config.go:172-179)
        job_raws.append(cfg.telemetry.to_job_config_raw())

    cfg.jobs = new_job_configs(job_raws, cfg.discovery)
    cfg.watches = new_watch_configs(raw.get("watches"), cfg.discovery)
    return cfg


def load_config(path: Optional[str] = None) -> AppConfig:
    """Load, render, parse, and validate the config file
    (reference: config/config.go:91-125)."""
    if not path:
        path = os.environ.get("CONTAINERPILOT", "")
    if not path:
        raise ConfigError(
            "-config flag is required (or set the CONTAINERPILOT "
            "environment variable)"
        )
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError as exc:
        raise ConfigError(f"could not read config file: {exc}") from None
    cfg = new_config(parse_config(text))
    cfg.config_path = path
    return cfg
