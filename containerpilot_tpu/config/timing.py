"""Duration parsing: bare numbers are seconds, strings use Go-style units.

Capability parity with the reference's timing helpers
(reference: config/timing/duration.go): ``parse_duration`` accepts an
int/float (seconds), a numeric string (seconds), or a Go-style duration
string ("300ms", "1.5h", "1h2m3s"); ``get_timeout`` maps the empty
value to zero (meaning "no timeout").

All durations in this framework are float seconds.
"""
from __future__ import annotations

import re
from typing import Any, Optional, Union

_UNITS = {
    "ns": 1e-9,
    "us": 1e-6,
    "µs": 1e-6,  # µs
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
}

_SEGMENT = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")


class DurationError(ValueError):
    """Raised for an unparseable duration value."""


def parse_duration(value: Any) -> float:
    """Parse a config duration into float seconds."""
    if isinstance(value, bool):
        raise DurationError(f"unexpected duration of type {type(value).__name__}")
    if isinstance(value, (int, float)):
        return float(value)
    if isinstance(value, str):
        s = value.strip()
        try:
            return float(int(s))  # bare integer string = seconds
        except ValueError:
            pass
        matched = _SEGMENT.findall(s)
        if not matched or "".join(n + u for n, u in matched) != s:
            raise DurationError(f"invalid duration: {value!r}")
        return sum(float(n) * _UNITS[u] for n, u in matched)
    raise DurationError(f"unexpected duration of type {type(value).__name__}")


def get_timeout(value: Optional[Union[str, int, float]]) -> float:
    """Like parse_duration but empty/None means no timeout (0.0)
    (reference: config/timing/duration.go:13-22)."""
    if value in (None, ""):
        return 0.0
    return parse_duration(value)
