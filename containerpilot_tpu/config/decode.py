"""Weakly-typed config decoding helpers.

Capability parity with the reference's mapstructure wrapper
(reference: config/decode/decode.go:13-23 — WeaklyTypedInput): config
values may arrive as strings where numbers are expected (templating
always produces strings), so numeric fields coerce before validation.
"""
from __future__ import annotations

from typing import Any, Optional


def coerce_number(value: Any) -> Any:
    """'8080' -> 8080, '7.5' -> 7.5; non-numeric strings pass through
    unchanged for the caller's validation to reject."""
    if isinstance(value, str):
        try:
            return int(value)
        except ValueError:
            try:
                return float(value)
            except ValueError:
                return value
    return value


def coerce_int(value: Any) -> Optional[int]:
    """Coerce to an integer, accepting integral floats ('8080', 8080.0);
    returns None when the value isn't an integral number."""
    value = coerce_number(value)
    if isinstance(value, bool):
        return None
    if isinstance(value, int):
        return value
    if isinstance(value, float) and value.is_integer():
        return int(value)
    return None
