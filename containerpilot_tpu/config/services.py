"""Service naming and NIC/IP-selection DSL.

Capability parity with the reference's services config helpers
(reference: config/services/names.go, config/services/ips.go):

- ``validate_name``: service names must be DNS-safe
  (``^[a-z][a-zA-Z0-9-]+$``, reference: names.go:8-21).
- ``get_ip(specs)``: pick the advertised IP from an ordered list of
  interface specs — ``eth0``, ``eth0[1]``, ``eth0:inet6``, ``inet``,
  ``inet6``, a CIDR like ``10.0.0.0/16``, or ``static:<ip>`` — matching
  against interface IPs sorted by interface name then IP bytes for
  stable selection (reference: ips.go:31-66,159-223,297-310).

On TPU VMs the default spec list works as-is (the primary NIC is
``ens*``/``eth0``); ``inet`` is the portable fallback.
"""
from __future__ import annotations

import ipaddress
import logging
import re
import socket
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

log = logging.getLogger("containerpilot.config")

_VALID_NAME = re.compile(r"^[a-z][a-zA-Z0-9\-]+$")


def validate_name(name: str) -> None:
    if not name:
        raise ValueError("'name' must not be blank")
    if not _VALID_NAME.match(name):
        raise ValueError(
            "service names must be alphanumeric with dashes to comply "
            "with service discovery"
        )


# --- interface enumeration -------------------------------------------------

IPAddr = Union[ipaddress.IPv4Address, ipaddress.IPv6Address]


@dataclass(frozen=True)
class InterfaceIP:
    name: str
    ip: IPAddr

    @property
    def is_ipv4(self) -> bool:
        return self.ip.version == 4

    def ip_string(self) -> str:
        return str(self.ip)


def _gather_interface_ips() -> List[InterfaceIP]:
    """Enumerate (interface, IP) pairs, sorted by name then IP bytes
    (reference: ips.go:253-310)."""
    out: List[InterfaceIP] = []
    import psutil  # baked into the image; gathered lazily for testability

    for name, addrs in psutil.net_if_addrs().items():
        for addr in addrs:
            if addr.family == socket.AF_INET:
                out.append(InterfaceIP(name, ipaddress.IPv4Address(addr.address)))
            elif addr.family == socket.AF_INET6:
                host = addr.address.split("%", 1)[0]  # strip scope id
                out.append(InterfaceIP(name, ipaddress.IPv6Address(host)))
    out.sort(key=lambda iip: (iip.name, iip.ip.version, int(iip.ip)))
    return out


# --- spec parsing ----------------------------------------------------------

_IFACE_SPEC = re.compile(r"^(?P<name>\w+)(?:(?:\[(?P<index>\d+)\])|(?::(?P<ver>inet6?)))?$")

MatchFn = Callable[[int, InterfaceIP], bool]


@dataclass
class _Spec:
    spec: str
    match: Optional[MatchFn]  # None for static specs
    static_ip: Optional[str] = None


def _parse_spec(spec: str) -> _Spec:
    if spec == "inet":
        return _Spec(spec, lambda i, iip: not iip.ip.is_loopback and iip.is_ipv4)
    if spec == "inet6":
        return _Spec(spec, lambda i, iip: not iip.ip.is_loopback and not iip.is_ipv4)
    if spec.startswith("static:"):
        raw = spec[len("static:"):]
        try:
            ipaddress.ip_address(raw)
        except ValueError:
            raise ValueError(f"unable to parse static ip {raw!r} in {spec!r}")
        return _Spec(spec, None, static_ip=raw)
    m = _IFACE_SPEC.match(spec)
    if m:
        name, index, ver = m.group("name"), m.group("index"), m.group("ver")
        if index is not None:
            want = int(index)
            return _Spec(
                spec,
                lambda i, iip, n=name, w=want: iip.name == n and i == w,
            )
        want_v6 = ver == "inet6"
        return _Spec(
            spec,
            lambda i, iip, n=name, v6=want_v6: iip.name == n and iip.is_ipv4 != v6,
        )
    try:
        network = ipaddress.ip_network(spec, strict=False)
        return _Spec(spec, lambda i, iip, net=network: iip.ip in net)
    except ValueError:
        pass
    raise ValueError(f"unable to parse interface spec: {spec!r}")


def get_ip(
    spec_list: Optional[Sequence[str]] = None,
    interface_ips: Optional[List[InterfaceIP]] = None,
) -> str:
    """Resolve the advertised IP from ordered interface specs
    (reference: ips.go:31-99). ``interface_ips`` is injectable for
    deterministic tests, like the reference's pure matcher."""
    if not spec_list:
        spec_list = ["eth0:inet", "inet"]
    specs = [_parse_spec(s) for s in spec_list]
    if interface_ips is None:
        interface_ips = _gather_interface_ips()

    for spec in specs:
        if spec.static_ip is not None:
            return spec.static_ip
        index = 0
        current = ""
        for iip in interface_ips:
            # index counts addresses within one interface; name changes
            # reset it (the list is sorted by interface name)
            if current != iip.name:
                index = 0
                current = iip.name
            else:
                index += 1
            assert spec.match is not None
            if spec.match(index, iip):
                return iip.ip_string()
    raise ValueError(
        "none of the interface specifications were able to match\n"
        f"specifications: {[s.spec for s in specs]}\n"
        f"interface IPs: {[(i.name, i.ip_string()) for i in interface_ips]}"
    )
