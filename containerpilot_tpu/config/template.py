"""Config-file template rendering over the process environment.

Capability parity with the reference's template preprocessing
(reference: config/template/template.go): configs are rendered before
JSON5 parsing, with environment variables addressable as ``{{ .VAR }}``
(missing variables render empty — Go's ``missingkey=zero``) and the
same helper functions with the same argument order:

- ``default <fallback> <value>``  (template.go:126-136)
- ``env <name>``                  (template.go:62-64)
- ``split <sep> <s>`` / ``join <sep> <list>``    (template.go:19-32)
- ``replaceAll <from> <to> <s>``                 (template.go:36-38)
- ``regexReplaceAll <re> <to> <s>``              (template.go:41-47)
- ``loop [start] <stop>`` (ranges, descending supported; template.go:80-117)

plus pipelines (``{{ .VAR | default "x" }}`` appends the piped value as
the last argument), ``if``/``else``/``end`` blocks, and
``range``/``end`` blocks with ``.`` bound to the loop item.

This is a fresh implementation of the *template dialect the reference's
config files use*, not a Go text/template port: the grammar here is the
subset that appears in supervisor configs.
"""
from __future__ import annotations

import os
import re
from typing import Any, Callable, Dict, List, Optional, Tuple, Union


class TemplateError(ValueError):
    """Template syntax or rendering error."""


# --- helper functions (reference: template.go) -----------------------------

def _fn_default(fallback: Any, value: Any = None) -> str:
    # only a non-empty string wins over the fallback
    # (reference: template.go:126-136)
    if isinstance(value, str) and value != "":
        return value
    return _to_string(fallback)


def _fn_env(name: Any) -> str:
    return os.environ.get(str(name), "")


def _fn_split(sep: Any, s: Any) -> List[str]:
    s = str(s).strip()
    if s == "":
        return []
    return s.split(str(sep))


def _fn_join(sep: Any, items: Any) -> str:
    if not items:
        return ""
    return str(sep).join(str(i) for i in items)


def _fn_replace_all(frm: Any, to: Any, s: Any) -> str:
    return str(s).replace(str(frm), str(to))


def _fn_regex_replace_all(pattern: Any, to: Any, s: Any) -> str:
    # Go regexp uses $1 for group refs; Python uses \1 — accept both
    replacement = re.sub(r"\$(\d+)", r"\\\1", str(to))
    return re.sub(str(pattern), replacement, str(s))


def _ensure_int(v: Any) -> int:
    if isinstance(v, str):
        return int(v)
    if isinstance(v, bool):
        raise TemplateError(f"loop: not an integer: {v!r}")
    if isinstance(v, (int, float)):
        return int(v)
    raise TemplateError(f"loop: not an integer: {v!r}")


def _fn_loop(*params: Any) -> List[int]:
    if len(params) == 1:
        start, stop = 0, _ensure_int(params[0])
    elif len(params) == 2:
        start, stop = _ensure_int(params[0]), _ensure_int(params[1])
    else:
        raise TemplateError(
            f"loop: wrong number of arguments, expected 1 or 2, got {len(params)}"
        )
    step = 1 if stop >= start else -1
    return list(range(start, stop, step))


def _check_comparable(a: Any, b: Any) -> None:
    """Go's eq/ne raise on incomparable basic kinds; env values are
    always strings and number literals are int/float, so a silent
    False on `eq .COUNT 2` would take the wrong branch with no
    diagnostic. Mirrors the reference for mixed numeric kinds too:
    Go treats int vs float as incomparable (``eq 1 1.0`` errors), so
    we reject it rather than silently returning Python's True."""
    def kind(v: Any) -> str:
        if isinstance(v, bool):
            return "bool"
        if isinstance(v, int):
            return "int"
        if isinstance(v, float):
            return "float"
        if isinstance(v, str):
            return "str"
        return "other"

    ka, kb = kind(a), kind(b)
    mismatch = (
        ka != kb
        and {ka, kb} <= {"str", "int", "float"}
    )
    if mismatch:
        hint = (
            "(env values are strings; quote the literal)"
            if "str" in (ka, kb)
            else "(int and float literals are incomparable kinds in "
            "Go templates; use matching literals)"
        )
        raise TemplateError(
            f"incompatible types for comparison: {a!r} vs {b!r} {hint}"
        )


def _fn_eq(first: Any, *rest: Any) -> bool:
    """Go text/template's builtin ``eq``: true when arg1 equals ANY of
    the remaining args (reference configs use it inside if blocks)."""
    if not rest:
        raise TemplateError("eq needs at least two arguments")
    for other in rest:
        _check_comparable(first, other)
    return any(first == other for other in rest)


def _fn_ne(a: Any, b: Any) -> bool:
    """Go text/template's builtin ``ne``."""
    _check_comparable(a, b)
    return a != b


FUNCS: Dict[str, Callable[..., Any]] = {
    "default": _fn_default,
    "env": _fn_env,
    "split": _fn_split,
    "join": _fn_join,
    "replaceAll": _fn_replace_all,
    "regexReplaceAll": _fn_regex_replace_all,
    "loop": _fn_loop,
    "eq": _fn_eq,
    "ne": _fn_ne,
}


def _to_string(v: Any) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, list):
        return "[" + " ".join(_to_string(i) for i in v) + "]"
    return str(v)


def _truthy(v: Any) -> bool:
    if isinstance(v, str):
        return v != ""
    if isinstance(v, (list, dict)):
        return len(v) > 0
    return bool(v)


# --- expression mini-language ----------------------------------------------

_TOKEN = re.compile(
    r"""\s*(?:
        (?P<string>"(?:\\.|[^"\\])*")
      | (?P<number>-?\d+(?:\.\d+)?)
      | (?P<var>\.[A-Za-z_][A-Za-z0-9_]*)
      | (?P<dot>\.)
      | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
      | (?P<pipe>\|)
      | (?P<lparen>\()
      | (?P<rparen>\))
    )""",
    re.VERBOSE,
)


def _tokenize_expr(src: str) -> List[Tuple[str, str]]:
    tokens: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN.match(src, pos)
        if not m or m.end() == pos:
            rest = src[pos:].strip()
            if not rest:
                break
            raise TemplateError(f"bad token in template expression: {rest!r}")
        pos = m.end()
        for kind in ("string", "number", "var", "dot", "ident", "pipe",
                     "lparen", "rparen"):
            val = m.group(kind)
            if val is not None:
                tokens.append((kind, val))
                break
    return tokens


class _ExprParser:
    """Parses one action's expression: pipeline of commands, each a
    function call or a term."""

    def __init__(self, tokens: List[Tuple[str, str]]) -> None:
        self.tokens = tokens
        self.pos = 0

    def peek(self) -> Optional[Tuple[str, str]]:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self) -> Tuple[str, str]:
        tok = self.tokens[self.pos]
        self.pos += 1
        return tok

    def parse_pipeline(self) -> "Pipeline":
        commands = [self.parse_command()]
        while self.peek() and self.peek()[0] == "pipe":
            self.next()
            commands.append(self.parse_command())
        return Pipeline(commands)

    def parse_command(self) -> "CommandNode":
        head = self.peek()
        if head is None:
            raise TemplateError("empty template expression")
        if head[0] == "ident":
            name = self.next()[1]
            args: List[Any] = []
            while self.peek() and self.peek()[0] not in ("pipe", "rparen"):
                args.append(self.parse_term())
            return CommandNode(func=name, args=args)
        term = self.parse_term()
        return CommandNode(func=None, args=[term])

    def parse_term(self) -> Any:
        kind, val = self.next()
        if kind == "string":
            # single left-to-right pass so \\n decodes to backslash+n,
            # not to a newline
            escapes = {'"': '"', "n": "\n", "t": "\t", "\\": "\\"}
            return StringLit(
                re.sub(
                    r"\\(.)",
                    lambda m: escapes.get(m.group(1), "\\" + m.group(1)),
                    val[1:-1],
                )
            )
        if kind == "number":
            return NumberLit(float(val) if "." in val else int(val))
        if kind == "var":
            return VarRef(val[1:])
        if kind == "dot":
            return DotRef()
        if kind == "lparen":
            inner = self.parse_pipeline()
            if not self.peek() or self.next()[0] != "rparen":
                raise TemplateError("unclosed '(' in template expression")
            return inner
        if kind == "ident":
            # bare identifier as arg: nested no-arg function (e.g. env)
            return CommandNode(func=val, args=[])
        raise TemplateError(f"unexpected token {val!r}")


class StringLit:
    def __init__(self, v: str) -> None:
        self.v = v

    def eval(self, ctx: "Context") -> Any:
        return self.v


class NumberLit:
    def __init__(self, v: Union[int, float]) -> None:
        self.v = v

    def eval(self, ctx: "Context") -> Any:
        return self.v


class VarRef:
    def __init__(self, name: str) -> None:
        self.name = name

    def eval(self, ctx: "Context") -> Any:
        return ctx.lookup(self.name)


class DotRef:
    def eval(self, ctx: "Context") -> Any:
        return ctx.dot


_SENTINEL = object()


class CommandNode:
    def __init__(self, func: Optional[str], args: List[Any]) -> None:
        self.func = func
        self.args = args

    def eval(self, ctx: "Context", piped: Any = _SENTINEL) -> Any:
        args = [a.eval(ctx) for a in self.args]
        if self.func is None:
            if piped is not _SENTINEL:
                raise TemplateError("cannot pipe into a literal")
            return args[0]
        fn = FUNCS.get(self.func)
        if fn is None:
            raise TemplateError(f"unknown template function: {self.func!r}")
        if piped is not _SENTINEL:
            args.append(piped)
        try:
            return fn(*args)
        except TemplateError:
            raise
        except Exception as exc:
            raise TemplateError(f"{self.func}: {exc}") from None


class Pipeline:
    def __init__(self, commands: List[CommandNode]) -> None:
        self.commands = commands

    def eval(self, ctx: "Context") -> Any:
        value = self.commands[0].eval(ctx)
        for cmd in self.commands[1:]:
            value = cmd.eval(ctx, value)
        return value


# --- block structure -------------------------------------------------------

class Context:
    def __init__(self, env: Dict[str, str], dot: Any = None) -> None:
        self.env = env
        self.dot = dot if dot is not None else env

    def lookup(self, name: str) -> str:
        # missingkey=zero: absent vars render as the zero value ""
        return self.env.get(name, "")

    def child(self, dot: Any) -> "Context":
        return Context(self.env, dot)


_ACTION = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}", re.DOTALL)


def _parse_blocks(src: str):
    """Split source into a node tree: text, actions, if/range blocks."""
    nodes: List[Any] = []
    stack: List[Tuple[str, Any, List[Any]]] = []  # (kind, pipeline, nodes)
    current = nodes
    pos = 0
    for m in _ACTION.finditer(src):
        if m.start() > pos:
            current.append(("text", src[pos:m.start()]))
        pos = m.end()
        body = m.group(1).strip()
        if body.startswith("if "):
            pipeline = _ExprParser(_tokenize_expr(body[3:])).parse_pipeline()
            stack.append(("if", pipeline, current))
            block: List[Any] = []
            current.append(("if", pipeline, block, None))
            current = block
        elif body.startswith("range "):
            pipeline = _ExprParser(_tokenize_expr(body[6:])).parse_pipeline()
            stack.append(("range", pipeline, current))
            block = []
            current.append(("range", pipeline, block))
            current = block
        elif body == "else":
            if not stack or stack[-1][0] != "if":
                raise TemplateError("'else' outside of 'if'")
            parent = stack[-1][2]
            # replace the if-node's else-branch with a fresh block
            kind, pipeline, then_block, _ = parent[-1]
            else_block: List[Any] = []
            parent[-1] = (kind, pipeline, then_block, else_block)
            current = else_block
        elif body == "end":
            if not stack:
                raise TemplateError("'end' without open block")
            _, _, parent = stack.pop()
            current = parent
        else:
            pipeline = _ExprParser(_tokenize_expr(body)).parse_pipeline()
            current.append(("expr", pipeline))
    if stack:
        raise TemplateError("unclosed block in template")
    if pos < len(src):
        current.append(("text", src[pos:]))
    return nodes


def _render_nodes(nodes: List[Any], ctx: Context, out: List[str]) -> None:
    for node in nodes:
        kind = node[0]
        if kind == "text":
            out.append(node[1])
        elif kind == "expr":
            out.append(_to_string(node[1].eval(ctx)))
        elif kind == "if":
            _, pipeline, then_block, else_block = node
            if _truthy(pipeline.eval(ctx)):
                _render_nodes(then_block, ctx, out)
            elif else_block:
                _render_nodes(else_block, ctx, out)
        elif kind == "range":
            _, pipeline, block = node
            items = pipeline.eval(ctx)
            if isinstance(items, dict):
                items = list(items.values())
            for item in items or []:
                _render_nodes(block, ctx.child(item), out)


def apply_template(
    config_text: str, env: Optional[Dict[str, str]] = None
) -> str:
    """Render a config template against the environment
    (reference: config/template/template.go:167-180)."""
    if env is None:
        env = dict(os.environ)
    nodes = _parse_blocks(config_text)
    out: List[str] = []
    _render_nodes(nodes, Context(env), out)
    return "".join(out)
