"""Owned-task discipline for fire-and-forget asyncio tasks.

The event loop holds only a weak reference to a running task: a task
whose return value is discarded can be garbage-collected mid-flight,
and an exception it raises evaporates with it — the relay/watchdog the
task implemented just stops existing while /health stays green. That
is CP-TASKLEAK's hazard (analysis/cpcheck.py), and ``spawn`` is the
one-call fix every background task in the tree uses:

- a **live reference**: the task joins ``owner`` (an owner-object
  field's set, or the module-level ``_BACKGROUND`` pending set when no
  owner is given) and leaves it on completion;
- a **done-callback** that logs any exception that is not a
  ``CancelledError`` — a supervisor loop that dies must say so, loudly,
  the moment it dies, not when someone notices heartbeats stopped.

Callers that also keep their own handle (``self._task = spawn(...)``)
lose nothing: the set membership is belt-and-braces against the field
being dropped, and the logging callback runs either way. The runtime
backstop for tasks created OUTSIDE this helper is
``analysis/loopcheck.TaskWatchdog``.
"""
from __future__ import annotations

import asyncio
import logging
from typing import Coroutine, Optional, Set

log = logging.getLogger("containerpilot.tasks")

#: module-level pending set: the reference of last resort for spawns
#: with no owner object (e.g. a reload's straggler-killer that must
#: outlive the generation that scheduled it)
_BACKGROUND: Set["asyncio.Task"] = set()


def _log_done(task: "asyncio.Task") -> None:
    """Done-callback: surface non-CancelledError deaths immediately."""
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None:
        log.error(
            "background task %r died: %r", task.get_name(), exc,
            exc_info=exc,
        )


def spawn(
    coro: Coroutine,
    *,
    name: Optional[str] = None,
    owner: Optional[Set["asyncio.Task"]] = None,
) -> "asyncio.Task":
    """``create_task`` plus the two things a fire-and-forget task must
    have: a live reference and an exception-logging done-callback.

    ``owner`` is a set the task should live in (an owner object's
    field); default is the module-level pending set. The task removes
    itself on completion either way.
    """
    task = asyncio.get_event_loop().create_task(coro, name=name)
    holder = _BACKGROUND if owner is None else owner
    holder.add(task)
    task.add_done_callback(holder.discard)
    task.add_done_callback(_log_done)
    return task


def pending_count() -> int:
    """How many ownerless background tasks are still in flight
    (observability + tests)."""
    return len(_BACKGROUND)
