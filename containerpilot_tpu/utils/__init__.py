"""Shared utilities (asyncio HTTP plumbing, helpers)."""
