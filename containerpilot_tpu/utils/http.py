"""Minimal asyncio HTTP/1.1 server with keep-alive.

Shared plumbing for every in-process server in the tree — the
telemetry endpoint on TCP (reference: telemetry/telemetry.go), the
control plane on a unix domain socket (reference: control/control.go),
the inference servers, the fleet gateway, and the catalog emulator.

Connection contract:

- **Buffered responses are Content-Length-framed and the connection
  stays open** (HTTP/1.1 keep-alive): sequential requests on one
  connection skip the dial + teardown tax, which is what the fleet
  gateway's replica pool, the ControlClient, and the catalog
  heartbeat/poll clients rely on. A client sends ``Connection:
  close`` (or speaks HTTP/1.0 without ``keep-alive``) to get the old
  one-shot behavior. Idle connections are reaped after
  ``KEEPALIVE_IDLE_TIMEOUT`` and capped at ``KEEPALIVE_MAX_REQUESTS``
  requests; protocol-level errors (400/408) always close, since the
  connection's framing can no longer be trusted.
- **StreamingResponse keeps its close-delimited contract**: sent with
  ``Connection: close`` and no Content-Length, the closing connection
  ends the stream.
- No chunked encoding; bodies need Content-Length.
- **cp-mux/1 multiplexing is negotiated, never assumed**: a client
  that sends ``Connection: Upgrade`` + ``Upgrade: cp-mux/1`` on a
  request switches the connection to the framed, multiplexed protocol
  below (many concurrent requests — streams included — interleaved on
  one socket). A client that never sends the upgrade gets the exact
  HTTP/1.1 byte stream it always got, and a server with
  ``mux_enabled=False`` answers the upgrade request through the
  normal route table (404), leaving the connection usable as plain
  keep-alive — which is precisely the client's fallback signal.

cp-mux/1 wire format (one frame)::

    u32 payload_length | u8 type | u32 stream_id | payload

Types: HEADERS (1, JSON request/response head), DATA (2, body
bytes), END (3, closes that direction of the stream), CANCEL (4,
abort the stream, either side), PING (5) / PONG (6, liveness, stream
id echoed), WINDOW (7, u32 flow-control credit). Response DATA is
window-gated per stream (``MUX_INITIAL_WINDOW`` bytes of credit,
refilled by WINDOW frames as the consumer drains), so one slow SSE
consumer stalls only its own stream while co-resident streams keep
interleaving. Request bodies are small and bounded by ``MAX_BODY``
instead of windowed. Framing violations (unknown type, oversized
frame, HEADERS for a live stream id, malformed HEADERS JSON) close
the whole connection: its framing can no longer be trusted, exactly
like a 400 on the HTTP/1.1 path.
"""
from __future__ import annotations

import asyncio
import json
import logging
import struct
from typing import Awaitable, Callable, Dict, List, Optional, Set, Tuple
from urllib.parse import parse_qs, urlsplit

log = logging.getLogger("containerpilot.http")

MAX_BODY = 4 * 1024 * 1024

_tracing = None


def _get_tracing():
    """Lazy tracing accessor: utils.http is imported by nearly every
    package, so the telemetry dependency stays off the module import
    path and is resolved once, on the first mux stream served."""
    global _tracing
    if _tracing is None:
        from ..telemetry import tracing as _tracing_mod

        _tracing = _tracing_mod
    return _tracing

# -- cp-mux/1 framed multiplexing ------------------------------------

MUX_PROTOCOL = "cp-mux/1"
#: path the client's upgrade request targets; unroutable on purpose,
#: so a mux-less server answers it 404 (the fallback signal) without
#: ever colliding with a real route
MUX_UPGRADE_PATH = "/_mux"

FRAME_HEADERS = 1
FRAME_DATA = 2
FRAME_END = 3
FRAME_CANCEL = 4
FRAME_PING = 5
FRAME_PONG = 6
FRAME_WINDOW = 7
FRAME_TYPES = frozenset((
    FRAME_HEADERS, FRAME_DATA, FRAME_END, FRAME_CANCEL,
    FRAME_PING, FRAME_PONG, FRAME_WINDOW,
))

FRAME_HEAD = struct.Struct(">IBI")  # payload length, type, stream id
MUX_MAX_FRAME = 1 << 20
#: per-stream response-DATA credit a receiver starts with
MUX_INITIAL_WINDOW = 64 * 1024
#: largest single DATA frame a sender emits (interleaving granularity)
MUX_CHUNK = 32 * 1024
#: concurrent streams one connection may carry; the 513th is refused
#: with a per-stream 503, never a connection error
MUX_MAX_STREAMS = 512


class MuxProtocolError(Exception):
    """The peer violated cp-mux/1 framing; the connection is dead."""


def encode_frame(ftype: int, stream_id: int, payload: bytes = b"") -> bytes:
    return FRAME_HEAD.pack(len(payload), ftype, stream_id) + payload


async def read_frame(reader: asyncio.StreamReader) -> Tuple[int, int, bytes]:
    """One frame off the wire; raises MuxProtocolError on framing
    violations and IncompleteReadError on EOF."""
    length, ftype, stream_id = FRAME_HEAD.unpack(
        await reader.readexactly(FRAME_HEAD.size)
    )
    if ftype not in FRAME_TYPES:
        raise MuxProtocolError(f"unknown frame type {ftype}")
    if length > MUX_MAX_FRAME:
        raise MuxProtocolError(f"{length}-byte frame exceeds cap")
    payload = await reader.readexactly(length) if length else b""
    return ftype, stream_id, payload


async def timed_read(reader: asyncio.StreamReader, coro, timeout: float):
    """Await one read (or a multi-read coroutine) on ``reader`` under
    a deadline WITHOUT ``asyncio.wait_for``: wait_for creates a Task
    plus a timer per call (~100us on a busy host), which at one-per-
    header-line dominates a proxied request's hot path. A plain timer
    handle costs ~1us; on expiry it poisons the reader with
    ``asyncio.TimeoutError``, which the pending await raises.

    A reader poisoned by a TRUE timeout stays failed — correct here,
    because every caller abandons the connection after a read
    timeout. But the timer can also fire in the same event-loop tick
    in which the read completed (data callback and due timer both run
    before the awaiting task resumes and cancels the handle); in that
    race the read returns normally while the poison would fail the
    connection's NEXT read — so after a successful await, this call's
    own sentinel exception is cleared."""
    exc = asyncio.TimeoutError()
    handle = asyncio.get_event_loop().call_later(
        timeout, reader.set_exception, exc
    )
    try:
        result = await coro
    finally:
        handle.cancel()
        if reader.exception() is exc:
            # the timer fired after the read already completed: the
            # connection is healthy, un-poison it (on the raise path
            # this is dead state either way — the conn is abandoned)
            reader._exception = None  # noqa: SLF001
    return result


class Request:
    def __init__(
        self,
        method: str,
        path: str,
        query: Dict[str, list],
        headers: Dict[str, str],
        body: bytes,
        version: str = "HTTP/1.1",
    ) -> None:
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers
        self.body = body
        self.version = version

    def wants_keepalive(self) -> bool:
        """The client side of the connection-reuse handshake:
        HTTP/1.1 defaults to keep-alive unless the request says
        ``Connection: close``; HTTP/1.0 defaults to close unless it
        says ``Connection: keep-alive``."""
        connection = self.headers.get("connection", "").lower()
        if "close" in connection:
            return False
        if self.version.upper().startswith("HTTP/1.0"):
            return "keep-alive" in connection
        return True


class Response:
    def __init__(
        self,
        status: int = 200,
        body: bytes = b"",
        content_type: str = "text/plain; charset=utf-8",
        headers: Optional[Dict[str, str]] = None,
    ) -> None:
        self.status = status
        self.body = body
        self.content_type = content_type
        self.headers = headers or {}


class StreamingResponse:
    """A response whose body arrives incrementally from an async
    iterator of byte chunks (SSE events, chunk-boundary token
    deltas). Sent with ``Connection: close`` and no Content-Length:
    the closing connection delimits the stream, which every HTTP/1.1
    client understands. A stream therefore always ENDS its connection
    — streaming responses opt out of the server's keep-alive.

    Client disconnects are detected promptly (the reader hits EOF)
    and the iterator is ``aclose()``d, so a handler generator's
    ``finally`` can release what the request holds (e.g. free a slot
    mid-generation)."""

    def __init__(
        self,
        chunks,  # AsyncIterator[bytes]
        status: int = 200,
        content_type: str = "text/event-stream",
        headers: Optional[Dict[str, str]] = None,
        close: Optional[Callable[[], None]] = None,
    ) -> None:
        self.status = status
        self.chunks = chunks
        self.content_type = content_type
        self.headers = headers or {}
        # aclose() on a NEVER-STARTED async generator skips its body
        # entirely (an immediate disconnect aborts before the first
        # __anext__), so generator-finally cleanup alone is not
        # enough: ``close`` is invoked unconditionally when the
        # stream ends, however it ends. Make it idempotent — the
        # generator's own finally may run too.
        self.close = close


class _MuxServerStream:
    """Server-side state for one cp-mux stream: the decoded HEADERS,
    the accumulating request body, the handler task once END arrives,
    and the response-DATA flow-control window."""

    __slots__ = (
        "sid", "head", "body", "body_len", "task", "window", "credit",
    )

    def __init__(self, sid: int, head: Dict) -> None:
        self.sid = sid
        self.head = head
        self.body: List[bytes] = []
        self.body_len = 0
        self.task: Optional["asyncio.Task[None]"] = None
        self.window = MUX_INITIAL_WINDOW
        self.credit = asyncio.Event()

    def to_request(self):
        """Build the Request this stream carries, or a Response for
        content-level errors (bad head shape earns a per-stream 400,
        not a connection teardown — the framing itself was fine)."""
        method = self.head.get("method")
        path = self.head.get("path")
        if not isinstance(method, str) or not isinstance(path, str):
            return Response(400, b"malformed mux request head\n")
        raw_headers = self.head.get("headers")
        headers: Dict[str, str] = {}
        if isinstance(raw_headers, dict):
            headers = {
                str(k).lower(): str(v) for k, v in raw_headers.items()
            }
        parts = urlsplit(path)
        return Request(
            method.upper(), parts.path, parse_qs(parts.query), headers,
            b"".join(self.body),
        )


def _mux_response_head(response) -> bytes:
    """The JSON HEADERS payload for a Response/StreamingResponse."""
    headers = {"content-type": response.content_type}
    for key, value in response.headers.items():
        headers[key.lower()] = value
    return json.dumps(
        {"status": response.status, "headers": headers}
    ).encode()


def _mux_refusal_head() -> bytes:
    return json.dumps(
        {
            "status": 503,
            "headers": {
                "content-type": "text/plain; charset=utf-8",
                "retry-after": "1",
            },
        }
    ).encode()


_REASONS = {
    200: "OK",
    400: "Bad Request",
    404: "Not Found",
    405: "Method Not Allowed",
    408: "Request Timeout",
    422: "Unprocessable Entity",
    429: "Too Many Requests",
    500: "Internal Server Error",
    503: "Service Unavailable",
    504: "Gateway Timeout",
}

Handler = Callable[[Request], Awaitable[Response]]


class HTTPServer:
    """Route-table HTTP server over asyncio streams; bind via
    ``start_tcp`` or ``start_unix``."""

    def __init__(self) -> None:
        self.routes: Dict[Tuple[str, str], Handler] = {}
        # optional catch-all for dynamic paths (e.g. /v1/agent/service/
        # deregister/<id>); returning None falls through to 404
        self.fallback: Optional[
            Callable[[Request], Awaitable[Optional[Response]]]
        ] = None
        self._server: Optional[asyncio.AbstractServer] = None
        # live connection writers, so stop() can force-close lingering
        # keep-alive connections instead of leaving their handler
        # coroutines parked on a readline forever
        self._conns: Set[asyncio.StreamWriter] = set()
        # observability (and the keep-alive test suite's ground truth):
        # how many connections were accepted vs requests served — a
        # reuse ratio of requests/connections >> 1 means pooling works
        self.connections_accepted = 0
        self.requests_served = 0
        # cp-mux/1: whether this server accepts the upgrade, and how
        # many connections/streams took it (mux requests also count
        # into requests_served — they ARE requests)
        self.mux_enabled = True
        self.mux_connections = 0
        self.mux_streams_served = 0

    def route(self, method: str, path: str, handler: Handler) -> None:
        self.routes[(method.upper(), path)] = handler

    async def start_tcp(self, host: str, port: int) -> None:
        self._server = await asyncio.start_server(self._handle, host, port)

    @property
    def bound_port(self) -> Optional[int]:
        """The actual TCP port after binding (useful with port 0)."""
        if self._server is None or not self._server.sockets:
            return None
        return self._server.sockets[0].getsockname()[1]

    async def start_unix(self, path: str) -> None:
        self._server = await asyncio.start_unix_server(self._handle, path)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # force-close lingering keep-alive connections BEFORE
            # awaiting wait_closed(): on Python >= 3.12.1 wait_closed
            # blocks until every connection handler finishes, and an
            # idle handler is parked on its next-request read for up
            # to KEEPALIVE_IDLE_TIMEOUT
            for conn_writer in list(self._conns):
                conn_writer.close()
            await self._server.wait_closed()
            self._server = None
        else:
            for conn_writer in list(self._conns):
                conn_writer.close()
        # yield once so the force-closed handlers observe EOF and exit
        await asyncio.sleep(0)

    async def abort(self) -> None:
        """Die like SIGKILL (chaos/testing): drop the listener and RST
        every live connection with nothing flushed. ``stop()`` closes
        connections politely (FIN after buffered bytes), which lets a
        handler racing shutdown still deliver a well-formed error
        response — a process that was KILLED can't do that, and fault
        injection must not be gentler than the fault it models."""
        if self._server is not None:
            self._server.close()
        for conn_writer in list(self._conns):
            transport = conn_writer.transport
            if transport is not None:
                transport.abort()
        if self._server is not None:
            await self._server.wait_closed()
            self._server = None
        await asyncio.sleep(0)

    # bound on reading one request (headers+body): a stalled client
    # can't pin a connection open indefinitely. Handler execution is
    # deliberately unbounded (inference warmup can be slow).
    REQUEST_READ_TIMEOUT = 30.0
    # how long a keep-alive connection may sit idle between requests
    # before the server reaps it, and how many requests one connection
    # may carry before being retired (bounds fd/state lifetime under
    # misbehaving clients)
    KEEPALIVE_IDLE_TIMEOUT = 75.0
    KEEPALIVE_MAX_REQUESTS = 1000
    # concurrent cp-mux streams one connection may carry; an excess
    # stream is refused with a per-stream 503, never a conn error
    MUX_MAX_STREAMS = MUX_MAX_STREAMS

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self.connections_accepted += 1
        self._conns.add(writer)
        try:
            await self._serve_connection(reader, writer)
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _serve_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        """The keep-alive loop: requests are served off one connection
        until the client closes, asks to close, idles out, hits the
        per-connection request cap, or trips a protocol error."""
        served = 0
        while True:
            # the FIRST request on a fresh connection is bounded by the
            # read timeout (a stalled half-request earns a 408, see the
            # slow-loris path below); BETWEEN requests the bound is the
            # idle timeout and expiry is a quiet reap, not an error —
            # an idle pooled client did nothing wrong
            try:
                request_line = await timed_read(
                    reader,
                    reader.readline(),
                    self.REQUEST_READ_TIMEOUT
                    if served == 0
                    else self.KEEPALIVE_IDLE_TIMEOUT,
                )
            except asyncio.TimeoutError:
                if served == 0:
                    await self._write_response(
                        writer, Response(408, b"request timeout\n"),
                        close=True,
                    )
                return
            except (ConnectionError, asyncio.IncompleteReadError):
                return
            except Exception:
                # e.g. ValueError from a request line overrunning the
                # StreamReader limit: a client error must still get an
                # answer, never an unhandled task exception
                log.exception("request line read failed")
                await self._write_response(
                    writer,
                    Response(400, b"malformed request line\n"),
                    close=True,
                )
                return
            if not request_line:
                return  # client closed the connection cleanly
            # the narrow client-error excepts cover only the READ
            # phase; a handler raising TimeoutError must surface as a
            # logged 500, not be misblamed on the client as a 408
            try:
                request = await timed_read(
                    reader,
                    self._read_request(reader, request_line),
                    self.REQUEST_READ_TIMEOUT,
                )
            except asyncio.TimeoutError:
                request = Response(408, b"request timeout\n")
            except asyncio.IncompleteReadError:
                request = Response(400, b"truncated request\n")
            except ConnectionError:
                return
            except Exception:
                log.exception("request read failed")
                request = Response(500, b"internal server error\n")
            if isinstance(request, Response):
                # protocol-level failure: request framing can no
                # longer be trusted, so answer and close
                await self._write_response(writer, request, close=True)
                return
            served += 1
            self.requests_served += 1
            if (
                self.mux_enabled
                and request.headers.get("upgrade", "").lower()
                == MUX_PROTOCOL
                and "upgrade"
                in request.headers.get("connection", "").lower()
            ):
                # negotiated switch to framed multiplexing: everything
                # after the 101 is cp-mux/1 frames, both directions.
                # With mux_enabled=False the request instead falls
                # through to the route table (MUX_UPGRADE_PATH is
                # unroutable -> 404 keep-alive), which is the
                # client's signal to stay on plain HTTP/1.1.
                try:
                    writer.write(
                        b"HTTP/1.1 101 Switching Protocols\r\n"
                        b"Upgrade: " + MUX_PROTOCOL.encode() + b"\r\n"
                        b"Connection: Upgrade\r\n\r\n"
                    )
                    await writer.drain()
                except (ConnectionError, BrokenPipeError, OSError):
                    return  # client reset before/under the 101
                self.mux_connections += 1
                await self._serve_mux(reader, writer)
                return
            keep = (
                request.wants_keepalive()
                and served < self.KEEPALIVE_MAX_REQUESTS
            )
            try:
                response = await self._dispatch(request)
            except Exception:
                log.exception("request handling failed")
                response = Response(500, b"internal server error\n")
            if isinstance(response, StreamingResponse):
                # close-delimited by contract; ends the connection
                await self._write_stream(reader, writer, response)
                return
            if not await self._write_response(
                writer, response, close=not keep
            ):
                return  # client went away mid-write
            if not keep:
                return

    # -- cp-mux/1 accept path -------------------------------------------

    async def _serve_mux(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        """The multiplexed sibling of the keep-alive loop: one read
        loop demultiplexes frames into per-stream state, each
        completed request dispatches as its own task, and response
        writes interleave on the shared socket. Frames are enqueued
        whole under a writer lock, so concurrent stream tasks can
        never tear each other's frames; per-stream WINDOW credit gates
        response DATA, so a stream whose consumer stalls parks only
        its own task while the others keep writing."""
        streams: Dict[int, _MuxServerStream] = {}
        tasks: Set["asyncio.Task[None]"] = set()
        frames_seen = 0

        # frame writes need no lock: each frame is emitted by ONE
        # synchronous writer.write() call (built fully before the
        # write, no await in between), so concurrent stream tasks
        # interleave at frame granularity by construction — and the
        # drain afterwards is pure flow control, safe to share. This
        # also keeps the writer publishing outside any lock
        # (CP-LOCKPUB's shape: never await subscribers mid-critical-
        # section).
        async def send(ftype: int, sid: int, payload: bytes = b"") -> None:
            writer.write(encode_frame(ftype, sid, payload))
            await writer.drain()

        async def send_data(stream: "_MuxServerStream", data: bytes) -> None:
            view = memoryview(data)
            while view:
                while stream.window <= 0:
                    stream.credit.clear()
                    await stream.credit.wait()
                n = min(len(view), stream.window, MUX_CHUNK)
                stream.window -= n
                await send(FRAME_DATA, stream.sid, bytes(view[:n]))
                view = view[n:]

        async def send_streaming(
            stream: "_MuxServerStream", response: StreamingResponse
        ) -> None:
            """Relay an async-iterator body as interleaved DATA
            frames. Mirrors _write_stream's cleanup contract: the
            generator is aclose()d and the close callback fires
            however the stream ends (completion, CANCEL, connection
            death) — a handler's finally still frees what the request
            holds. A handler that dies mid-iteration CANCELs the
            stream (the client's error signal), never leaves it
            dangling without an END."""
            agen = response.chunks
            ended = False
            try:
                await send(
                    FRAME_HEADERS, stream.sid,
                    _mux_response_head(response),
                )
                async for chunk in agen:
                    await send_data(stream, chunk)
                await send(FRAME_END, stream.sid)
                ended = True
            except (ConnectionError, BrokenPipeError, OSError):
                ended = True  # connection is gone; nothing to CANCEL
            except Exception:
                log.exception("mux stream write failed")
            finally:
                if not ended:
                    try:
                        await send(FRAME_CANCEL, stream.sid)
                    except (ConnectionError, BrokenPipeError, OSError):
                        log.debug("mux: CANCEL after failed stream "
                                  "write found the connection gone")
                try:
                    await agen.aclose()
                except Exception:
                    log.exception("mux stream close failed")
                if response.close is not None:
                    try:
                        response.close()
                    except Exception:
                        log.exception("mux stream close callback failed")

        async def run_stream(stream: "_MuxServerStream") -> None:
            # each stream runs as its own task, so binding the stream
            # id here scopes it to exactly this request's handler —
            # log records emitted under it carry stream_id (and the
            # handler's trace carries it for /v1/traces)
            _get_tracing().set_stream_id(stream.sid)
            try:
                request = stream.to_request()
                if isinstance(request, Response):
                    response: Response = request
                else:
                    self.requests_served += 1
                    self.mux_streams_served += 1
                    try:
                        response = await self._dispatch(request)
                    except Exception:
                        log.exception("mux request handling failed")
                        response = Response(
                            500, b"internal server error\n"
                        )
                if isinstance(response, StreamingResponse):
                    await send_streaming(stream, response)
                    return
                try:
                    head = _mux_response_head(response)
                    body = response.body
                    if len(body) <= stream.window:
                        # common case: the whole response fits the
                        # client's current window — HEADERS+DATA+END
                        # as ONE write and ONE drain (three separate
                        # frame sends cost two extra drain cycles on
                        # the hot path)
                        stream.window -= len(body)
                        frames = encode_frame(
                            FRAME_HEADERS, stream.sid, head
                        )
                        if body:
                            frames += encode_frame(
                                FRAME_DATA, stream.sid, body
                            )
                        frames += encode_frame(FRAME_END, stream.sid)
                        writer.write(frames)
                        await writer.drain()
                    else:
                        await send(FRAME_HEADERS, stream.sid, head)
                        await send_data(stream, body)
                        await send(FRAME_END, stream.sid)
                except (ConnectionError, BrokenPipeError, OSError):
                    return  # peer is gone; reader loop unwinds the rest
            finally:
                streams.pop(stream.sid, None)

        async def watchdog() -> None:
            # the mux analog of the keep-alive idle reap: a connection
            # with no live streams and no frames for a full idle
            # window is retired; one with in-flight streams is never
            # reaped, however slow its handlers (handler execution is
            # deliberately unbounded, as on the HTTP/1.1 path)
            seen = -1
            while True:
                await asyncio.sleep(self.KEEPALIVE_IDLE_TIMEOUT)
                if not streams and frames_seen == seen:
                    writer.close()
                    return
                seen = frames_seen

        reaper = asyncio.ensure_future(watchdog())
        try:
            while True:
                try:
                    ftype, sid, payload = await read_frame(reader)
                except (
                    asyncio.IncompleteReadError, ConnectionError, OSError,
                ):
                    return  # peer went away; tasks unwind in finally
                except MuxProtocolError as exc:
                    log.warning("mux: protocol error: %s", exc)
                    return
                frames_seen += 1
                if ftype == FRAME_PING:
                    await send(FRAME_PONG, sid, payload)
                elif ftype == FRAME_HEADERS:
                    if sid == 0 or sid in streams:
                        log.warning(
                            "mux: HEADERS for invalid/live stream %d", sid
                        )
                        return
                    try:
                        head = json.loads(payload.decode())
                        if not isinstance(head, dict):
                            raise ValueError("head is not an object")
                    except (ValueError, UnicodeDecodeError) as exc:
                        log.warning("mux: malformed HEADERS: %s", exc)
                        return
                    if len(streams) >= self.MUX_MAX_STREAMS:
                        # refuse THIS stream, keep the connection: the
                        # client sees a retryable 503, its co-resident
                        # streams see nothing at all
                        await send(
                            FRAME_HEADERS, sid,
                            _mux_refusal_head(),
                        )
                        await send(FRAME_END, sid)
                        continue
                    streams[sid] = _MuxServerStream(sid, head)
                elif ftype == FRAME_DATA:
                    stream = streams.get(sid)
                    if stream is None or stream.task is not None:
                        continue  # cancelled/raced: late frames are noise
                    stream.body_len += len(payload)
                    if stream.body_len > MAX_BODY:
                        log.warning("mux: stream %d body exceeds cap", sid)
                        return
                    stream.body.append(payload)
                elif ftype == FRAME_END:
                    stream = streams.get(sid)
                    if stream is None or stream.task is not None:
                        continue
                    stream.task = asyncio.ensure_future(
                        run_stream(stream)
                    )
                    tasks.add(stream.task)
                    stream.task.add_done_callback(tasks.discard)
                elif ftype == FRAME_CANCEL:
                    stream = streams.pop(sid, None)
                    if stream is not None and stream.task is not None:
                        # the handler task's finally (and a streaming
                        # response's aclose/close) runs its cleanup;
                        # the stream id is free for reuse immediately
                        stream.task.cancel()
                elif ftype == FRAME_WINDOW:
                    stream = streams.get(sid)
                    if stream is not None and len(payload) == 4:
                        stream.window += int.from_bytes(payload, "big")
                        stream.credit.set()
                # FRAME_PONG from a client is valid but meaningless here
        except (ConnectionError, BrokenPipeError, OSError):
            # a read-loop send (PONG, stream-cap refusal) bounced off
            # a peer that just reset: same quiet exit as read-side EOF
            return
        finally:
            reaper.cancel()
            for task in list(tasks):
                task.cancel()
            for task in list(tasks):
                try:
                    await task
                except asyncio.CancelledError:
                    pass
                except Exception:
                    log.exception("mux stream task failed during close")

    async def _write_response(
        self,
        writer: asyncio.StreamWriter,
        response: Response,
        *,
        close: bool,
    ) -> bool:
        """Send one Content-Length-framed response. Returns False when
        the client is gone (the connection is unusable either way)."""
        try:
            reason = _REASONS.get(response.status, "Unknown")
            headers = {
                "Content-Type": response.content_type,
                "Content-Length": str(len(response.body)),
                "Connection": "close" if close else "keep-alive",
                **response.headers,
            }
            head = f"HTTP/1.1 {response.status} {reason}\r\n" + "".join(
                f"{k}: {v}\r\n" for k, v in headers.items()
            )
            writer.write(head.encode() + b"\r\n" + response.body)
            await writer.drain()
            return True
        except (ConnectionError, BrokenPipeError):
            return False

    async def _write_stream(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        response: StreamingResponse,
    ) -> None:
        """Send head, then relay chunks as they arrive; abort the
        moment the client goes away. Each chunk wait races a read on
        the request side of the socket — EOF there is the earliest
        reliable disconnect signal (drain() only fails on a later
        write)."""
        async def _client_gone() -> None:
            # only a true EOF means the client left: a pipelined
            # second request from a keep-alive client puts BYTES on
            # the read side, which must not abort the stream mid-way
            while await reader.read(65536):
                pass

        agen = response.chunks
        eof_task = asyncio.ensure_future(_client_gone())
        try:
            reason = _REASONS.get(response.status, "Unknown")
            headers = {
                "Content-Type": response.content_type,
                "Cache-Control": "no-store",
                "Connection": "close",
                **response.headers,
            }
            head = f"HTTP/1.1 {response.status} {reason}\r\n" + "".join(
                f"{k}: {v}\r\n" for k, v in headers.items()
            )
            writer.write(head.encode() + b"\r\n")
            await writer.drain()
            while True:
                get_task = asyncio.ensure_future(agen.__anext__())
                await asyncio.wait(
                    {get_task, eof_task},
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if eof_task.done():
                    get_task.cancel()
                    try:
                        await get_task
                    except (StopAsyncIteration, asyncio.CancelledError,
                            Exception):
                        pass
                    break
                chunk = get_task.result()  # raises StopAsyncIteration
                writer.write(chunk)
                await writer.drain()
        except StopAsyncIteration:
            pass
        except (ConnectionError, BrokenPipeError):
            pass
        except Exception:
            log.exception("stream write failed")
        finally:
            eof_task.cancel()
            try:
                await eof_task
            except (asyncio.CancelledError, Exception):
                pass
            try:
                await agen.aclose()  # run the generator's cleanup
            except Exception:
                log.exception("stream close failed")
            if response.close is not None:
                try:
                    response.close()
                except Exception:
                    log.exception("stream close callback failed")
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _read_request(
        self, reader: asyncio.StreamReader, request_line: bytes
    ):
        """Parse one request whose request line was already read;
        returns a Request, or a Response for protocol-level errors."""
        try:
            method, target, version = request_line.decode().split(None, 2)
        except (ValueError, UnicodeDecodeError):
            return Response(400, b"malformed request line\n")
        headers: Dict[str, str] = {}
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            if b":" in line:
                try:
                    key, _, value = line.decode().partition(":")
                except UnicodeDecodeError:
                    return Response(400, b"malformed header\n")
                headers[key.strip().lower()] = value.strip()
        try:
            length = int(headers.get("content-length", "0") or "0")
        except ValueError:
            return Response(400, b"bad content-length\n")
        if length < 0:
            return Response(400, b"bad content-length\n")
        if length > MAX_BODY:
            return Response(400, b"body too large\n")
        body = await reader.readexactly(length) if length else b""
        parts = urlsplit(target)
        return Request(
            method.upper(), parts.path, parse_qs(parts.query), headers,
            body, version=version.strip(),
        )

    async def _dispatch(self, request: Request) -> Response:
        handler = self.routes.get((request.method, request.path))
        if handler is None:
            if self.fallback is not None:
                response = await self.fallback(request)
                if response is not None:
                    return response
            if any(p == request.path for (_m, p) in self.routes):
                return Response(405, b"method not allowed\n")
            return Response(404, b"not found\n")
        return await handler(request)
