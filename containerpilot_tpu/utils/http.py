"""Minimal asyncio HTTP/1.1 server.

Shared plumbing for the two in-process servers the supervisor runs —
the telemetry endpoint on TCP (reference: telemetry/telemetry.go) and
the control plane on a unix domain socket (reference: control/control.go).
Requests are tiny and local, so this deliberately supports only what
those servers need: one request per connection, optional content-length
bodies, no keep-alive, no chunked encoding.
"""
from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

log = logging.getLogger("containerpilot.http")

MAX_BODY = 4 * 1024 * 1024


class Request:
    def __init__(
        self,
        method: str,
        path: str,
        query: Dict[str, list],
        headers: Dict[str, str],
        body: bytes,
    ) -> None:
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers
        self.body = body


class Response:
    def __init__(
        self,
        status: int = 200,
        body: bytes = b"",
        content_type: str = "text/plain; charset=utf-8",
        headers: Optional[Dict[str, str]] = None,
    ) -> None:
        self.status = status
        self.body = body
        self.content_type = content_type
        self.headers = headers or {}


class StreamingResponse:
    """A response whose body arrives incrementally from an async
    iterator of byte chunks (SSE events, chunk-boundary token
    deltas). Sent with ``Connection: close`` and no Content-Length:
    the closing connection delimits the stream, which every HTTP/1.1
    client understands and which keeps this server's one-request-per-
    connection model intact.

    Client disconnects are detected promptly (the reader hits EOF)
    and the iterator is ``aclose()``d, so a handler generator's
    ``finally`` can release what the request holds (e.g. free a slot
    mid-generation)."""

    def __init__(
        self,
        chunks,  # AsyncIterator[bytes]
        status: int = 200,
        content_type: str = "text/event-stream",
        headers: Optional[Dict[str, str]] = None,
        close: Optional[Callable[[], None]] = None,
    ) -> None:
        self.status = status
        self.chunks = chunks
        self.content_type = content_type
        self.headers = headers or {}
        # aclose() on a NEVER-STARTED async generator skips its body
        # entirely (an immediate disconnect aborts before the first
        # __anext__), so generator-finally cleanup alone is not
        # enough: ``close`` is invoked unconditionally when the
        # stream ends, however it ends. Make it idempotent — the
        # generator's own finally may run too.
        self.close = close


_REASONS = {
    200: "OK",
    400: "Bad Request",
    404: "Not Found",
    405: "Method Not Allowed",
    408: "Request Timeout",
    422: "Unprocessable Entity",
    500: "Internal Server Error",
    503: "Service Unavailable",
}

Handler = Callable[[Request], Awaitable[Response]]


class HTTPServer:
    """Route-table HTTP server over asyncio streams; bind via
    ``start_tcp`` or ``start_unix``."""

    def __init__(self) -> None:
        self.routes: Dict[Tuple[str, str], Handler] = {}
        # optional catch-all for dynamic paths (e.g. /v1/agent/service/
        # deregister/<id>); returning None falls through to 404
        self.fallback: Optional[
            Callable[[Request], Awaitable[Optional[Response]]]
        ] = None
        self._server: Optional[asyncio.AbstractServer] = None

    def route(self, method: str, path: str, handler: Handler) -> None:
        self.routes[(method.upper(), path)] = handler

    async def start_tcp(self, host: str, port: int) -> None:
        self._server = await asyncio.start_server(self._handle, host, port)

    @property
    def bound_port(self) -> Optional[int]:
        """The actual TCP port after binding (useful with port 0)."""
        if self._server is None or not self._server.sockets:
            return None
        return self._server.sockets[0].getsockname()[1]

    async def start_unix(self, path: str) -> None:
        self._server = await asyncio.start_unix_server(self._handle, path)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # bound on reading one request (headers+body): a stalled client
    # can't pin a connection open indefinitely. Handler execution is
    # deliberately unbounded (inference warmup can be slow).
    REQUEST_READ_TIMEOUT = 30.0

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        # the narrow client-error excepts cover only the READ phase;
        # a handler raising TimeoutError must surface as a logged 500,
        # not be misblamed on the client as a 408
        try:
            request = await asyncio.wait_for(
                self._read_request(reader), timeout=self.REQUEST_READ_TIMEOUT
            )
        except asyncio.TimeoutError:
            request = Response(408, b"request timeout\n")
        except asyncio.IncompleteReadError:
            request = Response(400, b"truncated request\n")
        except Exception:
            log.exception("request read failed")
            request = Response(500, b"internal server error\n")
        if isinstance(request, Response):
            response = request
        else:
            try:
                response = await self._dispatch(request)
            except Exception:
                log.exception("request handling failed")
                response = Response(500, b"internal server error\n")
        if isinstance(response, StreamingResponse):
            await self._write_stream(reader, writer, response)
            return
        try:
            reason = _REASONS.get(response.status, "Unknown")
            headers = {
                "Content-Type": response.content_type,
                "Content-Length": str(len(response.body)),
                "Connection": "close",
                **response.headers,
            }
            head = f"HTTP/1.1 {response.status} {reason}\r\n" + "".join(
                f"{k}: {v}\r\n" for k, v in headers.items()
            )
            writer.write(head.encode() + b"\r\n" + response.body)
            await writer.drain()
        except (ConnectionError, BrokenPipeError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _write_stream(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        response: StreamingResponse,
    ) -> None:
        """Send head, then relay chunks as they arrive; abort the
        moment the client goes away. Each chunk wait races a read on
        the request side of the socket — EOF there is the earliest
        reliable disconnect signal (drain() only fails on a later
        write)."""
        async def _client_gone() -> None:
            # only a true EOF means the client left: a pipelined
            # second request from a keep-alive client puts BYTES on
            # the read side, which must not abort the stream mid-way
            while await reader.read(65536):
                pass

        agen = response.chunks
        eof_task = asyncio.ensure_future(_client_gone())
        try:
            reason = _REASONS.get(response.status, "Unknown")
            headers = {
                "Content-Type": response.content_type,
                "Cache-Control": "no-store",
                "Connection": "close",
                **response.headers,
            }
            head = f"HTTP/1.1 {response.status} {reason}\r\n" + "".join(
                f"{k}: {v}\r\n" for k, v in headers.items()
            )
            writer.write(head.encode() + b"\r\n")
            await writer.drain()
            while True:
                get_task = asyncio.ensure_future(agen.__anext__())
                await asyncio.wait(
                    {get_task, eof_task},
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if eof_task.done():
                    get_task.cancel()
                    try:
                        await get_task
                    except (StopAsyncIteration, asyncio.CancelledError,
                            Exception):
                        pass
                    break
                chunk = get_task.result()  # raises StopAsyncIteration
                writer.write(chunk)
                await writer.drain()
        except StopAsyncIteration:
            pass
        except (ConnectionError, BrokenPipeError):
            pass
        except Exception:
            log.exception("stream write failed")
        finally:
            eof_task.cancel()
            try:
                await eof_task
            except (asyncio.CancelledError, Exception):
                pass
            try:
                await agen.aclose()  # run the generator's cleanup
            except Exception:
                log.exception("stream close failed")
            if response.close is not None:
                try:
                    response.close()
                except Exception:
                    log.exception("stream close callback failed")
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _read_request(self, reader: asyncio.StreamReader):
        """Parse one request; returns a Request, or a Response for
        protocol-level errors."""
        request_line = await reader.readline()
        if not request_line:
            return Response(400, b"empty request\n")
        try:
            method, target, _version = request_line.decode().split(None, 2)
        except (ValueError, UnicodeDecodeError):
            return Response(400, b"malformed request line\n")
        headers: Dict[str, str] = {}
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            if b":" in line:
                try:
                    key, _, value = line.decode().partition(":")
                except UnicodeDecodeError:
                    return Response(400, b"malformed header\n")
                headers[key.strip().lower()] = value.strip()
        try:
            length = int(headers.get("content-length", "0") or "0")
        except ValueError:
            return Response(400, b"bad content-length\n")
        if length < 0:
            return Response(400, b"bad content-length\n")
        if length > MAX_BODY:
            return Response(400, b"body too large\n")
        body = await reader.readexactly(length) if length else b""
        parts = urlsplit(target)
        return Request(
            method.upper(), parts.path, parse_qs(parts.query), headers, body
        )

    async def _dispatch(self, request: Request) -> Response:
        handler = self.routes.get((request.method, request.path))
        if handler is None:
            if self.fallback is not None:
                response = await self.fallback(request)
                if response is not None:
                    return response
            if any(p == request.path for (_m, p) in self.routes):
                return Response(405, b"method not allowed\n")
            return Response(404, b"not found\n")
        return await handler(request)
