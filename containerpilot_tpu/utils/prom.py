"""Shared Prometheus exposition plumbing for the serving surfaces.

The single-host server (workload/serve.py) and the pod frontend
(workload/serve_dist.py) each keep their own metrics in a PRIVATE
CollectorRegistry (an in-process supervisor's metrics must never
collide with a workload's), but the /metrics response format is ONE
convention — exposed here so the two surfaces cannot drift.
"""
from __future__ import annotations

from typing import Tuple

PROM_CONTENT_TYPE = "text/plain; version=0.0.4"


def exposition(registry) -> Tuple[bytes, str]:
    """(body, content_type) for a /metrics response over ``registry``."""
    from prometheus_client import generate_latest

    return generate_latest(registry), PROM_CONTENT_TYPE
