"""Shared Prometheus exposition plumbing for the serving surfaces.

The single-host server (workload/serve.py) and the pod frontend
(workload/serve_dist.py) each keep their own metrics in a PRIVATE
CollectorRegistry (an in-process supervisor's metrics must never
collide with a workload's), but the /metrics response format is ONE
convention — exposed here so the two surfaces cannot drift.
"""
from __future__ import annotations

from typing import Tuple

PROM_CONTENT_TYPE = "text/plain; version=0.0.4"


def exposition(registry) -> Tuple[bytes, str]:
    """(body, content_type) for a /metrics response over ``registry``."""
    from prometheus_client import generate_latest

    return generate_latest(registry), PROM_CONTENT_TYPE


def ensure_build_info(registry, role: str) -> None:
    """Register the ONE shared identity gauge every /metrics surface
    in the tree exports: ``cp_build_info{version,role} 1``. The first
    question on any triage call — "which build is this, and what is
    it?" — must be answerable from the metrics alone; a constant-1
    info gauge is the standard Prometheus idiom for it. Idempotent
    per registry (re-registration — config reloads, test fixtures
    sharing the global registry — is a no-op, never a crash)."""
    from prometheus_client import Gauge

    from ..version import VERSION

    try:
        gauge = Gauge(
            "cp_build_info",
            "build identity: constant 1, labeled by version and the "
            "process role (supervisor/replica/pod/gateway)",
            ["version", "role"],
            registry=registry,
        )
    except ValueError:
        # already registered in this registry (reload/fixture reuse)
        return
    gauge.labels(VERSION, role).set(1)


def ensure_loop_lag_gauge(registry, probe) -> None:
    """Register the shared event-loop health gauge
    ``cp_loop_lag_ms{stat="max"|"p99"}`` over a
    ``analysis/loopcheck.LoopLagProbe`` — one definition, so the
    gateway and replica surfaces cannot drift. Idempotent per
    registry, like ``ensure_build_info``."""
    from prometheus_client import Gauge

    try:
        gauge = Gauge(
            "cp_loop_lag_ms",
            "event-loop scheduling delay over the probe ring, ms "
            "(docs/70-static-analysis.md has the loopcheck runbook)",
            ["stat"],
            registry=registry,
        )
    except ValueError:
        return
    gauge.labels("max").set_function(probe.max_ms)
    gauge.labels("p99").set_function(probe.p99_ms)
