"""Shared Prometheus exposition plumbing for the serving surfaces.

The single-host server (workload/serve.py) and the pod frontend
(workload/serve_dist.py) each keep their own metrics in a PRIVATE
CollectorRegistry (an in-process supervisor's metrics must never
collide with a workload's), but the /metrics response format is ONE
convention — exposed here so the two surfaces cannot drift.
"""
from __future__ import annotations

from typing import Tuple

PROM_CONTENT_TYPE = "text/plain; version=0.0.4"


def exposition(registry) -> Tuple[bytes, str]:
    """(body, content_type) for a /metrics response over ``registry``."""
    from prometheus_client import generate_latest

    return generate_latest(registry), PROM_CONTENT_TYPE


def ensure_build_info(registry, role: str) -> None:
    """Register the ONE shared identity gauge every /metrics surface
    in the tree exports: ``cp_build_info{version,role} 1``. The first
    question on any triage call — "which build is this, and what is
    it?" — must be answerable from the metrics alone; a constant-1
    info gauge is the standard Prometheus idiom for it. Idempotent
    per registry (re-registration — config reloads, test fixtures
    sharing the global registry — is a no-op, never a crash)."""
    from prometheus_client import Gauge

    from ..version import VERSION

    try:
        gauge = Gauge(
            "cp_build_info",
            "build identity: constant 1, labeled by version and the "
            "process role (supervisor/replica/pod/gateway)",
            ["version", "role"],
            registry=registry,
        )
    except ValueError:
        # already registered in this registry (reload/fixture reuse)
        return
    gauge.labels(VERSION, role).set(1)


def ensure_goodput_gauges(registry, ledger, counters=None) -> None:
    """Register the shared device-time-ledger gauges over a
    ``telemetry/goodput.DeviceTimeLedger``:
    ``cp_device_seconds_total{stage}`` (one row per ledger stage,
    read live so the open segment is included) plus — when
    ``counters`` (a zero-arg callable returning ``(dispatches,
    tokens_out)``) is given — ``cp_decode_dispatches_total`` and
    ``cp_tokens_out_total``, the dispatches/token series the
    megakernel work is measured against. One definition, so the
    replica and pod surfaces cannot drift. Idempotent per registry,
    like ``ensure_build_info``."""
    from prometheus_client import Gauge

    from ..telemetry.goodput import STAGES

    try:
        gauge = Gauge(
            "cp_device_seconds_total",
            "device-time ledger: cumulative wall seconds attributed "
            "to each stage of this replica's life "
            "(docs/90-observability.md has the stage glossary)",
            ["stage"],
            registry=registry,
        )
    except ValueError:
        return
    for stage in STAGES:
        gauge.labels(stage).set_function(
            lambda s=stage: ledger.stage_seconds(s)
        )
    if counters is None:
        return
    Gauge(
        "cp_decode_dispatches_total",
        "host->device dispatches the decode path has issued "
        "(prefills + chunk rounds); divide by cp_tokens_out_total "
        "for dispatches/token",
        registry=registry,
    ).set_function(lambda: float(counters()[0]))
    Gauge(
        "cp_tokens_out_total",
        "tokens the decode path has emitted (pre-trim engine "
        "emission)",
        registry=registry,
    ).set_function(lambda: float(counters()[1]))


def ensure_loop_lag_gauge(registry, probe) -> None:
    """Register the shared event-loop health gauge
    ``cp_loop_lag_ms{stat="max"|"p99"}`` over a
    ``analysis/loopcheck.LoopLagProbe`` — one definition, so the
    gateway and replica surfaces cannot drift. Idempotent per
    registry, like ``ensure_build_info``."""
    from prometheus_client import Gauge

    try:
        gauge = Gauge(
            "cp_loop_lag_ms",
            "event-loop scheduling delay over the probe ring, ms "
            "(docs/70-static-analysis.md has the loopcheck runbook)",
            ["stat"],
            registry=registry,
        )
    except ValueError:
        return
    gauge.labels("max").set_function(probe.max_ms)
    gauge.labels("p99").set_function(probe.p99_ms)
