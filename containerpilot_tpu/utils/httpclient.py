"""Shared keep-alive discipline for synchronous http.client callers.

Two clients keep a connection across calls — the ControlClient (unix
socket, one conn per client) and the ConsulBackend (TCP, one conn per
thread). Both need the same subtle state machine, so it lives here
once:

- take the kept connection, else dial a fresh one;
- a KEPT connection that fails **before any response byte arrived**
  gets one transparent redial-and-resend: a reset/broken-pipe while
  SENDING means the server never took the full request, and
  ``RemoteDisconnected`` from ``getresponse()`` means the server
  closed without answering a byte — overwhelmingly the idle reaper
  racing our send. This is the standard keep-alive client heuristic
  (urllib3, Go's http.Transport do the same), not a guarantee: a
  server that processed the request and then died before writing ANY
  response byte is indistinguishable from a reap, so a verb can
  double-apply in that narrow crash window. Callers whose verbs
  can't tolerate that must not share a kept connection;
- a failure AFTER ``getresponse()`` returned (a reset mid-body, a
  garbled status line) is NOT resent — response bytes prove the
  server received and likely processed the request;
- the connection is kept again only when the response wasn't
  ``Connection: close``.

Transport exceptions propagate unchanged; callers wrap them in their
own error types (and own any connect-phase retry policy).
"""
from __future__ import annotations

import http.client
from typing import Callable, Dict, Optional, Tuple

_tracing = None


def _trace_id() -> str:
    """The active trace id, lazily bound: a control-plane or catalog
    call made while serving a traced request carries the request's
    X-CP-Trace, so cross-service log/trace greps pick it up too."""
    global _tracing
    if _tracing is None:
        try:
            from ..telemetry import tracing as _tracing_mod
        except ImportError:
            return ""
        _tracing = _tracing_mod
    return _tracing.current_trace_id()


def keepalive_request(
    take_conn: Callable[[], Optional[http.client.HTTPConnection]],
    put_conn: Callable[[http.client.HTTPConnection], None],
    new_conn: Callable[[], http.client.HTTPConnection],
    method: str,
    path: str,
    body=None,
    headers: Optional[Dict[str, str]] = None,
) -> Tuple[int, bytes]:
    """One request over the kept connection; returns (status, body).

    Raises whatever the transport raised (OSError /
    http.client.HTTPException) once the kept-connection redial is
    exhausted — at most one redial happens, since the redialed
    connection is fresh. See the module docstring for the resend
    heuristic's (narrow) double-apply window."""
    send_headers = dict(headers or {})
    trace_id = _trace_id()
    if trace_id and "X-CP-Trace" not in send_headers:
        send_headers["X-CP-Trace"] = trace_id
    while True:
        conn = take_conn()
        reused = conn is not None
        if conn is None:
            conn = new_conn()
        try:
            conn.request(method, path, body=body, headers=send_headers)
        except (OSError, http.client.HTTPException) as exc:
            conn.close()
            if reused and isinstance(exc, ConnectionError):
                continue  # send bounced off the reaped kept conn
            raise
        try:
            resp = conn.getresponse()
            payload = resp.read()
        except (OSError, http.client.HTTPException) as exc:
            conn.close()
            if reused and isinstance(exc, http.client.RemoteDisconnected):
                # closed without a single response byte: not processed
                continue
            raise
        if resp.will_close:
            conn.close()
        else:
            put_conn(conn)
        return resp.status, payload
