# Build/test entry points (reference: makefile — build, lint, test,
# integration tiers).

PYTHON ?= python

.PHONY: all build test integration bench lint clean

all: build test

build:
	$(MAKE) -C native

test:
	$(PYTHON) -m pytest tests/ -q

# the integration-grade scenarios only (real CLI, real processes)
integration: build
	$(PYTHON) -m pytest tests/test_integration.py tests/test_app.py -q

bench:
	$(PYTHON) bench.py

lint:
	$(PYTHON) -m compileall -q containerpilot_tpu

clean:
	$(MAKE) -C native clean
	rm -rf bin __pycache__ */__pycache__
