# Build/test entry points (reference: makefile — build, lint, test,
# integration tiers).

PYTHON ?= python

.PHONY: all build test test-fast test-workload integration fleet-smoke trace-smoke chaos chaos-smoke bench bench-host bench-gateway bench-reuse bench-goodput bench-coldstart bench-disagg bench-migrate lint lint-baseline lint-diff clean image

all: build test

build: bin/cpsup

bin/cpsup: native/sup.cpp
	$(MAKE) -C native cpsup
	mkdir -p bin
	cp native/cpsup bin/cpsup

# the tier-1 suite: everything except slow-marked chaos marathons
# (`make chaos` runs those; the tier-1 wall-time cap stays honest)
test:
	$(PYTHON) -m pytest tests/ -q -m 'not slow'

# supervisor tier only (~2 min): all host-side packages, no JAX compiles
test-fast:
	$(PYTHON) -m pytest tests/ -q -m supervisor

# the JAX models/ops/parallel tier (dominates full-suite wall time)
test-workload:
	$(PYTHON) -m pytest tests/ -q -m workload

# the integration-grade scenarios only (real CLI, real processes)
integration: build
	$(PYTHON) -m pytest tests/test_integration.py tests/test_app.py -q

# the inference-fleet scenarios (gateway routing units + the
# two-replica drain-mid-traffic integration test) on the CPU backend
fleet-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_fleet.py -q

# cross-hop tracing proof on a live 2-replica fleet: a buffered and
# an SSE request over cp-mux/1, each stitched (gateway + replica
# spans under one trace id) with non-overlapping stage accounting
# (docs/90-observability.md)
trace-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/trace_smoke.py

# trace-driven load + fault injection against a real fleet, scored on
# SLO-goodput (docs/80-chaos.md). chaos-smoke: the quick seeded
# scenarios (the same invariants tier-1 gates on — including the
# burst suite: burst_10x admission shedding and the autoscaled
# kill-under-burst) with the JSON goodput report; chaos: the full
# registry including the slow-marked compound marathons, plus the
# chaos test module end to end.
chaos-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m containerpilot_tpu.chaos \
		--suite quick --json chaos-report.json
chaos:
	JAX_PLATFORMS=cpu $(PYTHON) -m containerpilot_tpu.chaos \
		--suite full --json chaos-report.json
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_chaos.py -q

bench:
	$(PYTHON) bench.py

# the decode loop's host-overhead + dispatch-count story on this box:
# legacy vs device-resident engine per-round host ms, plus the fused
# multi-round sweep (K in {1,4,8} rounds per dispatch) with
# dispatches/token per K; meets_target pins overhead <= 0.5x legacy
# AND K=8 dispatches/token <= 0.3x K=1
bench-host:
	JAX_PLATFORMS=cpu $(PYTHON) -c "import json, bench; \
		print(json.dumps(bench.host_overhead_bench(), indent=2))"

# the gateway hop's mux-vs-pooled-vs-per-dial cost on this box, plus
# the concurrency-per-socket probe (host-side number; the CPU backend
# is representative)
bench-gateway:
	JAX_PLATFORMS=cpu $(PYTHON) -c "import json, bench; \
		print(json.dumps(bench.gateway_overhead_bench(), indent=2))"

# fleet-wide KV reuse vs the session-sticky baseline on the same
# multi-turn chat trace: tokens_reused/prompt token + shed-free TTFT
# p50 per arm; meets_target pins reuse strictly above baseline
bench-reuse:
	JAX_PLATFORMS=cpu $(PYTHON) -c "import json, bench; \
		print(json.dumps(bench.prefix_reuse_bench(), indent=2))"

# disaggregated prefill/decode vs the same-size mixed fleet (docs/60):
# decode-pool TPOT p99, per-transfer KV handoff cost, and per-role
# productive fraction; meets_target pins the decode tail strictly
# under mixed with handoffs completed and the decode pool's ledger
# fraction at or above the mixed arm's
bench-disagg:
	JAX_PLATFORMS=cpu $(PYTHON) -c "import json, bench; \
		print(json.dumps(bench.disagg_bench(), indent=2))"

# the drain-migration yardstick (docs/60 § drain runbook): next-turn
# latency for a session whose replica drains — warm ceiling vs
# migrated-over-the-wire vs the re-prefill baseline; meets_target
# pins migrated strictly below re-prefill and near warm, with bytes
# moved and zero counted fallbacks
bench-migrate:
	JAX_PLATFORMS=cpu $(PYTHON) -c "import json, bench; \
		print(json.dumps(bench.migration_bench(), indent=2))"

# the device-time ledger's accounting bench (docs/90): every replica
# wall-second attributed (|sum(stages) - uptime| <= 2%) plus the
# dispatches/token trajectory the megakernel work must drive down
bench-goodput:
	JAX_PLATFORMS=cpu $(PYTHON) -c "import json, bench; \
		print(json.dumps(bench.goodput_ledger_bench(), indent=2))"

# the cold-start collapse yardstick (docs/60 § cold-start runbook):
# cold launch vs standby promotion vs peer weight-transfer launch,
# TTFRT + per-stage ledger breakdown from /v1/goodput; meets_target
# pins promoted <= 0.25x cold
bench-coldstart:
	JAX_PLATFORMS=cpu $(PYTHON) -c "import json, bench; \
		print(json.dumps(bench.cold_start_bench(), indent=2))"

# cpcheck (AST invariant rules vs analysis/baseline.json) + compileall;
# see docs/70-static-analysis.md. Non-zero on any non-baselined finding.
lint:
	$(PYTHON) -m containerpilot_tpu.analysis

# regenerate the committed baseline (shrink it, never grow it);
# reports which entries were added/removed and why they went stale
lint-baseline:
	$(PYTHON) -m containerpilot_tpu.analysis --write-baseline

# cpcheck findings for files changed since $(SINCE) (default HEAD:
# staged + unstaged + untracked). Full call graph, findings filtered
# to the diff — a few-seconds loop, not a substitute for `make lint`.
lint-diff:
	scripts/cpcheck_diff.sh --since $(or $(SINCE),HEAD)

# release tarball (reference: makefile release target); VERSION expands
# lazily so only the release target pays the interpreter startup
VERSION = $(shell $(PYTHON) -c "from containerpilot_tpu.version import VERSION; print(VERSION)")
release: build
	mkdir -p release
	tar -czf release/containerpilot-tpu-$(VERSION).tar.gz \
		--exclude='__pycache__' --exclude='*.pyc' \
		--exclude='native/cpsup' \
		containerpilot_tpu bin/cpsup docs examples README.md \
		CHANGELOG.md pyproject.toml Makefile native

# container image with cpsup as the PID-1 entrypoint (reference:
# Dockerfile, makefile build-in-container targets)
IMAGE ?= containerpilot-tpu:latest
image:
	docker build -t $(IMAGE) .

clean:
	$(MAKE) -C native clean
	rm -rf bin release __pycache__ */__pycache__
