"""Supervised data-parallel training worker for the capstone e2e
(tests/test_capstone.py) — built entirely from framework pieces:

- rendezvous: parallel.distributed.initialize_from_catalog through a
  live catalog server (the supervisor's own daemon);
- training: models.transformer loss + parallel.make_optimizer under a
  multi-process pmap data-parallel step (1 CPU device per process;
  pmean spans the pod);
- checkpoint/resume: parallel.checkpoint save/restore, called in
  LOCKSTEP by every process on ONE SHARED directory (orbax is a
  global checkpointer under jax.distributed: the primary process
  writes the data, saves hold cross-process barriers, and a shared
  dir makes the resume-step decision identical everywhere — see
  parallel/checkpoint.py's module docstring);
- failure detection: parallel.StepWatchdog armed BEFORE restore with
  a startup grace — when a peer dies, the survivor blocks silently
  inside a restore barrier or a collective; the watchdog turns the
  hang into an exit the supervisor restarts, whether it strikes
  during startup or mid-run.

Fault injection: --crash-step N exits 1 after completing step N, once
(a sentinel file remembers the crash across the supervisor restart).
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_mesh(args, dog) -> int:
    """The production-path variant: a (data, model) mesh via
    parallel.train — init_train_state / make_train_step / sharded
    save+restore — so the capstone's crash/restart/resume story runs
    over cross-process TENSOR parallelism, not just pmap dp. The
    global batch is a pure function of the step on every process
    (make_array_from_callback slices it), so loss parity with a
    1-process --tp 1 baseline holds by construction."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding

    from containerpilot_tpu.models.transformer import TransformerConfig
    from containerpilot_tpu.parallel import (
        MeshPlan,
        abstract_train_state,
        init_train_state,
        latest_step,
        make_mesh,
        make_train_step,
        restore_checkpoint,
        save_checkpoint,
    )
    from containerpilot_tpu.parallel.sharding import batch_spec

    n_global = jax.device_count()
    assert n_global % args.tp == 0, (n_global, args.tp)
    plan = MeshPlan(data=n_global // args.tp, model=args.tp)
    mesh = make_mesh(jax.devices(), plan=plan)
    assert args.global_batch % plan.data == 0

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=128,
        max_seq_len=16, dtype=jnp.float32, flash_min_seq=0,
    )
    seq = cfg.max_seq_len
    lr = 1e-2

    rng = jax.random.PRNGKey(0)
    state = init_train_state(rng, cfg, mesh, learning_rate=lr)
    start = 0
    restored = restore_checkpoint(
        args.checkpoint_dir,
        abstract_train_state(rng, cfg, mesh, lr),
    )
    if restored is not None:
        state = restored
        start = latest_step(args.checkpoint_dir)
        print(f"worker {args.process_id}: resumed at step {start} "
              f"(mesh {plan.data}x{plan.model})", flush=True)

    step_fn = make_train_step(cfg, mesh, learning_rate=lr)
    batch_sharding = NamedSharding(mesh, batch_spec())

    def global_batch_for(step: int):
        rows = jax.device_get(
            jax.random.randint(
                jax.random.PRNGKey(10_000 + step),
                (args.global_batch, seq + 1), 0, cfg.vocab_size,
                jnp.int32,
            )
        )
        return jax.make_array_from_callback(
            rows.shape, batch_sharding, lambda idx: rows[idx]
        )

    digest_fn = jax.jit(
        lambda p: sum(
            jnp.sum(jnp.abs(x.astype(jnp.float32)))
            for x in jax.tree.leaves(p)
        )
    )

    final_loss = None
    for step in range(start, args.steps):
        state, loss = step_fn(state, global_batch_for(step))
        final_loss = float(jax.device_get(loss))
        dog.beat()
        if args.heartbeat_file:
            with open(args.heartbeat_file, "w") as fh:
                fh.write(str(step))
        # sharded save in lockstep on the pod's ONE shared directory
        save_checkpoint(args.checkpoint_dir, step + 1, state)
        dog.beat()
        print(f"worker {args.process_id}: step {step} loss "
              f"{final_loss:.5f}", flush=True)
        if step == args.crash_step and args.crash_sentinel:
            if not os.path.exists(args.crash_sentinel):
                with open(args.crash_sentinel, "w") as fh:
                    fh.write(str(step))
                print(f"worker {args.process_id}: injected crash after "
                      f"step {step}", flush=True)
                sys.stdout.flush()
                os._exit(1)
    digest = float(jax.device_get(digest_fn(state.params)))
    dog.stop()

    with open(args.out, "w") as fh:
        json.dump(
            {
                "process_id": args.process_id,
                "final_loss": final_loss,
                "params_digest": digest,
                "resumed_from": start,
            },
            fh,
        )
    print(f"worker {args.process_id}: done (loss {final_loss:.5f})",
          flush=True)
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--process-id", type=int, required=True)
    parser.add_argument("--num-processes", type=int, default=2)
    parser.add_argument("--catalog", default="")
    parser.add_argument("--coordinator-port", type=int, default=0)
    parser.add_argument("--steps", type=int, default=6)
    parser.add_argument("--global-batch", type=int, default=8)
    parser.add_argument("--checkpoint-dir", required=True)
    parser.add_argument("--out", required=True)
    parser.add_argument("--crash-step", type=int, default=-1)
    parser.add_argument("--crash-sentinel", default="")
    parser.add_argument("--step-timeout", type=float, default=30.0)
    parser.add_argument("--startup-timeout", type=float, default=150.0)
    parser.add_argument("--heartbeat-file", default="")
    parser.add_argument("--tp", type=int, default=0,
                        help="tensor-parallel axis size: > 0 switches "
                        "from the pmap data-parallel path to the "
                        "production mesh path (parallel.train: "
                        "make_mesh + init_train_state + "
                        "make_train_step + sharded checkpointing) on "
                        "a (devices/tp, tp) dp x tp mesh — tensor "
                        "parallelism then crosses process boundaries")
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from containerpilot_tpu.workload.modelcfg import (
        enable_compile_cache,
    )

    # honors CONTAINERPILOT_COMPILE_CACHE exactly like the real
    # workload CLIs: a reincarnated worker re-warms from cached
    # executables, which is both the feature's purpose and what keeps
    # the crash-resume capstones' restart windows short
    enable_compile_cache()

    from containerpilot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
        loss_fn,
    )
    from containerpilot_tpu.parallel import (
        StepWatchdog,
        latest_step,
        make_optimizer,
        restore_checkpoint,
        save_checkpoint,
    )

    # a reincarnation that finds training already finished must NOT
    # rendezvous (its peers may be done and gone); report and exit
    done_before = latest_step(args.checkpoint_dir)
    if done_before is not None and done_before >= args.steps:
        print(f"worker {args.process_id}: already complete "
              f"(step {done_before})", flush=True)
        return 0

    if args.num_processes > 1:
        from containerpilot_tpu.discovery.consul import ConsulBackend
        from containerpilot_tpu.parallel import initialize_from_catalog

        initialize_from_catalog(
            ConsulBackend(address=args.catalog),
            args.process_id,
            args.num_processes,
            coordinator_port=args.coordinator_port,
            advertise_address="127.0.0.1",
            timeout=180,
            poll_interval=0.2,
        )

    # armed over the WHOLE startup window (restore barriers + first
    # compile-bearing step, where a dead peer wedges us just as
    # silently as mid-run) with a generous grace; each beat tightens
    # the deadline to the steady-state step budget
    dog = StepWatchdog(args.step_timeout).start(
        grace_s=max(args.startup_timeout, args.step_timeout)
    )

    if args.tp > 0:
        return run_mesh(args, dog)

    n_global = jax.device_count()
    n_local = jax.local_device_count()
    assert args.global_batch % n_global == 0
    per_dev = args.global_batch // n_global

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=16, dtype=jnp.float32, flash_min_seq=0,
    )
    seq = cfg.max_seq_len

    params = init_params(jax.random.PRNGKey(0), cfg)
    optimizer = make_optimizer(1e-2)
    opt_state = optimizer.init(params)
    host_state = {
        "params": jax.device_get(params),
        "opt_state": jax.device_get(opt_state),
    }

    start = 0
    restored = restore_checkpoint(args.checkpoint_dir, host_state)
    if restored is not None:
        host_state = restored
        start = latest_step(args.checkpoint_dir)
        print(f"worker {args.process_id}: resumed at step {start}",
              flush=True)

    import optax

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, cfg)
        )(params)
        grads = jax.lax.pmean(grads, "b")
        loss = jax.lax.pmean(loss, "b")
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    pstep = jax.pmap(train_step, axis_name="b")

    def replicate(tree):
        return jax.tree.map(
            lambda x: jnp.broadcast_to(
                jnp.asarray(x), (n_local,) + jnp.asarray(x).shape
            ),
            tree,
        )

    r_params = replicate(host_state["params"])
    r_opt = replicate(host_state["opt_state"])

    def global_batch_for(step: int) -> np.ndarray:
        # every process derives the IDENTICAL global batch, then takes
        # its device rows — data parity with the 1-process baseline by
        # construction
        rows = jax.device_get(
            jax.random.randint(
                jax.random.PRNGKey(10_000 + step),
                (args.global_batch, seq + 1), 0, cfg.vocab_size,
                jnp.int32,
            )
        )
        first = args.process_id * n_local * per_dev
        local = rows[first:first + n_local * per_dev]
        return local.reshape(n_local, per_dev, seq + 1)

    def progress_beat() -> None:
        # the externally visible twin of dog.beat(): the supervisor's
        # health exec checks this file's freshness, so stalled-or-dead
        # training goes catalog-critical by TTL expiry (the
        # reference's health semantics) while the in-process watchdog
        # handles the exit
        if args.heartbeat_file:
            with open(args.heartbeat_file, "w") as fh:
                fh.write(str(step))

    final_loss = None
    for step in range(start, args.steps):
        r_params, r_opt, loss = pstep(
            r_params, r_opt, jnp.asarray(global_batch_for(step))
        )
        final_loss = float(jax.device_get(loss)[0])
        dog.beat()
        progress_beat()
        host_state = {
            "params": jax.device_get(
                jax.tree.map(lambda x: x[0], r_params)
            ),
            "opt_state": jax.device_get(
                jax.tree.map(lambda x: x[0], r_opt)
            ),
        }
        # EVERY process saves in lockstep on the pod's ONE shared
        # directory: orbax's barrier is global and the primary process
        # writes the data (module docstring, parallel/checkpoint.py)
        save_checkpoint(args.checkpoint_dir, step + 1, host_state)
        dog.beat()
        print(f"worker {args.process_id}: step {step} loss "
              f"{final_loss:.5f}", flush=True)
        if step == args.crash_step and args.crash_sentinel:
            if not os.path.exists(args.crash_sentinel):
                with open(args.crash_sentinel, "w") as fh:
                    fh.write(str(step))
                print(f"worker {args.process_id}: injected crash after "
                      f"step {step}", flush=True)
                sys.stdout.flush()
                os._exit(1)
    dog.stop()

    digest = float(
        sum(
            np.abs(np.asarray(x, np.float64)).sum()
            for x in jax.tree.leaves(host_state["params"])
        )
    )
    with open(args.out, "w") as fh:
        json.dump(
            {
                "process_id": args.process_id,
                "final_loss": final_loss,
                "params_digest": digest,
                "resumed_from": start,
            },
            fh,
        )
    print(f"worker {args.process_id}: done (loss {final_loss:.5f})",
          flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
