"""Logging config tests (reference: config/logger/logging_test.go):
formats, outputs, level filtering, and validation."""
import json
import logging

import pytest

from containerpilot_tpu.config.logger import LogConfig, LogConfigError


@pytest.fixture
def cp_logger():
    return logging.getLogger("containerpilot")


def test_defaults():
    cfg = LogConfig(None)
    assert (cfg.level, cfg.format, cfg.output) == ("INFO", "default", "stdout")


@pytest.mark.parametrize(
    "raw",
    [
        {"level": "SOMETIMES"},
        {"format": "xml"},
        {"bogus": 1},
    ],
)
def test_invalid_config_rejected(raw):
    with pytest.raises(LogConfigError):
        LogConfig(raw)


def test_json_format_to_file(tmp_path, cp_logger):
    log_file = tmp_path / "cp.json.log"
    LogConfig({"level": "INFO", "format": "json", "output": str(log_file)}).init()
    cp_logger.info("hello %s", "world", extra={"job": "j1", "pid": 42})
    cp_logger.debug("filtered out")
    for handler in cp_logger.handlers:
        handler.flush()
    lines = log_file.read_text().strip().splitlines()
    assert len(lines) == 1
    entry = json.loads(lines[0])
    assert entry["msg"] == "hello world"
    assert entry["level"] == "info"
    assert entry["job"] == "j1" and entry["pid"] == 42


def test_default_format_includes_fields(tmp_path, cp_logger):
    log_file = tmp_path / "cp.log"
    LogConfig({"level": "DEBUG", "output": str(log_file)}).init()
    cp_logger.debug("tick", extra={"check": "check.web"})
    for handler in cp_logger.handlers:
        handler.flush()
    line = log_file.read_text()
    assert "[DEBUG]" in line and "check=check.web" in line and "tick" in line


def test_text_format(tmp_path, cp_logger):
    log_file = tmp_path / "t.log"
    LogConfig({"format": "text", "output": str(log_file)}).init()
    cp_logger.warning("boom")
    for handler in cp_logger.handlers:
        handler.flush()
    assert "level=WARNING" in log_file.read_text()
