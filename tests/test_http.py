"""Keep-alive + cp-mux/1 conformance suites for the shared HTTP
server (utils/http.py).

Keep-alive: multiple requests per connection, the opt-outs
(``Connection: close``, HTTP/1.0), idle/cap reaping, the streaming
close-delimited contract, and no leaked handler state on abrupt
client disconnects. Every server in the tree (control plane,
telemetry, inference, gateway, catalog emulator) sits on this.

cp-mux/1 (the fleet's multiplexed transport): negotiated upgrade +
HTTP/1.1 fallback, stream interleaving on one connection, per-stream
backpressure windows, CANCEL mid-DATA with handler cleanup, protocol
errors closing the connection, abort() failing all streams, and the
per-connection stream cap refusing (not killing) the excess stream.
"""
import asyncio
import http.client
import json
import socket

from containerpilot_tpu.utils.http import (
    FRAME_END,
    FRAME_HEADERS,
    FRAME_PING,
    FRAME_PONG,
    HTTPServer,
    MUX_PROTOCOL,
    MUX_UPGRADE_PATH,
    Response,
    StreamingResponse,
    encode_frame,
    read_frame,
)


async def _start_server(**attrs):
    server = HTTPServer()
    for key, value in attrs.items():
        setattr(server, key, value)

    async def ok(req):
        return Response(200, b"hello\n")

    async def echo(req):
        return Response(200, req.body, content_type="application/json")

    async def stream(_req):
        async def gen():
            yield b"data: 1\n\n"
            yield b"data: 2\n\n"

        return StreamingResponse(gen())

    server.route("GET", "/ok", ok)
    server.route("POST", "/echo", echo)
    server.route("GET", "/stream", stream)
    await server.start_tcp("127.0.0.1", 0)
    return server


def _recv_all(sock, timeout=5.0):
    """Read until EOF (or timeout, which fails the test loudly)."""
    sock.settimeout(timeout)
    chunks = []
    while True:
        chunk = sock.recv(65536)
        if not chunk:
            return b"".join(chunks)
        chunks.append(chunk)


def test_sequential_requests_reuse_one_connection(run):
    """N requests on one http.client connection: one accept, N
    responses, each advertising keep-alive."""

    async def scenario():
        server = await _start_server()
        loop = asyncio.get_event_loop()

        def client():
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.bound_port, timeout=5
            )
            out = []
            for _ in range(5):
                conn.request("GET", "/ok")
                resp = conn.getresponse()
                out.append(
                    (resp.status, resp.read(), resp.getheader("Connection"))
                )
            conn.close()
            return out

        out = await loop.run_in_executor(None, client)
        counters = (server.connections_accepted, server.requests_served)
        await server.stop()
        return out, counters

    out, (conns, reqs) = run(scenario(), timeout=30)
    assert out == [(200, b"hello\n", "keep-alive")] * 5
    assert conns == 1 and reqs == 5


def test_connection_close_header_is_honored(run):
    """A request carrying ``Connection: close`` mid-keep-alive gets a
    closing response and EOF; earlier requests on the same connection
    were served keep-alive."""

    async def scenario():
        server = await _start_server()
        loop = asyncio.get_event_loop()

        def client():
            sock = socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=5
            )
            sock.sendall(b"GET /ok HTTP/1.1\r\nHost: x\r\n\r\n")
            first = b""
            while b"hello\n" not in first:
                first += sock.recv(65536)
            sock.sendall(
                b"GET /ok HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n"
            )
            rest = _recv_all(sock)
            sock.close()
            return first, rest

        first, rest = await loop.run_in_executor(None, client)
        counters = (server.connections_accepted, server.requests_served)
        await server.stop()
        return first, rest, counters

    first, rest, (conns, reqs) = run(scenario(), timeout=30)
    assert b"Connection: keep-alive" in first
    assert b"Connection: close" in rest and rest.endswith(b"hello\n")
    assert conns == 1 and reqs == 2


def test_http10_defaults_to_close(run):
    """HTTP/1.0 without ``Connection: keep-alive`` is one-shot."""

    async def scenario():
        server = await _start_server()
        loop = asyncio.get_event_loop()

        def client():
            sock = socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=5
            )
            sock.sendall(b"GET /ok HTTP/1.0\r\n\r\n")
            data = _recv_all(sock)
            sock.close()
            return data

        data = await loop.run_in_executor(None, client)
        await server.stop()
        return data

    data = run(scenario(), timeout=30)
    assert data.startswith(b"HTTP/1.1 200")
    assert b"Connection: close" in data


def test_idle_keepalive_connection_is_reaped(run):
    """A connection idle past KEEPALIVE_IDLE_TIMEOUT between requests
    is closed by the server (quietly — no 408: the client did nothing
    wrong)."""

    async def scenario():
        server = await _start_server(KEEPALIVE_IDLE_TIMEOUT=0.2)
        loop = asyncio.get_event_loop()

        def client():
            sock = socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=5
            )
            sock.sendall(b"GET /ok HTTP/1.1\r\nHost: x\r\n\r\n")
            first = b""
            while b"hello\n" not in first:
                first += sock.recv(65536)
            data = _recv_all(sock)  # EOF, with no error response
            sock.close()
            return data

        data = await loop.run_in_executor(None, client)
        tracked = len(server._conns)  # noqa: SLF001
        await server.stop()
        return data, tracked

    data, tracked = run(scenario(), timeout=30)
    assert data == b""  # reaped: EOF only, no 408 bytes
    assert tracked == 0  # the handler exited and untracked itself


def test_max_requests_cap_retires_the_connection(run):
    async def scenario():
        server = await _start_server(KEEPALIVE_MAX_REQUESTS=2)
        loop = asyncio.get_event_loop()

        def client():
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.bound_port, timeout=5
            )
            headers = []
            for _ in range(2):
                conn.request("GET", "/ok")
                resp = conn.getresponse()
                resp.read()
                headers.append(resp.getheader("Connection"))
            conn.close()
            return headers

        headers = await loop.run_in_executor(None, client)
        await server.stop()
        return headers

    headers = run(scenario(), timeout=30)
    assert headers == ["keep-alive", "close"]


def test_streaming_response_still_closes_the_connection(run):
    """StreamingResponse keeps its close-delimited contract: no
    Content-Length, ``Connection: close``, EOF ends the stream even
    when the request asked for keep-alive."""

    async def scenario():
        server = await _start_server()
        loop = asyncio.get_event_loop()

        def client():
            sock = socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=5
            )
            sock.sendall(
                b"GET /stream HTTP/1.1\r\nHost: x\r\n"
                b"Connection: keep-alive\r\n\r\n"
            )
            data = _recv_all(sock)
            sock.close()
            return data

        data = await loop.run_in_executor(None, client)
        await server.stop()
        return data

    data = run(scenario(), timeout=30)
    assert b"Connection: close" in data
    assert b"Content-Length" not in data
    assert data.endswith(b"data: 1\n\ndata: 2\n\n")


def test_client_disconnect_mid_keepalive_frees_the_handler(run):
    """A client that vanishes between keep-alive requests must not
    leave its handler coroutine parked forever: the read sees EOF and
    the connection untracks itself."""

    async def scenario():
        server = await _start_server()
        loop = asyncio.get_event_loop()

        def client():
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.bound_port, timeout=5
            )
            conn.request("GET", "/ok")
            conn.getresponse().read()
            conn.close()  # abrupt: no Connection: close handshake

        await loop.run_in_executor(None, client)
        for _ in range(100):
            if not server._conns:  # noqa: SLF001
                break
            await asyncio.sleep(0.02)
        tracked = len(server._conns)  # noqa: SLF001
        await server.stop()
        return tracked

    assert run(scenario(), timeout=30) == 0


def test_protocol_error_closes_the_connection(run):
    """After a malformed request the framing is untrusted: 400 is
    answered with ``Connection: close`` and the socket ends, even
    mid-keep-alive."""

    async def scenario():
        server = await _start_server()
        loop = asyncio.get_event_loop()

        def client():
            sock = socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=5
            )
            sock.sendall(b"GET /ok HTTP/1.1\r\nHost: x\r\n\r\n")
            first = b""
            while b"hello\n" not in first:
                first += sock.recv(65536)
            sock.sendall(
                b"GET /ok HTTP/1.1\r\nContent-Length: nope\r\n\r\n"
            )
            data = _recv_all(sock)
            sock.close()
            return data

        data = await loop.run_in_executor(None, client)
        await server.stop()
        return data

    data = run(scenario(), timeout=30)
    assert data.startswith(b"HTTP/1.1 400")
    assert b"Connection: close" in data


def test_pipelined_requests_are_both_answered(run):
    async def scenario():
        server = await _start_server()
        loop = asyncio.get_event_loop()

        def client():
            sock = socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=5
            )
            sock.sendall(
                b"GET /ok HTTP/1.1\r\nHost: x\r\n\r\n"
                b"GET /ok HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n"
            )
            data = _recv_all(sock)
            sock.close()
            return data

        data = await loop.run_in_executor(None, client)
        counters = (server.connections_accepted, server.requests_served)
        await server.stop()
        return data, counters

    data, (conns, reqs) = run(scenario(), timeout=30)
    assert data.count(b"hello\n") == 2
    assert conns == 1 and reqs == 2


def test_stop_force_closes_idle_keepalive_connections(run):
    """stop() must not leave parked keep-alive handlers behind (nor
    hang on them): lingering idle connections are closed."""

    async def scenario():
        server = await _start_server()
        loop = asyncio.get_event_loop()

        sock = await loop.run_in_executor(
            None,
            lambda: socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=5
            ),
        )

        def request():
            sock.sendall(b"GET /ok HTTP/1.1\r\nHost: x\r\n\r\n")
            data = b""
            while b"hello\n" not in data:
                data += sock.recv(65536)
            return data

        await loop.run_in_executor(None, request)
        await server.stop()  # idle keep-alive connection still open
        data = await loop.run_in_executor(None, lambda: _recv_all(sock))
        sock.close()
        return data

    assert run(scenario(), timeout=30) == b""  # EOF promptly, no hang


def test_oversized_request_line_gets_400_not_task_crash(run):
    """A request line overrunning the StreamReader limit (64KB, no
    newline) raises ValueError inside readline — the client must get
    a 400 + close, never a silent drop via an unhandled task
    exception."""

    async def scenario():
        server = await _start_server()
        loop = asyncio.get_event_loop()

        def client():
            sock = socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=5
            )
            sock.sendall(b"GET /" + b"a" * 70000 + b" HTTP/1.1")
            data = _recv_all(sock)
            sock.close()
            return data

        data = await loop.run_in_executor(None, client)
        await server.stop()
        return data

    data = run(scenario(), timeout=30)
    assert data.startswith(b"HTTP/1.1 400")
    assert b"Connection: close" in data


# -- cp-mux/1 conformance (the fleet's multiplexed transport) -----------


async def _mux_upgrade(port):
    """Raw-socket upgrade handshake; returns (reader, writer, head)."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(
        f"GET {MUX_UPGRADE_PATH} HTTP/1.1\r\nHost: x\r\n"
        f"Connection: Upgrade\r\nUpgrade: {MUX_PROTOCOL}\r\n\r\n".encode()
    )
    await writer.drain()
    head = await reader.readuntil(b"\r\n\r\n")
    return reader, writer, head


def _head_frame(sid, method="GET", path="/ok"):
    return encode_frame(
        FRAME_HEADERS, sid,
        json.dumps({"method": method, "path": path, "headers": {}}).encode(),
    )


async def _mux_connect(port, replica_id="r1"):
    """A MuxConnection (the real fleet client) against a test server."""
    from containerpilot_tpu.fleet.gateway import Replica
    from containerpilot_tpu.fleet.pool import ConnectionPool

    pool = ConnectionPool(mux=True)
    conn = await pool.acquire_mux(
        Replica(replica_id, "127.0.0.1", port), 5.0
    )
    assert conn is not None
    return pool, conn


def test_mux_upgrade_negotiation_and_ping(run):
    """The upgrade earns a 101 and the connection speaks frames:
    PING round-trips as PONG with the payload echoed."""

    async def scenario():
        server = await _start_server()
        reader, writer, head = await _mux_upgrade(server.bound_port)
        writer.write(encode_frame(FRAME_PING, 0, b"nonce-1"))
        await writer.drain()
        pong = await read_frame(reader)
        counters = (server.mux_connections, server.connections_accepted)
        writer.close()
        await server.stop()
        return head, pong, counters

    head, pong, (mux_conns, conns) = run(scenario(), timeout=30)
    assert head.startswith(b"HTTP/1.1 101 ")
    assert b"Upgrade: cp-mux/1" in head
    assert pong == (FRAME_PONG, 0, b"nonce-1")
    assert mux_conns == 1 and conns == 1


def test_mux_streams_interleave_on_one_connection(run):
    """A fast stream opened AFTER a slow one completes first — the
    whole point of multiplexing: responses interleave per stream, on
    one socket, instead of queueing behind the slowest request."""

    async def scenario():
        server = await _start_server()
        gate = asyncio.Event()

        async def slow(_req):
            await gate.wait()
            return Response(200, b"slow\n")

        server.route("GET", "/slow", slow)
        pool, conn = await _mux_connect(server.bound_port)
        s_slow = await conn.open_stream("GET", "/slow")
        s_fast = await conn.open_stream("GET", "/ok")
        fast_status, _ = await s_fast.response_head(5.0)
        fast_body = await s_fast.read_body(5.0, 1 << 20)
        slow_still_inflight = not s_slow.ended
        gate.set()
        slow_status, _ = await s_slow.response_head(5.0)
        slow_body = await s_slow.read_body(5.0, 1 << 20)
        counters = (
            server.connections_accepted, server.mux_streams_served,
        )
        pool.close_all()
        await server.stop()
        return (
            fast_status, fast_body, slow_still_inflight,
            slow_status, slow_body, counters,
        )

    fast_status, fast_body, inflight, slow_status, slow_body, c = run(
        scenario(), timeout=30
    )
    assert fast_status == 200 and fast_body == b"hello\n"
    assert inflight  # the slow stream had not finished first
    assert slow_status == 200 and slow_body == b"slow\n"
    assert c == (1, 2)  # one socket, two streams


def test_mux_per_stream_backpressure(run):
    """A stream whose consumer stops granting WINDOW credit stalls
    ALONE at its window: the co-resident stream still completes, and
    draining the stalled stream releases the rest."""

    async def scenario():
        server = await _start_server()
        big = b"x" * (200 * 1024)  # > MUX_INITIAL_WINDOW (64KB)

        async def bulk(_req):
            async def gen():
                yield big

            return StreamingResponse(gen(), content_type="text/plain")

        server.route("GET", "/bulk", bulk)
        pool, conn = await _mux_connect(server.bound_port)
        s_bulk = await conn.open_stream("GET", "/bulk")
        await s_bulk.response_head(5.0)
        first = await s_bulk.read_chunk(5.0)  # grants a little credit
        # stop consuming /bulk: the server's writer for that stream
        # must park on its window while /ok flows freely
        s_ok = await conn.open_stream("GET", "/ok")
        ok_status, _ = await s_ok.response_head(5.0)
        ok_body = await s_ok.read_body(5.0, 1 << 20)
        # now drain the parked stream to completion
        rest = first
        while True:
            chunk = await s_bulk.read_chunk(5.0)
            if not chunk:
                break
            rest += chunk
        pool.close_all()
        await server.stop()
        return ok_status, ok_body, rest

    ok_status, ok_body, rest = run(scenario(), timeout=30)
    assert ok_status == 200 and ok_body == b"hello\n"
    assert rest == b"x" * (200 * 1024)  # nothing lost to the stall


def test_mux_cancel_mid_stream_runs_handler_cleanup(run):
    """CANCEL mid-DATA: the streaming handler's close callback and
    generator-finally both run, the stream id is freed, and the
    CONNECTION keeps serving other streams."""

    async def scenario():
        server = await _start_server()
        cleaned = {"finally": False, "close": False}

        async def endless(_req):
            async def gen():
                try:
                    while True:
                        yield b"tick\n"
                        await asyncio.sleep(0.01)
                finally:
                    cleaned["finally"] = True

            return StreamingResponse(
                gen(), close=lambda: cleaned.__setitem__("close", True)
            )

        server.route("GET", "/endless", endless)
        pool, conn = await _mux_connect(server.bound_port)
        stream = await conn.open_stream("GET", "/endless")
        await stream.response_head(5.0)
        assert await stream.read_chunk(5.0)  # mid-DATA
        assert stream.cancel()
        for _ in range(100):
            if cleaned["finally"] and cleaned["close"]:
                break
            await asyncio.sleep(0.02)
        # the shared connection survived the cancel
        s_ok = await conn.open_stream("GET", "/ok")
        ok_status, _ = await s_ok.response_head(5.0)
        await s_ok.read_body(5.0, 1 << 20)
        alive = await conn.ping()
        counters = server.connections_accepted
        pool.close_all()
        await server.stop()
        return dict(cleaned), ok_status, alive, counters

    cleaned, ok_status, alive, conns = run(scenario(), timeout=30)
    assert cleaned == {"finally": True, "close": True}
    assert ok_status == 200 and alive
    assert conns == 1


def test_mux_protocol_error_closes_the_connection(run):
    """Garbage framing (unknown frame type) kills the whole
    connection — its framing can no longer be trusted, exactly like a
    400 on the HTTP/1.1 path — and in-flight streams see EOF."""

    async def scenario():
        server = await _start_server()
        reader, writer, _ = await _mux_upgrade(server.bound_port)
        writer.write(_head_frame(1) + encode_frame(FRAME_END, 1))
        resp_head = await read_frame(reader)
        writer.write(b"\x00\x00\x00\x04\xff\x00\x00\x00\x01zzzz")
        await writer.drain()
        leftover = await reader.read()  # EOF after any buffered frames
        writer.close()
        await server.stop()
        return resp_head[0], leftover

    ftype, leftover = run(scenario(), timeout=30)
    assert ftype == FRAME_HEADERS
    # whatever was in flight, the server closed the connection: the
    # read drained to EOF instead of hanging on more frames
    assert leftover is not None


def test_mux_abort_rsts_all_streams(run):
    """abort() (SIGKILL semantics) fails every in-flight stream
    promptly and exactly once — each failure arms the caller's retry,
    none hangs."""
    from containerpilot_tpu.fleet.pool import UpstreamError

    async def scenario():
        server = await _start_server()
        gate = asyncio.Event()

        async def stuck(_req):
            await gate.wait()
            return Response(200, b"never\n")

        server.route("GET", "/stuck", stuck)
        pool, conn = await _mux_connect(server.bound_port)
        s1 = await conn.open_stream("GET", "/stuck")
        s2 = await conn.open_stream("GET", "/stuck")
        await asyncio.sleep(0.05)
        await server.abort()
        errors = []
        for stream in (s1, s2):
            try:
                await stream.response_head(5.0)
            except UpstreamError as exc:
                errors.append(exc)
        dead = conn.dead
        pool.close_all()
        return len(errors), dead

    n_errors, dead = run(scenario(), timeout=30)
    assert n_errors == 2 and dead


def test_mux_negotiation_fallback_to_http11(run):
    """A server with mux disabled answers the upgrade through the
    route table (404, keep-alive): acquire_mux reports 'no mux' AND
    pools the probe socket, so the classic path rides the very same
    connection — zero wasted dials."""
    from containerpilot_tpu.fleet.gateway import Replica
    from containerpilot_tpu.fleet.pool import ConnectionPool

    async def scenario():
        server = await _start_server(mux_enabled=False)
        pool = ConnectionPool(mux=True)
        replica = Replica("r1", "127.0.0.1", server.bound_port)
        conn = await pool.acquire_mux(replica, 5.0)
        idle = pool.idle_count("r1")
        stats = pool.mux_stats("r1")
        # the classic path reuses the probe's socket
        pooled = await pool.acquire(replica, 5.0)
        counters = server.connections_accepted
        pool.release(pooled)
        pool.close_all()
        await server.stop()
        return conn, idle, stats, counters

    conn, idle, stats, conns = run(scenario(), timeout=30)
    assert conn is None
    assert idle == 1 and stats["unsupported"] is True
    assert conns == 1  # probe socket reused, not burned


def test_plain_http_clients_unchanged_on_mux_server(run):
    """A client that never sends the upgrade gets byte-identical
    HTTP/1.1 from a mux-enabled server: keep-alive headers, framing,
    and counters exactly as the keep-alive suite pins them."""

    async def scenario():
        server = await _start_server()  # mux_enabled defaults True
        loop = asyncio.get_event_loop()

        def client():
            sock = socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=5
            )
            sock.sendall(b"GET /ok HTTP/1.1\r\nHost: x\r\n\r\n")
            first = b""
            while b"hello\n" not in first:
                first += sock.recv(65536)
            sock.close()
            return first

        data = await loop.run_in_executor(None, client)
        counters = (server.mux_connections, server.mux_streams_served)
        await server.stop()
        return data, counters

    data, (mux_conns, mux_streams) = run(scenario(), timeout=30)
    assert data.startswith(b"HTTP/1.1 200 OK\r\n")
    assert b"Connection: keep-alive" in data
    assert b"cp-mux" not in data  # no mux artifacts leak
    assert mux_conns == 0 and mux_streams == 0


def test_mux_stream_cap_refuses_excess_stream_with_503(run):
    """The stream cap refuses the EXCESS stream with a per-stream
    503 — retryable by the gateway — while the connection and its
    live streams are untouched."""

    async def scenario():
        server = await _start_server(MUX_MAX_STREAMS=1)
        gate = asyncio.Event()

        async def stuck(_req):
            await gate.wait()
            return Response(200, b"first\n")

        server.route("GET", "/stuck", stuck)
        pool, conn = await _mux_connect(server.bound_port)
        s1 = await conn.open_stream("GET", "/stuck")
        s2 = await conn.open_stream("GET", "/ok")
        refused_status, refused_headers = await s2.response_head(5.0)
        await s2.read_body(5.0, 1 << 20)
        gate.set()
        ok_status, _ = await s1.response_head(5.0)
        body = await s1.read_body(5.0, 1 << 20)
        pool.close_all()
        await server.stop()
        return refused_status, refused_headers, ok_status, body

    refused, headers, ok_status, body = run(scenario(), timeout=30)
    assert refused == 503 and headers.get("retry-after")
    assert ok_status == 200 and body == b"first\n"
