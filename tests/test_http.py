"""Keep-alive conformance suite for the shared HTTP server
(utils/http.py): multiple requests per connection, the opt-outs
(``Connection: close``, HTTP/1.0), idle/cap reaping, the streaming
close-delimited contract, and no leaked handler state on abrupt
client disconnects. Every server in the tree (control plane,
telemetry, inference, gateway, catalog emulator) sits on this.
"""
import asyncio
import http.client
import socket

from containerpilot_tpu.utils.http import (
    HTTPServer,
    Response,
    StreamingResponse,
)


async def _start_server(**attrs):
    server = HTTPServer()
    for key, value in attrs.items():
        setattr(server, key, value)

    async def ok(req):
        return Response(200, b"hello\n")

    async def echo(req):
        return Response(200, req.body, content_type="application/json")

    async def stream(_req):
        async def gen():
            yield b"data: 1\n\n"
            yield b"data: 2\n\n"

        return StreamingResponse(gen())

    server.route("GET", "/ok", ok)
    server.route("POST", "/echo", echo)
    server.route("GET", "/stream", stream)
    await server.start_tcp("127.0.0.1", 0)
    return server


def _recv_all(sock, timeout=5.0):
    """Read until EOF (or timeout, which fails the test loudly)."""
    sock.settimeout(timeout)
    chunks = []
    while True:
        chunk = sock.recv(65536)
        if not chunk:
            return b"".join(chunks)
        chunks.append(chunk)


def test_sequential_requests_reuse_one_connection(run):
    """N requests on one http.client connection: one accept, N
    responses, each advertising keep-alive."""

    async def scenario():
        server = await _start_server()
        loop = asyncio.get_event_loop()

        def client():
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.bound_port, timeout=5
            )
            out = []
            for _ in range(5):
                conn.request("GET", "/ok")
                resp = conn.getresponse()
                out.append(
                    (resp.status, resp.read(), resp.getheader("Connection"))
                )
            conn.close()
            return out

        out = await loop.run_in_executor(None, client)
        counters = (server.connections_accepted, server.requests_served)
        await server.stop()
        return out, counters

    out, (conns, reqs) = run(scenario(), timeout=30)
    assert out == [(200, b"hello\n", "keep-alive")] * 5
    assert conns == 1 and reqs == 5


def test_connection_close_header_is_honored(run):
    """A request carrying ``Connection: close`` mid-keep-alive gets a
    closing response and EOF; earlier requests on the same connection
    were served keep-alive."""

    async def scenario():
        server = await _start_server()
        loop = asyncio.get_event_loop()

        def client():
            sock = socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=5
            )
            sock.sendall(b"GET /ok HTTP/1.1\r\nHost: x\r\n\r\n")
            first = b""
            while b"hello\n" not in first:
                first += sock.recv(65536)
            sock.sendall(
                b"GET /ok HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n"
            )
            rest = _recv_all(sock)
            sock.close()
            return first, rest

        first, rest = await loop.run_in_executor(None, client)
        counters = (server.connections_accepted, server.requests_served)
        await server.stop()
        return first, rest, counters

    first, rest, (conns, reqs) = run(scenario(), timeout=30)
    assert b"Connection: keep-alive" in first
    assert b"Connection: close" in rest and rest.endswith(b"hello\n")
    assert conns == 1 and reqs == 2


def test_http10_defaults_to_close(run):
    """HTTP/1.0 without ``Connection: keep-alive`` is one-shot."""

    async def scenario():
        server = await _start_server()
        loop = asyncio.get_event_loop()

        def client():
            sock = socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=5
            )
            sock.sendall(b"GET /ok HTTP/1.0\r\n\r\n")
            data = _recv_all(sock)
            sock.close()
            return data

        data = await loop.run_in_executor(None, client)
        await server.stop()
        return data

    data = run(scenario(), timeout=30)
    assert data.startswith(b"HTTP/1.1 200")
    assert b"Connection: close" in data


def test_idle_keepalive_connection_is_reaped(run):
    """A connection idle past KEEPALIVE_IDLE_TIMEOUT between requests
    is closed by the server (quietly — no 408: the client did nothing
    wrong)."""

    async def scenario():
        server = await _start_server(KEEPALIVE_IDLE_TIMEOUT=0.2)
        loop = asyncio.get_event_loop()

        def client():
            sock = socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=5
            )
            sock.sendall(b"GET /ok HTTP/1.1\r\nHost: x\r\n\r\n")
            first = b""
            while b"hello\n" not in first:
                first += sock.recv(65536)
            data = _recv_all(sock)  # EOF, with no error response
            sock.close()
            return data

        data = await loop.run_in_executor(None, client)
        tracked = len(server._conns)  # noqa: SLF001
        await server.stop()
        return data, tracked

    data, tracked = run(scenario(), timeout=30)
    assert data == b""  # reaped: EOF only, no 408 bytes
    assert tracked == 0  # the handler exited and untracked itself


def test_max_requests_cap_retires_the_connection(run):
    async def scenario():
        server = await _start_server(KEEPALIVE_MAX_REQUESTS=2)
        loop = asyncio.get_event_loop()

        def client():
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.bound_port, timeout=5
            )
            headers = []
            for _ in range(2):
                conn.request("GET", "/ok")
                resp = conn.getresponse()
                resp.read()
                headers.append(resp.getheader("Connection"))
            conn.close()
            return headers

        headers = await loop.run_in_executor(None, client)
        await server.stop()
        return headers

    headers = run(scenario(), timeout=30)
    assert headers == ["keep-alive", "close"]


def test_streaming_response_still_closes_the_connection(run):
    """StreamingResponse keeps its close-delimited contract: no
    Content-Length, ``Connection: close``, EOF ends the stream even
    when the request asked for keep-alive."""

    async def scenario():
        server = await _start_server()
        loop = asyncio.get_event_loop()

        def client():
            sock = socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=5
            )
            sock.sendall(
                b"GET /stream HTTP/1.1\r\nHost: x\r\n"
                b"Connection: keep-alive\r\n\r\n"
            )
            data = _recv_all(sock)
            sock.close()
            return data

        data = await loop.run_in_executor(None, client)
        await server.stop()
        return data

    data = run(scenario(), timeout=30)
    assert b"Connection: close" in data
    assert b"Content-Length" not in data
    assert data.endswith(b"data: 1\n\ndata: 2\n\n")


def test_client_disconnect_mid_keepalive_frees_the_handler(run):
    """A client that vanishes between keep-alive requests must not
    leave its handler coroutine parked forever: the read sees EOF and
    the connection untracks itself."""

    async def scenario():
        server = await _start_server()
        loop = asyncio.get_event_loop()

        def client():
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.bound_port, timeout=5
            )
            conn.request("GET", "/ok")
            conn.getresponse().read()
            conn.close()  # abrupt: no Connection: close handshake

        await loop.run_in_executor(None, client)
        for _ in range(100):
            if not server._conns:  # noqa: SLF001
                break
            await asyncio.sleep(0.02)
        tracked = len(server._conns)  # noqa: SLF001
        await server.stop()
        return tracked

    assert run(scenario(), timeout=30) == 0


def test_protocol_error_closes_the_connection(run):
    """After a malformed request the framing is untrusted: 400 is
    answered with ``Connection: close`` and the socket ends, even
    mid-keep-alive."""

    async def scenario():
        server = await _start_server()
        loop = asyncio.get_event_loop()

        def client():
            sock = socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=5
            )
            sock.sendall(b"GET /ok HTTP/1.1\r\nHost: x\r\n\r\n")
            first = b""
            while b"hello\n" not in first:
                first += sock.recv(65536)
            sock.sendall(
                b"GET /ok HTTP/1.1\r\nContent-Length: nope\r\n\r\n"
            )
            data = _recv_all(sock)
            sock.close()
            return data

        data = await loop.run_in_executor(None, client)
        await server.stop()
        return data

    data = run(scenario(), timeout=30)
    assert data.startswith(b"HTTP/1.1 400")
    assert b"Connection: close" in data


def test_pipelined_requests_are_both_answered(run):
    async def scenario():
        server = await _start_server()
        loop = asyncio.get_event_loop()

        def client():
            sock = socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=5
            )
            sock.sendall(
                b"GET /ok HTTP/1.1\r\nHost: x\r\n\r\n"
                b"GET /ok HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n"
            )
            data = _recv_all(sock)
            sock.close()
            return data

        data = await loop.run_in_executor(None, client)
        counters = (server.connections_accepted, server.requests_served)
        await server.stop()
        return data, counters

    data, (conns, reqs) = run(scenario(), timeout=30)
    assert data.count(b"hello\n") == 2
    assert conns == 1 and reqs == 2


def test_stop_force_closes_idle_keepalive_connections(run):
    """stop() must not leave parked keep-alive handlers behind (nor
    hang on them): lingering idle connections are closed."""

    async def scenario():
        server = await _start_server()
        loop = asyncio.get_event_loop()

        sock = await loop.run_in_executor(
            None,
            lambda: socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=5
            ),
        )

        def request():
            sock.sendall(b"GET /ok HTTP/1.1\r\nHost: x\r\n\r\n")
            data = b""
            while b"hello\n" not in data:
                data += sock.recv(65536)
            return data

        await loop.run_in_executor(None, request)
        await server.stop()  # idle keep-alive connection still open
        data = await loop.run_in_executor(None, lambda: _recv_all(sock))
        sock.close()
        return data

    assert run(scenario(), timeout=30) == b""  # EOF promptly, no hang


def test_oversized_request_line_gets_400_not_task_crash(run):
    """A request line overrunning the StreamReader limit (64KB, no
    newline) raises ValueError inside readline — the client must get
    a 400 + close, never a silent drop via an unhandled task
    exception."""

    async def scenario():
        server = await _start_server()
        loop = asyncio.get_event_loop()

        def client():
            sock = socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=5
            )
            sock.sendall(b"GET /" + b"a" * 70000 + b" HTTP/1.1")
            data = _recv_all(sock)
            sock.close()
            return data

        data = await loop.run_in_executor(None, client)
        await server.stop()
        return data

    data = run(scenario(), timeout=30)
    assert data.startswith(b"HTTP/1.1 400")
    assert b"Connection: close" in data
