"""Disaggregated prefill/decode fleet tests (docs/60 § disaggregated
serving): the kv handoff codec's byte parity and strictness, the spill
tier's host-side export/inject surface, phase-aware routing units
(preference, degradation, the dead-pin invalidation regression), the
tolerant heartbeat note parser with every field coexisting, the pool
autoscaler label — and the tier-1 integration scenario: a real
prefill+decode fleet behind the gateway whose handed-off generations
are byte-identical to a standalone mixed replica's, buffered AND SSE,
with a poisoned-chunk handoff degrading to a local prefill (never
serving corrupt KV) on the same fleet.
"""
import asyncio
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from containerpilot_tpu.discovery import FileCatalogBackend, NoopBackend
from containerpilot_tpu.fleet import FleetGateway, FleetMember
from containerpilot_tpu.fleet.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    FleetLoad,
)
from containerpilot_tpu.fleet.gateway import Replica
from containerpilot_tpu.kvtier.digest import prefix_fingerprint
from containerpilot_tpu.kvtier.handoff import (
    KVTransferError,
    encode_kv_manifest,
    kv_transfer_plan,
    rebuild_kv,
)
from containerpilot_tpu.kvtier.spill import HostSpillTier

def _counter(metric, label: str) -> float:
    return metric.labels(label)._value.get()  # noqa: SLF001


def _post(port, path, payload, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode(), dict(exc.headers)


def _wire_chunks(manifest, blobs):
    """Slice leaf blobs into the wire chunks the manifest names —
    what the export stream yields after the length-prefixed head."""
    return [
        blobs[spec["leaf"]][spec["offset"]:spec["offset"] + spec["len"]]
        for spec in manifest["chunks"]
    ]


# -- the self-describing KV codec (no servers, no JAX) -----------------


def test_kv_codec_roundtrip_byte_parity():
    """plan -> frame -> chunk -> rebuild is byte-exact: every leaf
    comes back with its dtype, shape, and bytes intact, containers
    keep their kinds (tuple stays tuple), and zero-length leaves
    survive the trip."""
    tree = {
        "layers": [
            {
                "k": np.arange(24, dtype=np.float32).reshape(2, 3, 4),
                "v": np.full((2, 3, 4), 0.5, dtype=np.float16),
            },
        ],
        "lens": (np.array([7, 9], dtype=np.int32),),
        "scalar": np.float64(3.5),
        "empty": np.zeros((0,), dtype=np.float32),
    }
    # tiny chunk size forces multi-chunk leaves, so reassembly from
    # pieces (not just one chunk per leaf) is what's being pinned
    manifest, blobs = kv_transfer_plan(tree, chunk_bytes=16)
    assert manifest["version"] == 1
    assert manifest["total_bytes"] == sum(len(b) for b in blobs)
    assert any(
        sum(1 for s in manifest["chunks"] if s["leaf"] == i) > 1
        for i in range(len(blobs))
    )
    head = encode_kv_manifest(manifest)
    assert int.from_bytes(head[:8], "big") == len(head) - 8
    assert json.loads(head[8:].decode()) == json.loads(
        json.dumps(manifest)
    )
    rebuilt = rebuild_kv(manifest, _wire_chunks(manifest, blobs))
    assert isinstance(rebuilt["layers"], list)
    assert isinstance(rebuilt["lens"], tuple)
    for path in ("k", "v"):
        orig = tree["layers"][0][path]
        back = rebuilt["layers"][0][path]
        assert back.dtype == orig.dtype and back.shape == orig.shape
        assert back.tobytes() == orig.tobytes()
    assert rebuilt["lens"][0].tobytes() == tree["lens"][0].tobytes()
    assert np.asarray(rebuilt["scalar"]).item() == 3.5
    assert rebuilt["empty"].shape == (0,)
    # determinism: a resumed stream's digests must match the first
    # attempt's manifest
    again, _ = kv_transfer_plan(tree, chunk_bytes=16)
    assert again == manifest


def test_kv_codec_refuses_malformed():
    """Structural disagreement is KVTransferError everywhere — the
    receiver falls back to a local prefill instead of guessing."""
    with pytest.raises(KVTransferError):
        kv_transfer_plan({1: np.zeros(2, dtype=np.float32)})
    manifest, blobs = kv_transfer_plan(
        {"a": np.arange(8, dtype=np.int32)}, chunk_bytes=16
    )
    chunks = _wire_chunks(manifest, blobs)
    with pytest.raises(KVTransferError):
        rebuild_kv(manifest, chunks[:-1])  # chunk count mismatch
    with pytest.raises(KVTransferError):
        rebuild_kv(manifest, [chunks[0][:-1]] + chunks[1:])  # short leaf
    with pytest.raises(KVTransferError):
        rebuild_kv({"skeleton": {"x": 0}}, [])  # missing tables
    for skeleton in ({"x": 99}, {"z": 1}, {"d": [1]}, "junk"):
        bad = json.loads(json.dumps(manifest))
        bad["skeleton"] = skeleton
        with pytest.raises(KVTransferError):
            rebuild_kv(bad, chunks)


def test_spill_put_host_peek_and_budget():
    """put_host injects an already-host-side entry with no device
    round-trip; peek reads it non-destructively for export; the byte
    budget refuses oversized entries and evicts LRU-first."""
    tier = HostSpillTier(1024)
    key = tuple(range(20))
    host = {"k": np.ones((4, 4), dtype=np.float32)}  # 64 bytes
    assert tier.put_host(key, host) == 64
    assert tier.bytes_used == 64 and tier.stats["spilled"] == 1
    # peek: the stored tree itself, still resident afterwards
    assert tier.peek(key) is host
    assert tier.peek(key) is host
    assert key in tier.candidates(prefix_fingerprint(key))
    # oversized: refused, counted, nothing stored
    big = {"k": np.zeros((64, 64), dtype=np.float32)}  # 16 KiB
    assert tier.put_host(tuple(range(100, 120)), big) == 0
    assert tier.stats["refused"] == 1 and len(tier) == 1
    # budget pressure evicts least-recently-used spilled entries
    half = {"k": np.zeros((8, 16), dtype=np.float32)}  # 512 bytes
    assert tier.put_host(tuple(range(200, 220)), half) == 512
    assert tier.put_host(tuple(range(300, 320)), half) == 512
    assert tier.stats["evicted"] >= 1 and tier.bytes_used <= 1024
    # take pops: readmitted once, gone after
    taken_key = tuple(range(300, 320))
    assert tier.take(taken_key) is not None
    assert tier.peek(taken_key) is None
    assert tier.stats["readmitted"] == 1


# -- phase-aware routing units (no servers, no JAX) --------------------


def test_pick_phase_preference_and_degradation():
    """phase='decode' keeps generation off the prefill pool and
    phase='prefill' keeps seeding off the decode pool — softly: a
    pool that empties (or is wholly excluded) degrades to every
    serving candidate, while standby stays unroutable throughout."""
    gw = FleetGateway(NoopBackend(), "svc")
    gw._replicas = {
        "d1": Replica("d1", "h", 1, role="decode"),
        "m1": Replica("m1", "h", 2, outstanding=1),
        "p1": Replica("p1", "h", 3, role="prefill"),
        "sb": Replica("sb", "h", 4, role="standby"),
    }
    assert gw._pick(phase="decode").id == "d1"
    assert gw._pick(phase="prefill").id == "p1"
    # mixed replicas qualify for both phases on load
    gw._replicas["d1"].outstanding = 3
    gw._replicas["p1"].outstanding = 3
    assert gw._pick(phase="decode").id == "m1"
    assert gw._pick(phase="prefill").id == "m1"
    # the preferred subset emptied by exclusion: degrade to mixed
    # routing (the prefill replica serves decode) instead of 503ing
    assert gw._pick(exclude={"d1", "m1"}, phase="decode").id == "p1"
    assert gw._pick(exclude={"p1", "m1"}, phase="prefill").id == "d1"
    # a standby is NEVER the degradation target
    for rid in ("d1", "m1", "p1"):
        del gw._replicas[rid]
    assert gw._pick(phase="decode") is None


def test_route_dead_pin_invalidated_same_cycle():
    """Regression: a sticky pin on a replica a handoff/proxy leg
    PROVED unreachable must be invalidated and re-pinned in the SAME
    routing call — not kept as a transient exclusion that burns every
    retry until the catalog poll expires it."""
    gw = FleetGateway(NoopBackend(), "svc", affinity="session")
    gw._replicas = {
        "a": Replica("a", "h", 1),
        "b": Replica("b", "h", 2),
    }
    first = gw._route("s:conv")
    other = "b" if first.id == "a" else "a"
    # contrast: a plain retry exclusion re-routes this request but
    # KEEPS the pin and counts nothing
    assert gw._route("s:conv", exclude={first.id}).id == other
    assert gw._sticky["s:conv"] == first.id
    assert _counter(gw._m_drained, first.id) == 0
    # a dead id — still in the routing view, the poll hasn't noticed —
    # invalidates the pin, counts drained_away, and re-pins NOW
    rerouted = gw._route("s:conv", dead={first.id})
    assert rerouted.id == other
    assert gw._sticky["s:conv"] == other
    assert _counter(gw._m_drained, first.id) == 1
    # the fresh pin then holds without further dead hints
    assert gw._route("s:conv").id == other


def test_pool_load_signal_split():
    """The admission queue depth rides the prefill/mixed signals
    (TTFT pressure) while the decode pool's is pure slot occupancy —
    what lets the two autoscalers size independently."""
    import types

    gw = FleetGateway(NoopBackend(), "svc")
    gw._replicas = {
        "p1": Replica("p1", "h", 1, outstanding=2, role="prefill"),
        "d1": Replica("d1", "h", 2, outstanding=3, role="decode"),
        "m1": Replica("m1", "h", 3, outstanding=1),
        "sb": Replica("sb", "h", 4, role="standby"),
    }
    gw._admission = types.SimpleNamespace(depth=7)
    prefill = gw.pool_load("prefill")
    decode = gw.pool_load("decode")
    mixed = gw.pool_load()
    assert prefill.queue_depth == 7
    assert prefill.per_replica == {"p1": 2.0}
    assert decode.queue_depth == 0
    assert decode.per_replica == {"d1": 3.0}
    assert mixed.queue_depth == 7
    # the mixed signal folds every SERVING replica; standby is parked
    assert set(mixed.per_replica) == {"p1", "d1", "m1"}


def test_apply_notes_all_fields_coexist_and_torn_never_throw():
    """One heartbeat note carrying role= AND kv= AND gp= AND pd= AND
    cc= parses field-by-field; garbage values degrade per-field; any
    truncation parses without throwing; and role flips to active only
    on a note that PARSED without a role field."""
    from containerpilot_tpu.kvtier import encode_fingerprints

    gw = FleetGateway(NoopBackend(), "svc")
    r = Replica("a", "h", 1)
    digest = encode_fingerprints(1, {0xAB})
    note = (
        "ok occ=0.25 role=decode kv=4,2,96,1,1 "
        "gp=1.0,2.5,0.5,3.0,4.0,0.25,0.0,12,340 "
        f"pd={digest} cc=beef:%2Ftmp%2Fcc"
    )
    gw._apply_notes(r, note)
    assert r.role == "decode"
    assert r.kv["hits"] == 4 and r.kv["tokens_reused"] == 96
    assert r.goodput["prefill"] == 3.0 and r.goodput["decode"] == 4.0
    assert r.goodput["tokens_out"] == 340.0
    assert r.digest == frozenset({0xAB})
    assert r.compile_cache == "beef:%2Ftmp%2Fcc"
    # garbage values next to a good role: per-field tolerance, and
    # cumulative counters never regress
    gw._apply_notes(
        r, "ok occ=0.30 role=decode kv=nonsense gp=nonsense pd=garbage"
    )
    assert r.role == "decode"
    assert r.kv["tokens_reused"] == 96
    assert r.digest == frozenset({0xAB})
    # every prefix of the full note parses without throwing
    torn = Replica("b", "h", 2, role="decode")
    for i in range(len(note)):
        gw._apply_notes(torn, note[:i])
    # a read that parsed NO fields keeps the previous role…
    gw._apply_notes(r, "")
    gw._apply_notes(r, "ok")
    assert r.role == "decode"
    # …a parsed beat without role= is a promotion (active by
    # omission), and an unknown role value routes as active
    gw._apply_notes(r, "ok occ=0.10")
    assert r.role == "active"
    gw._apply_notes(r, "ok role=superdecode")
    assert r.role == "active"


def test_autoscaler_pool_label(run):
    """A pool autoscaler stamps its pool into stats and into every
    scale_log entry, so /fleet attributes each decision to the pool
    that made it; the classic mixed actor reports 'fleet'."""

    class _StubLauncher:
        def __init__(self):
            self._ids = ["r0"]

        def count(self):
            return len(self._ids)

        def ids(self):
            return list(self._ids)

        async def launch(self):
            rid = f"r{len(self._ids)}"
            self._ids.append(rid)
            return rid

        async def retire(self, rid):
            self._ids.remove(rid)

    cfg = AutoscalerConfig(
        min_replicas=1, max_replicas=2, slots_per_replica=1,
        high_water=0.5, up_sustain_s=0.0, cooldown_s=0.0,
        tick_interval=0.01,
    )
    scaler = Autoscaler(
        _StubLauncher(),
        lambda: FleetLoad(queue_depth=5, per_replica={"r0": 5.0}),
        cfg, registry=None, pool="prefill",
    )
    assert scaler.stats["pool"] == "prefill"
    assert Autoscaler(
        _StubLauncher(), lambda: FleetLoad(0, {}), registry=None,
    ).stats["pool"] == "fleet"

    async def drive():
        for _ in range(10):
            await scaler.tick()
            if scaler.scale_ups:
                break
            await asyncio.sleep(0.01)

    run(drive())
    assert scaler.scale_ups >= 1
    ups = [e for e in scaler.scale_log if e["direction"] == "up"]
    assert ups and all(e["pool"] == "prefill" for e in ups)


# -- the tier-1 integration scenario -----------------------------------


def _sse_tokens(text):
    events = [
        json.loads(line[len("data: "):])
        for line in text.splitlines()
        if line.startswith("data: ")
    ]
    assert events and events[-1].get("done") is True
    return [t for e in events if "tokens" in e for t in e["tokens"]]


def test_disagg_fleet_byte_parity_and_poisoned_handoff(
    run, tmp_path, monkeypatch
):
    """A prefill+decode fleet behind the gateway vs one standalone
    mixed replica with the same weights: handed-off generations are
    byte-identical, buffered AND SSE — parity by construction through
    the shared reuse_admission path. A digest-warm repeat skips the
    handoff. Then a poisoned chunk (corrupted after digests were
    computed) makes the pull fail digest verification: the decode
    replica adopts nothing, the gateway counts a failed handoff, and
    the client still gets the byte-identical answer from a local
    prefill."""
    import jax
    import jax.numpy as jnp

    import containerpilot_tpu.kvtier.handoff as handoff_mod
    from containerpilot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    server_kwargs = dict(
        max_len=64, slots=2, slot_chunk=4,
        prefix_cache_entries=2, kv_spill_bytes=512 * 1024,
    )
    ref = InferenceServer(cfg, params, "127.0.0.1", 0, **server_kwargs)
    prefill_srv = InferenceServer(
        cfg, params, "127.0.0.1", 0, role="prefill", **server_kwargs
    )
    decode_srv = InferenceServer(
        cfg, params, "127.0.0.1", 0, role="decode", **server_kwargs
    )
    backend = FileCatalogBackend(str(tmp_path))
    # three prompts, each >= 16 tokens (handoff-eligible) with
    # distinct 16-token prefixes (distinct fingerprints)
    row1 = list(range(1, 25))
    row2 = list(range(30, 54))
    row3 = list(range(5, 29))

    real_plan = handoff_mod.kv_transfer_plan

    def poisoned_plan(host_tree, chunk_bytes=handoff_mod.KV_CHUNK):
        # corrupt one blob byte AFTER the manifest's digests were
        # computed from the pristine data: the wire chunk no longer
        # matches its digest, which is corruption (not transport)
        manifest, blobs = real_plan(host_tree, chunk_bytes)
        for i, blob in enumerate(blobs):
            if blob:
                flipped = bytearray(blob)
                flipped[-1] ^= 0xFF
                blobs[i] = bytes(flipped)
                break
        return manifest, blobs

    async def scenario():
        loop = asyncio.get_event_loop()
        await ref.run()
        await prefill_srv.run()
        await decode_srv.run()
        member_p = FleetMember(
            prefill_srv, backend, "inference", ttl=5,
            heartbeat_interval=0.1, instance_id="prefill-1",
        )
        member_d = FleetMember(
            decode_srv, backend, "inference", ttl=5,
            heartbeat_interval=0.1, instance_id="decode-1",
        )
        await member_p.start()
        await member_d.start()
        gateway = FleetGateway(
            backend, "inference", "127.0.0.1", 0,
            poll_interval=0.2, hedge=False, retry_backoff=0.01,
        )
        await gateway.run()
        # converge on both replicas AND their roles (the role rides
        # the heartbeat note; routing is phase-blind until it lands)
        for _ in range(200):
            rs = gateway._replicas
            if (
                rs.get("prefill-1") is not None
                and rs["prefill-1"].role == "prefill"
                and rs.get("decode-1") is not None
                and rs["decode-1"].role == "decode"
            ):
                break
            await asyncio.sleep(0.05)
        assert gateway._replicas["prefill-1"].role == "prefill"
        assert gateway._replicas["decode-1"].role == "decode"

        async def generate(port, body):
            return await loop.run_in_executor(
                None, _post, port, "/v1/generate", body
            )

        # -- buffered parity through a live handoff ----------------
        body1 = {"tokens": [row1], "max_new_tokens": 8, "seed": 11}
        via_gw = await generate(gateway.port, body1)
        direct = await generate(ref.port, body1)
        assert via_gw[0] == 200 and direct[0] == 200
        tokens_gw = json.loads(via_gw[1])["tokens"]
        tokens_ref = json.loads(direct[1])["tokens"]
        assert tokens_gw == tokens_ref
        assert gateway.handoffs["total"] >= 1
        assert gateway.handoffs["failed"] == 0
        assert gateway.handoffs["bytes"] > 0
        assert gateway.handoffs["ms_sum"] > 0.0
        # the handed-off entry actually fed the decode replica: it
        # readmitted through the spill tier's reuse_admission path
        spill_stats = decode_srv.prefix_cache.spill.snapshot()
        assert spill_stats["readmitted"] >= 1

        # -- SSE parity through a second handoff -------------------
        body2 = {
            "tokens": [row2], "max_new_tokens": 8, "seed": 12,
            "stream": True,
        }
        sse_gw = await generate(gateway.port, body2)
        sse_ref = await generate(ref.port, body2)
        assert sse_gw[0] == 200 and sse_ref[0] == 200
        ct = {k.lower(): v for k, v in sse_gw[2].items()}["content-type"]
        assert "text/event-stream" in ct
        streamed_gw = _sse_tokens(sse_gw[1])
        streamed_ref = _sse_tokens(sse_ref[1])
        assert streamed_gw == streamed_ref and streamed_gw
        assert gateway.handoffs["total"] >= 2

        # -- digest-warm repeat skips the handoff ------------------
        fp1 = prefix_fingerprint(row1)
        for _ in range(200):
            if fp1 in gateway._replicas["decode-1"].digest:
                break
            await asyncio.sleep(0.05)
        assert fp1 in gateway._replicas["decode-1"].digest
        total_before = gateway.handoffs["total"]
        repeat = await generate(gateway.port, body1)
        assert repeat[0] == 200
        assert json.loads(repeat[1])["tokens"] == tokens_ref
        assert gateway.handoffs["skipped_warm"] >= 1
        assert gateway.handoffs["total"] == total_before

        # -- poisoned chunk: fall back, never adopt corrupt KV -----
        monkeypatch.setattr(
            handoff_mod, "kv_transfer_plan", poisoned_plan
        )
        failed_before = gateway.handoffs["failed"]
        total_before = gateway.handoffs["total"]
        # had the corrupt entry been adopted, the generation would
        # READMIT it (readmitted +1); local LRU churn can legitimately
        # bump "spilled", so readmissions are the adoption signal
        readmitted_before = decode_srv.prefix_cache.spill.snapshot()[
            "readmitted"
        ]
        body3 = {"tokens": [row3], "max_new_tokens": 8, "seed": 13}
        via_gw3 = await generate(gateway.port, body3)
        direct3 = await generate(ref.port, body3)
        assert via_gw3[0] == 200 and direct3[0] == 200
        assert (
            json.loads(via_gw3[1])["tokens"]
            == json.loads(direct3[1])["tokens"]
        )
        assert gateway.handoffs["failed"] == failed_before + 1
        assert gateway.handoffs["total"] == total_before
        after = decode_srv.prefix_cache.spill.snapshot()
        assert after["readmitted"] == readmitted_before

        await gateway.stop()
        await member_p.stop()
        await member_d.stop()
        await decode_srv.stop()
        await prefill_srv.stop()
        await ref.stop()

    run(scenario(), timeout=600)
