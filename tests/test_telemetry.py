"""Telemetry tests: metric actors + /metrics + /status server
(reference: telemetry/*_test.go)."""
import asyncio
import json
import urllib.request

import pytest

from containerpilot_tpu.events import Event, EventBus, EventCode
from containerpilot_tpu.jobs import Job, JobConfig
from containerpilot_tpu.telemetry import Metric, Telemetry, TelemetryConfig
from containerpilot_tpu.telemetry.config import (
    MetricConfig,
    TelemetryConfigError,
)


def test_telemetry_config_defaults():
    cfg = TelemetryConfig({"interfaces": ["static:127.0.0.1"]})
    assert cfg.port == 9090
    assert cfg.address == "127.0.0.1"
    raw = cfg.to_job_config_raw()
    assert raw["name"] == "containerpilot"
    assert raw["health"] == {"interval": 5, "ttl": 15}


def test_metric_config_validation():
    with pytest.raises(TelemetryConfigError):
        TelemetryConfig(
            {
                "interfaces": ["static:127.0.0.1"],
                "metrics": [{"name": "x", "type": "bogus"}],
            }
        )


# -- config validation (the seed package's near-untested paths) --------


def test_telemetry_config_rejects_non_mapping_and_unknown_keys():
    with pytest.raises(TelemetryConfigError):
        TelemetryConfig(["not", "a", "mapping"])
    with pytest.raises(TelemetryConfigError) as exc:
        TelemetryConfig(
            {"interfaces": ["static:127.0.0.1"], "prot": "tcp"}
        )
    assert "unknown keys" in str(exc.value)


def test_metric_config_rejects_unknown_keys_and_missing_name():
    with pytest.raises(TelemetryConfigError) as exc:
        MetricConfig({"name": "x", "type": "counter", "bogus": 1})
    assert "unknown keys" in str(exc.value)
    with pytest.raises(TelemetryConfigError):
        MetricConfig({"type": "counter"})  # no name


def test_telemetry_config_bad_interface_is_config_error():
    """get_ip failures surface as TelemetryConfigError (the config
    layer's contract), not a bare ValueError from the IP helper."""
    with pytest.raises(TelemetryConfigError):
        TelemetryConfig({"interfaces": ["static:"]})


def test_telemetry_config_string_interface_coerced():
    cfg = TelemetryConfig({"interfaces": "static:127.0.0.1"})
    assert cfg.address == "127.0.0.1"
    # the raw (uncoerced) value round-trips into the self-ad job
    assert cfg.to_job_config_raw()["interfaces"] == "static:127.0.0.1"


def test_to_job_config_raw_tags_and_version():
    from containerpilot_tpu.version import VERSION

    cfg = TelemetryConfig(
        {"interfaces": ["static:127.0.0.1"], "tags": ["az1"]}
    )
    raw = cfg.to_job_config_raw()
    assert raw["tags"] == ["az1", VERSION]
    assert "interfaces" not in TelemetryConfig(
        {}
    ).to_job_config_raw()  # unset stays unset


def test_metric_config_reload_reregisters_without_collision():
    """Config reloads re-create the same metric; the prometheus
    registry treats a duplicate register as fatal, so MetricConfig
    must unregister-then-register (reference: metrics_config.go)."""
    spec = {"name": "zz_reload_gauge", "type": "gauge", "help": "g"}
    first = MetricConfig(dict(spec))
    first.collector.set(7)
    second = MetricConfig(dict(spec))  # same full name: no raise
    assert second.collector is not first.collector
    assert second.full_name == "zz_reload_gauge"


def test_metric_config_full_name_joins_nonempty_parts():
    cfg = MetricConfig(
        {"namespace": "zz", "name": "depth", "type": "gauge"}
    )
    assert cfg.full_name == "zz_depth"  # empty subsystem dropped
    assert cfg.help == "depth"  # help defaults to the name


# -- metric record paths ----------------------------------------------


def _metric(name, mtype):
    return Metric(MetricConfig({"name": name, "type": mtype}))


def test_counter_adds_and_gauge_sets():
    counter = _metric("zz_rec_counter", "counter")
    counter.record("2")
    counter.record("3.5")
    assert counter.collector._value.get() == 5.5  # noqa: SLF001
    gauge = _metric("zz_rec_gauge", "gauge")
    gauge.record("9")
    gauge.record("4")  # set, not add
    assert gauge.collector._value.get() == 4.0  # noqa: SLF001


def test_histogram_and_summary_observe():
    histogram = _metric("zz_rec_histogram", "histogram")
    histogram.record("0.25")
    histogram.record("0.75")
    assert histogram.collector._sum.get() == 1.0  # noqa: SLF001
    summary = _metric("zz_rec_summary", "summary")
    summary.record("2")
    assert summary.collector._count.get() == 1  # noqa: SLF001
    assert summary.collector._sum.get() == 2.0  # noqa: SLF001


def test_record_non_numeric_value_is_dropped_not_fatal():
    counter = _metric("zz_rec_bad_value", "counter")
    counter.record("not-a-number")
    assert counter.collector._value.get() == 0.0  # noqa: SLF001


def test_process_metric_matches_by_full_name_only():
    metric = Metric(
        MetricConfig(
            {"namespace": "zz", "subsystem": "app",
             "name": "hits", "type": "counter"}
        )
    )
    metric.process_metric("zz_app_hits|1")
    metric.process_metric("zz_app_misses|5")  # someone else's
    metric.process_metric("zz_app_hits")  # no value: logged, dropped
    # value with extra pipes: fields beyond the second are ignored
    metric.process_metric("zz_app_hits|2|junk")
    assert metric.collector._value.get() == 3.0  # noqa: SLF001


def test_metric_actor_records(run):
    async def scenario():
        cfg = TelemetryConfig(
            {
                "interfaces": ["static:127.0.0.1"],
                "metrics": [
                    {
                        "namespace": "zz",
                        "subsystem": "app",
                        "name": "connections",
                        "type": "gauge",
                        "help": "connection count",
                    }
                ],
            }
        )
        bus = EventBus()
        metric = Metric(cfg.metrics[0])
        metric.run(bus)
        bus.publish(Event(EventCode.METRIC, "zz_app_connections|42"))
        bus.publish(Event(EventCode.METRIC, "other_metric|1"))  # ignored
        bus.publish(Event(EventCode.METRIC, "garbage-no-pipe"))  # ignored
        await asyncio.sleep(0.05)
        metric.stop()
        await bus.wait()
        return cfg.metrics[0].collector

    collector = run(scenario())
    assert collector._value.get() == 42.0  # noqa: SLF001


def test_server_metrics_and_status(run):
    async def scenario():
        cfg = TelemetryConfig(
            {
                "port": 19091,
                "interfaces": ["static:127.0.0.1"],
                "metrics": [
                    {"name": "zz_requests_total", "type": "counter",
                     "help": "requests"},
                ],
            }
        )
        telemetry = Telemetry(cfg)
        bus = EventBus()
        for m in telemetry.metrics:
            m.run(bus)
        job = Job(
            JobConfig({"name": "app", "exec": "sleep 1"}).validate(None)
        )
        telemetry.monitor_jobs([job])
        await telemetry.run()
        bus.publish(Event(EventCode.METRIC, "zz_requests_total|3"))
        await asyncio.sleep(0.05)

        def fetch(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:19091{path}", timeout=5
            ) as resp:
                return resp.read().decode()

        loop = asyncio.get_event_loop()
        metrics_body = await loop.run_in_executor(None, fetch, "/metrics")
        status_body = await loop.run_in_executor(None, fetch, "/status")
        for m in telemetry.metrics:
            m.stop()
        await telemetry.stop()
        await bus.wait()
        return metrics_body, status_body

    metrics_body, status_body = run(scenario())
    assert "zz_requests_total" in metrics_body
    assert "containerpilot_events_total" in metrics_body  # built-in
    status = json.loads(status_body)
    assert status["Jobs"] == [{"Name": "app", "Status": "unknown"}]
    assert "Version" in status
