"""Telemetry tests: metric actors + /metrics + /status server
(reference: telemetry/*_test.go)."""
import asyncio
import json
import urllib.request

import pytest

from containerpilot_tpu.events import Event, EventBus, EventCode
from containerpilot_tpu.jobs import Job, JobConfig
from containerpilot_tpu.telemetry import Metric, Telemetry, TelemetryConfig
from containerpilot_tpu.telemetry.config import TelemetryConfigError


def test_telemetry_config_defaults():
    cfg = TelemetryConfig({"interfaces": ["static:127.0.0.1"]})
    assert cfg.port == 9090
    assert cfg.address == "127.0.0.1"
    raw = cfg.to_job_config_raw()
    assert raw["name"] == "containerpilot"
    assert raw["health"] == {"interval": 5, "ttl": 15}


def test_metric_config_validation():
    with pytest.raises(TelemetryConfigError):
        TelemetryConfig(
            {
                "interfaces": ["static:127.0.0.1"],
                "metrics": [{"name": "x", "type": "bogus"}],
            }
        )


def test_metric_actor_records(run):
    async def scenario():
        cfg = TelemetryConfig(
            {
                "interfaces": ["static:127.0.0.1"],
                "metrics": [
                    {
                        "namespace": "zz",
                        "subsystem": "app",
                        "name": "connections",
                        "type": "gauge",
                        "help": "connection count",
                    }
                ],
            }
        )
        bus = EventBus()
        metric = Metric(cfg.metrics[0])
        metric.run(bus)
        bus.publish(Event(EventCode.METRIC, "zz_app_connections|42"))
        bus.publish(Event(EventCode.METRIC, "other_metric|1"))  # ignored
        bus.publish(Event(EventCode.METRIC, "garbage-no-pipe"))  # ignored
        await asyncio.sleep(0.05)
        metric.stop()
        await bus.wait()
        return cfg.metrics[0].collector

    collector = run(scenario())
    assert collector._value.get() == 42.0  # noqa: SLF001


def test_server_metrics_and_status(run):
    async def scenario():
        cfg = TelemetryConfig(
            {
                "port": 19091,
                "interfaces": ["static:127.0.0.1"],
                "metrics": [
                    {"name": "zz_requests_total", "type": "counter",
                     "help": "requests"},
                ],
            }
        )
        telemetry = Telemetry(cfg)
        bus = EventBus()
        for m in telemetry.metrics:
            m.run(bus)
        job = Job(
            JobConfig({"name": "app", "exec": "sleep 1"}).validate(None)
        )
        telemetry.monitor_jobs([job])
        await telemetry.run()
        bus.publish(Event(EventCode.METRIC, "zz_requests_total|3"))
        await asyncio.sleep(0.05)

        def fetch(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:19091{path}", timeout=5
            ) as resp:
                return resp.read().decode()

        loop = asyncio.get_event_loop()
        metrics_body = await loop.run_in_executor(None, fetch, "/metrics")
        status_body = await loop.run_in_executor(None, fetch, "/status")
        for m in telemetry.metrics:
            m.stop()
        await telemetry.stop()
        await bus.wait()
        return metrics_body, status_body

    metrics_body, status_body = run(scenario())
    assert "zz_requests_total" in metrics_body
    assert "containerpilot_events_total" in metrics_body  # built-in
    status = json.loads(status_body)
    assert status["Jobs"] == [{"Name": "app", "Status": "unknown"}]
    assert "Version" in status
