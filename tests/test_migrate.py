"""Live drain-migration tests (docs/60 § drain runbook): the
deterministic push plan (fp-family affinity, digest-coldest balancing,
warm short-circuit), the ``mg=`` heartbeat note codec and the
gateway's torn-note-tolerant repoint path, the migration-aware drain
answer (progress-derived Retry-After + X-CP-Migrated-To), the
autoscaler's retire path surviving a drainer that dies mid-migration —
and the tier-1 integration scenario: a sticky session whose replica
drains mid-conversation lands its KV on the survivor over the handoff
wire and answers its next turns byte-identically, buffered AND SSE,
with a poisoned chunk degrading to a counted re-prefill fallback that
never surfaces as a client error.
"""
import asyncio
import json
import urllib.error
import urllib.request

import pytest

from containerpilot_tpu.discovery import FileCatalogBackend, NoopBackend
from containerpilot_tpu.fleet import FleetGateway, FleetMember
from containerpilot_tpu.fleet.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    FleetLoad,
)
from containerpilot_tpu.fleet.gateway import Replica
from containerpilot_tpu.kvtier import (
    encode_migration_note,
    parse_migration_note,
    plan_migration,
)
from containerpilot_tpu.kvtier.digest import prefix_fingerprint


def _post(port, path, payload, timeout=120, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers=dict(
            {"Content-Type": "application/json"}, **(headers or {})
        ),
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode(), dict(exc.headers)


# -- the deterministic push plan (no servers, no JAX) ------------------


def test_plan_migration_deterministic_affine_and_warm():
    """Same inputs -> identical plan; target-list order never changes
    the assignment; keys sharing a fingerprint family all land on ONE
    survivor; a digest-warm fp goes to its warm holder flagged
    warm=True; cold families balance toward the digest-coldest."""
    fam_a = tuple(range(100, 124))        # 24 tokens, one fp family
    fam_a_long = fam_a + tuple(range(124, 140))
    fam_b = tuple(range(500, 520))
    fam_c = tuple(range(900, 920))
    keys = [fam_a, fam_b, fam_a_long, fam_c]
    fp_a = prefix_fingerprint(list(fam_a))
    fp_b = prefix_fingerprint(list(fam_b))
    targets = [
        ("s1", frozenset({fp_b, 1, 2, 3})),   # warm for fam_b, busy
        ("s2", frozenset()),                   # coldest
    ]
    plan = plan_migration(keys, targets)
    assert plan == plan_migration(keys, targets)
    assert plan == plan_migration(keys, list(reversed(targets)))
    by_fp = {}
    for entry in plan:
        by_fp.setdefault(entry["fp"], set()).add(entry["target"])
    # family affinity: both fam_a keys share one survivor
    assert len(by_fp[fp_a]) == 1
    # warm fp lands on its warm holder, flagged, zero cost
    b_entries = [e for e in plan if e["fp"] == fp_b]
    assert b_entries == [
        {"key": fam_b, "fp": fp_b, "target": "s1", "warm": True}
    ]
    # cold families avoid the digest-heavy survivor
    cold = [e for e in plan if not e["warm"]]
    assert cold and all(e["target"] == "s2" for e in cold)
    # longer keys are planned first (most prefill value moves before
    # a window can expire)
    assert plan[0]["key"] == fam_a_long
    # degenerate inputs: no targets / sub-fingerprint keys plan empty
    assert plan_migration(keys, []) == []
    assert plan_migration([tuple(range(4))], targets) == []


# -- the mg= note codec + the gateway's repoint path -------------------


def test_migration_note_roundtrip_truncation_and_garbage():
    note = encode_migration_note(
        3, 7, 1, 2, True, [(0xDEADBEEF, "replica-1"), (0xAB, "r2")]
    )
    counters, landed = parse_migration_note(note)
    assert counters == {
        "done": 3, "total": 7, "failed": 1, "timeout": 2, "active": 1,
    }
    assert landed == {0xDEADBEEF: "replica-1", 0xAB: "r2"}
    # most-recent-first: truncation drops OLD landings; the duplicate
    # fp keeps its freshest (first-encoded) target
    dup = encode_migration_note(
        1, 1, 0, 0, False, [(0xAB, "new"), (0xAB, "old")]
    )
    assert parse_migration_note(dup)[1] == {0xAB: "new"}
    # a tight budget drops landings, never the counter head
    tight = encode_migration_note(
        9, 9, 0, 0, False,
        [(i, f"survivor-{i}") for i in range(64)], max_bytes=40,
    )
    assert len(tight) <= 40
    assert parse_migration_note(tight)[0]["done"] == 9
    # every torn prefix parses without throwing, zero-filled
    for i in range(len(note)):
        c, _l = parse_migration_note(note[:i])
        assert set(c) == {"done", "total", "failed", "timeout",
                          "active"}
    for garbage in ("", "x", "1,2", "a,b,c,d,e", "1,2,3,4,5;zz:t",
                    "1,2,3,4,9000;deadbeef:"):
        c, landed = parse_migration_note(garbage)
        assert all(v >= 0 for v in c.values()) and c["active"] <= 1
        assert landed == {}


def test_gateway_repoints_pins_on_mg_landings():
    """An mg= landing moves exactly the sticky pins whose session
    fingerprint matches, counts the move, and never regresses the
    cumulative mirrors on a torn re-read."""
    gw = FleetGateway(NoopBackend(), "svc", affinity="session")
    gw._replicas = {
        "a": Replica("a", "h", 1),
        "b": Replica("b", "h", 2),
    }
    gw._route("s:conv", fp=0xAB)
    gw._route("s:other", fp=0xCD)
    gw._sticky["s:conv"] = "a"
    gw._sticky["s:other"] = "a"
    note = "ok occ=0.5 mg=" + encode_migration_note(
        2, 3, 0, 0, True, [(0xAB, "b")]
    )
    gw._apply_notes(gw._replicas["a"], note)
    assert gw._sticky["s:conv"] == "b"          # landed fp repointed
    assert gw._sticky["s:other"] == "a"         # other fp untouched
    assert gw.migrations["sessions_migrated"] == 2
    assert gw.migrations["pins_repointed"] == 1
    assert gw._replicas["a"].migrating is True
    assert gw._m_migrated._value.get() == 2  # noqa: SLF001
    # replayed/torn notes with LOWER counters never regress, and a
    # re-announced landing does not double-repoint
    gw._apply_notes(
        gw._replicas["a"],
        "ok mg=" + encode_migration_note(1, 3, 0, 0, False,
                                         [(0xAB, "b")]),
    )
    assert gw.migrations["sessions_migrated"] == 2
    assert gw.migrations["pins_repointed"] == 1
    assert gw._replicas["a"].migrating is False
    # failures/timeouts mirror as deltas
    gw._apply_notes(
        gw._replicas["a"],
        "ok mg=" + encode_migration_note(2, 5, 2, 1, False),
    )
    assert gw.migrations["failed"] == 2
    assert gw.migrations["timeout"] == 1
    # a landing naming an UNKNOWN survivor repoints nothing (the
    # ordinary drained-away re-pin covers it) and never throws
    gw._apply_notes(
        gw._replicas["a"],
        "ok mg=" + encode_migration_note(3, 5, 2, 1, False,
                                         [(0xCD, "gone")]),
    )
    assert gw._sticky["s:other"] == "a"
    # byte-level fuzz: every prefix of a full note applies cleanly,
    # and the elementwise-max merge counts replica c's done=2 ONCE
    # across all the torn re-reads
    torn = Replica("c", "h", 3)
    gw._replicas["c"] = torn
    for i in range(len(note) + 1):
        gw._apply_notes(torn, note[:i])
    assert gw.migrations["sessions_migrated"] == 5


def test_drain_bounce_repoints_on_migrated_to_header(run):
    """A 503 bounce carrying X-CP-Migrated-To repoints the pin
    synchronously (warm reconnect even if the drainer deregisters
    before its final mg= beat lands); an unknown target or a missing
    header takes the plain retry path."""
    gw = FleetGateway(
        NoopBackend(), "svc", affinity="session", retry_backoff=0.001,
    )
    gw._replicas = {
        "a": Replica("a", "h", 1),
        "b": Replica("b", "h", 2),
    }
    gw._sticky["s:conv"] = "a"

    async def bounce(headers):
        return await gw._drain_bounce(
            "s:conv", "a", headers, {"a"}, 0, 0.001
        )

    run(bounce({"x-cp-migrated-to": "b"}))
    assert gw._sticky["s:conv"] == "b"
    assert gw.migrations == {
        "sessions_migrated": 0, "failed": 0, "timeout": 0,
        "pins_repointed": 1, "drain_answers": 1,
    }
    # pin no longer on the drainer: counted as an answer, not a move
    run(bounce({"x-cp-migrated-to": "b"}))
    assert gw.migrations["pins_repointed"] == 1
    assert gw.migrations["drain_answers"] == 2
    # unknown survivor: answer counted, pin untouched
    gw._sticky["s:conv"] = "a"
    run(bounce({"x-cp-migrated-to": "zz"}))
    assert gw._sticky["s:conv"] == "a"
    assert gw.migrations["drain_answers"] == 3
    # plain drain 503: nothing counted
    run(bounce({}))
    assert gw.migrations["drain_answers"] == 3


# -- the autoscaler's retire path vs a dying drainer -------------------


class _FragileLauncher:
    """Retire raises mid-drain (the drainer died inside its migrate
    window) — but the victim really is gone from the managed view."""

    def __init__(self, ids):
        self._ids = list(ids)
        self.retire_calls = 0

    def count(self):
        return len(self._ids)

    def ids(self):
        return list(self._ids)

    async def launch(self):
        rid = f"relaunched-{len(self._ids)}"
        self._ids.append(rid)
        return rid

    async def retire(self, rid):
        self.retire_calls += 1
        self._ids.remove(rid)
        raise RuntimeError("drainer died mid-migration")


def test_autoscaler_retire_failure_counted_and_repaired(run):
    """retire() raising mid-migration must not kill the tick or
    record a scale-down that didn't cleanly happen; the failure is
    counted, and when the fleet falls below min the ordinary repair
    path relaunches — no slot leak."""
    launcher = _FragileLauncher(["r0", "r1"])
    scaler = Autoscaler(
        launcher,
        lambda: FleetLoad(queue_depth=0, per_replica={}),
        AutoscalerConfig(
            min_replicas=1, max_replicas=3, slots_per_replica=1,
            high_water=0.9, low_water=0.5, up_sustain_s=0.0,
            down_sustain_s=0.0, cooldown_s=0.0, tick_interval=0.01,
        ),
        registry=None,
    )

    async def drive():
        for _ in range(10):
            await scaler.tick()
            if launcher.retire_calls:
                break
            await asyncio.sleep(0.01)

    run(drive())
    assert launcher.retire_calls == 1
    assert scaler.retire_failures == 1
    assert scaler.scale_downs == 0            # not a clean scale-down
    assert scaler.stats["retire_failures"] == 1
    assert not any(
        e["direction"] == "down" for e in scaler.scale_log
    )
    # the victim's death took the fleet to min; a second casualty
    # drops it below and the next ticks repair back up to min
    launcher._ids.clear()

    async def repair():
        for _ in range(20):
            await scaler.tick()
            if launcher.count() >= 1:
                return
            await asyncio.sleep(0.01)

    run(repair())
    assert launcher.count() == 1


# -- the tier-1 integration scenario -----------------------------------


def _sse_tokens(text):
    events = [
        json.loads(line[len("data: "):])
        for line in text.splitlines()
        if line.startswith("data: ")
    ]
    assert events and events[-1].get("done") is True
    return [t for e in events if "tokens" in e for t in e["tokens"]]


def _server_kwargs():
    return dict(
        max_len=64, slots=2, slot_chunk=4,
        prefix_cache_entries=4, kv_spill_bytes=512 * 1024,
    )


def _build_servers(n):
    import jax
    import jax.numpy as jnp

    from containerpilot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    return [
        InferenceServer(cfg, params, "127.0.0.1", 0, **_server_kwargs())
        for _ in range(n)
    ]


def test_drain_migrates_session_byte_parity_buffered_and_sse(
    run, tmp_path
):
    """A pinned session's replica drains mid-conversation: the drain
    pushes its KV to the survivor over the handoff wire, the gateway
    repoints the pin off the mg= landing, and the session's next
    turns — buffered AND SSE — answer byte-identically to a standalone
    replica that never lost its cache, with the survivor serving them
    from ADOPTED KV (spill readmission), not a re-prefill."""
    serv_a, serv_b, ref = _build_servers(3)
    backend = FileCatalogBackend(str(tmp_path))
    row1 = list(range(1, 25))  # 24 tokens: migration-eligible

    async def scenario():
        loop = asyncio.get_event_loop()
        for s in (serv_a, serv_b, ref):
            await s.run()
        members = {
            "replica-a": FleetMember(
                serv_a, backend, "inference", ttl=5,
                heartbeat_interval=0.1, instance_id="replica-a",
            ),
            "replica-b": FleetMember(
                serv_b, backend, "inference", ttl=5,
                heartbeat_interval=0.1, instance_id="replica-b",
            ),
        }
        servers = {"replica-a": serv_a, "replica-b": serv_b}
        for m in members.values():
            await m.start()
        gateway = FleetGateway(
            backend, "inference", "127.0.0.1", 0,
            affinity="session", poll_interval=0.1, hedge=False,
            retry_backoff=0.01,
        )
        await gateway.run()
        for _ in range(200):
            if len(gateway._replicas) == 2:
                break
            await asyncio.sleep(0.05)
        assert len(gateway._replicas) == 2

        async def generate(port, body, headers=None):
            return await loop.run_in_executor(
                None, lambda: _post(port, "/v1/generate", body,
                                    120, headers)
            )

        # -- turn 1 pins the session and seeds its KV --------------
        body1 = {
            "tokens": [row1], "max_new_tokens": 6, "seed": 11,
            "session_id": "conv",
        }
        turn1 = await generate(gateway.port, body1)
        ref1 = await generate(ref.port, body1)
        assert turn1[0] == 200 and ref1[0] == 200
        tokens1 = json.loads(turn1[1])["tokens"]
        assert tokens1 == json.loads(ref1[1])["tokens"]
        pinned = gateway._sticky["s:conv"]
        survivor = "replica-b" if pinned == "replica-a" else "replica-a"

        # -- the pinned replica drains: migrate, repoint, deregister
        drained = await members[pinned].drain()
        assert drained is True
        summary = servers[pinned].migration
        assert summary["done"] >= 1
        assert summary["failed"] == 0 and summary["timeout"] == 0
        # the landing repointed the pin (mg= beat or POST-back; the
        # gateway read it before the record deregistered)
        for _ in range(100):
            if (
                gateway._sticky.get("s:conv") == survivor
                and gateway.migrations["sessions_migrated"] >= 1
                and pinned not in gateway._replicas
            ):
                break
            await asyncio.sleep(0.05)
        assert gateway._sticky["s:conv"] == survivor
        assert gateway.migrations["sessions_migrated"] >= 1
        assert gateway.migrations["timeout"] == 0

        # the drained replica's /v1/migrate progress report (served
        # while draining) names the landing the pin followed
        fp1 = prefix_fingerprint(row1)
        progress = await generate(servers[pinned].port, {})
        assert progress[0] == 503  # generate is closed...
        report = await loop.run_in_executor(
            None, _post, servers[pinned].port, "/v1/migrate", {}
        )
        assert report[0] == 200  # ...the migration verb is not
        landed = json.loads(report[1])["landed"]
        assert landed.get(f"{fp1:08x}") == survivor
        assert json.loads(report[1])["cumulative"]["done"] >= 1
        malformed = await loop.run_in_executor(
            None, _post, servers[pinned].port, "/v1/migrate",
            {"targets": [{"bogus": 1}]},
        )
        assert malformed[0] == 422

        # -- turn 2, buffered, on the survivor: byte parity from
        # ADOPTED KV --------------------------------------------------
        readmit_before = servers[survivor].prefix_cache.spill.snapshot()[
            "readmitted"
        ]
        row2 = row1 + tokens1[0] + [3, 5]
        body2 = {
            "tokens": [row2], "max_new_tokens": 6, "seed": 12,
            "session_id": "conv",
        }
        turn2 = await generate(gateway.port, body2)
        ref2 = await generate(ref.port, dict(body2, session_id=None))
        assert turn2[0] == 200 and ref2[0] == 200
        tokens2 = json.loads(turn2[1])["tokens"]
        assert tokens2 == json.loads(ref2[1])["tokens"]
        after = servers[survivor].prefix_cache.spill.snapshot()
        assert after["readmitted"] >= readmit_before + 1

        # -- turn 3, SSE, still on the survivor ---------------------
        row3 = row2 + tokens2[0]
        body3 = {
            "tokens": [row3], "max_new_tokens": 6, "seed": 13,
            "session_id": "conv", "stream": True,
        }
        turn3 = await generate(gateway.port, body3)
        ref3 = await generate(ref.port, dict(body3, session_id=None))
        assert turn3[0] == 200 and ref3[0] == 200
        ct = {k.lower(): v for k, v in turn3[2].items()}["content-type"]
        assert "text/event-stream" in ct
        assert _sse_tokens(turn3[1]) == _sse_tokens(ref3[1])
        assert gateway._sticky["s:conv"] == survivor

        await gateway.stop()
        for m in members.values():
            await m.stop()
        for s in (serv_a, serv_b, ref):
            await s.stop()

    run(scenario(), timeout=600)


def test_poisoned_chunk_counts_failed_fallback_zero_5xx(
    run, monkeypatch
):
    """A poisoned chunk (corrupted after digests were computed) makes
    the survivor's pull fail verification: the push is a COUNTED
    failed fallback on the drainer, the survivor adopts nothing, and
    both replicas keep answering 200 — corruption never becomes a
    client error."""
    import containerpilot_tpu.kvtier.handoff as handoff_mod

    drainer, survivor = _build_servers(2)
    row = list(range(1, 25))
    real_plan = handoff_mod.kv_transfer_plan

    def poisoned_plan(host_tree, chunk_bytes=handoff_mod.KV_CHUNK):
        manifest, blobs = real_plan(host_tree, chunk_bytes)
        for i, blob in enumerate(blobs):
            if blob:
                flipped = bytearray(blob)
                flipped[-1] ^= 0xFF
                blobs[i] = bytes(flipped)
                break
        return manifest, blobs

    async def scenario():
        loop = asyncio.get_event_loop()
        await drainer.run()
        await survivor.run()
        seed = await loop.run_in_executor(
            None, _post, drainer.port, "/v1/generate",
            {"tokens": [row], "max_new_tokens": 4, "seed": 7},
        )
        assert seed[0] == 200
        monkeypatch.setattr(
            handoff_mod, "kv_transfer_plan", poisoned_plan
        )
        readmit_before = survivor.prefix_cache.spill.snapshot()[
            "readmitted"
        ]
        summary = await drainer.migrate_sessions(
            [("s", "127.0.0.1", survivor.port, frozenset())],
            window_s=10.0,
            authority=f"127.0.0.1:{drainer.port}",
        )
        assert summary["failed"] >= 1
        assert summary["done"] == 0
        assert summary["timeout"] == 0
        assert drainer._migration_landed == {}  # noqa: SLF001
        # nothing corrupt was adopted
        after = survivor.prefix_cache.spill.snapshot()
        assert after["readmitted"] == readmit_before
        # and the fallback is invisible to clients: both still 200
        monkeypatch.setattr(
            handoff_mod, "kv_transfer_plan", real_plan
        )
        for port in (survivor.port, drainer.port):
            ok = await loop.run_in_executor(
                None, _post, port, "/v1/generate",
                {"tokens": [row], "max_new_tokens": 4, "seed": 7},
            )
            assert ok[0] == 200
        await survivor.stop()
        await drainer.stop()

    run(scenario(), timeout=600)


def test_drain_answer_retry_after_tracks_progress_and_names_survivor(
    run,
):
    """The drain 503's Retry-After extrapolates the migration's
    observed pace (capped by the window's remainder, floored at 1),
    and once this request's prefix has landed the answer names the
    survivor in X-CP-Migrated-To."""
    import time

    (server,) = _build_servers(1)
    row = list(range(1, 25))
    fp = prefix_fingerprint(row)

    async def scenario():
        loop = asyncio.get_event_loop()
        await server.run()
        # no migration ever: the legacy fixed beat
        assert server._drain_retry_after() == "1"  # noqa: SLF001
        # mid-migration, half done after ~2s: pace says ~2s more
        server.migration.update(
            active=True, total=4, done=1, failed=1, timeout=0,
            window_s=20.0, started_at=time.monotonic() - 2.0,
        )
        assert server._drain_retry_after() == "2"  # noqa: SLF001
        # nothing settled yet: the whole window stands in, capped
        server.migration.update(done=0, failed=0, window_s=3.0)
        assert server._drain_retry_after() == "1"  # noqa: SLF001
        server.migration["active"] = False
        server._record_landing(fp, "survivor-1")  # noqa: SLF001
        server.enter_maintenance()
        resp = await loop.run_in_executor(
            None, _post, server.port, "/v1/generate",
            {"tokens": [row], "max_new_tokens": 4},
        )
        assert resp[0] == 503
        headers = {k.lower(): v for k, v in resp[2].items()}
        assert headers["x-cp-migrated-to"] == "survivor-1"
        assert int(headers["retry-after"]) >= 1
        # a different prefix has not landed: no header
        other = await loop.run_in_executor(
            None, _post, server.port, "/v1/generate",
            {"tokens": [list(range(500, 524))], "max_new_tokens": 4},
        )
        assert other[0] == 503
        assert "x-cp-migrated-to" not in {
            k.lower() for k in other[2]
        }
        await server.stop()

    run(scenario(), timeout=600)
