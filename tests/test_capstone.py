"""The SURVEY §7 capstone: supervised multi-process training e2e.

N supervisor instances (the real CLI, real configs) each run a
training worker job. The workers rendezvous through a live catalog
server (``-catalog-server``, the supervisor's own daemon), complete a
pod run over an N-process CPU mesh — pmap data-parallel at N=2, the
production 2x2 dp x tp mesh path (parallel.train + sharded
checkpointing) at N=4 — and checkpoint every step. A fault is injected: one worker crashes mid-run; its peer's
step watchdog turns the resulting collective hang into an exit; BOTH
supervisors apply their restart budgets; the reincarnated pod
re-rendezvouses and resumes from the latest checkpoint.

Asserted: final loss parity with a single-process run of the same
global batch schedule, both workers resumed (not restarted from
scratch), and the crash was catalog-visible (the dead worker's service
left the catalog and returned). Mirrors the reference's
multi-container integration tier (scripts/test.sh:50-140).
"""
import json
import os
import socket
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "capstone_worker.py")

STEPS = 6
CRASH_STEP = 2
GLOBAL_BATCH = 8


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _sub_env() -> dict:
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # exactly 1 CPU device per process
    # conftest's in-process cache env must not leak: subprocess cache
    # behavior is controlled ONLY by CONTAINERPILOT_COMPILE_CACHE
    # (enable_compile_cache), so dedicated-cache tests stay cold
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env.pop("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", None)
    # pod boots across this suite recompile the same tiny-model
    # program sets; the workload CLIs' opt-in persistent compile
    # cache (modelcfg.enable_compile_cache) turns every boot after
    # the first into cache re-warms — exactly the crash->restart
    # path it exists for, and minutes off the suite on one core
    env.setdefault(
        "CONTAINERPILOT_COMPILE_CACHE", "/tmp/cp_test_compile_cache"
    )
    return env


def _wait_http(url: str, deadline_s: float = 30) -> None:
    import urllib.request

    deadline = time.monotonic() + deadline_s
    while True:
        try:
            urllib.request.urlopen(url, timeout=1)
            return
        except Exception:
            if time.monotonic() > deadline:
                raise TimeoutError(f"never reachable: {url}")
            time.sleep(0.2)


def _supervisor_config(
    tmp_path, idx: int, catalog_port: int, coord_port: int,
    job_port: int, crash_idx: int = 1, n_procs: int = 2, tp: int = 0,
) -> str:
    # ONE shared checkpoint dir for the pod (orbax is a global
    # checkpointer: primary-process writes + cross-process barriers;
    # per-process dirs would leave worker 1's empty and deadlock the
    # post-restart restore — parallel/checkpoint.py module docstring)
    ckpt = tmp_path / "ckpt"
    out = tmp_path / f"out{idx}.json"
    heartbeat = tmp_path / f"heartbeat{idx}"
    exec_argv = [
        sys.executable, WORKER,
        "--process-id", str(idx),
        "--num-processes", str(n_procs),
        "--catalog", f"127.0.0.1:{catalog_port}",
        "--coordinator-port", str(coord_port),
        "--steps", str(STEPS),
        "--global-batch", str(GLOBAL_BATCH),
        "--checkpoint-dir", str(ckpt),
        "--out", str(out),
        # the single-core box serializes n_procs compiles: scale the
        # deadlines with the pod size
        "--step-timeout", str(30 * max(1, n_procs // 2)),
        "--startup-timeout", str(120 * max(1, n_procs // 2)),
        "--heartbeat-file", str(heartbeat),
    ]
    if tp:
        exec_argv += ["--tp", str(tp)]
    if idx == crash_idx:
        exec_argv += [
            "--crash-step", str(CRASH_STEP),
            "--crash-sentinel", str(tmp_path / "crash-sentinel"),
        ]
    config = {
        "consul": f"127.0.0.1:{catalog_port}",
        "stopTimeout": "5s",
        "logging": {
            "level": "INFO", "format": "default", "output": "stdout"
        },
        "jobs": [
            {
                "name": f"trainer{idx}",
                "exec": exec_argv,
                # budget absorbs: the injected crash / watchdog exit,
                # rendezvous-race failures (more peers, more races),
                # the successful rerun, and already-complete no-ops
                "restarts": 4 + max(0, n_procs - 2),
                "port": job_port,
                "interfaces": ["static:127.0.0.1"],
                # progress-based health: passes only while the worker
                # keeps its per-step heartbeat file fresh, so a crash
                # (or a wedge) lapses the TTL and the service goes
                # catalog-critical until the reincarnation resumes
                # stepping — the reference's TTL-criticality
                # semantics, driven by real training progress
                "health": {
                    "exec": [
                        "/bin/sh", "-c",
                        f'test -f "{heartbeat}" && '
                        f'test "$(( $(date +%s) - '
                        f'$(stat -c %Y "{heartbeat}") ))" -lt 12',
                    ],
                    "interval": 1, "ttl": 5,
                },
            }
        ],
    }
    path = tmp_path / f"host{idx}.json5"
    path.write_text(json.dumps(config))
    return str(path)


# the 2-proc worker-crash case is subsumed by dp2xtp2-worker-crash
# (same crash target, superset topology) — dropped to hold the
# one-core suite budget; coordinator-crash stays 2-proc because the
# crash TARGET differs
@pytest.mark.parametrize(
    "n_procs,tp,crash_idx", [(2, 0, 0), (4, 2, 1)],
    ids=["coordinator-crash", "dp2xtp2-worker-crash"],
)
def test_supervised_multiprocess_training_with_crash_and_resume(
    tmp_path, n_procs, tp, crash_idx
):
    """crash_idx=0 kills the process HOSTING the jax coordinator —
    the harder failure: the whole rendezvous must rebuild (the
    reincarnated process 0 clears the stale coordinator registration
    and re-registers; the survivor's watchdog turns its hang into a
    restart that discovers the fresh coordinator).

    The dp2xtp2 variant runs FOUR supervised processes on a 2x2
    dp x tp mesh through the production path (parallel.train +
    sharded checkpointing), so the crash/restart/resume story covers
    cross-process tensor parallelism, not just pmap dp."""
    from containerpilot_tpu.discovery.consul import ConsulBackend

    catalog_port, coord_port = _free_port(), _free_port()
    job_ports = tuple(_free_port() for _ in range(n_procs))
    env = _sub_env()
    # the restart half of the story is exactly what the shared XLA
    # compile cache exists for: the reincarnated worker re-warms from
    # cached executables instead of recompiling the train step
    env["CONTAINERPILOT_COMPILE_CACHE"] = str(tmp_path / "xla-cache")

    catalog = subprocess.Popen(
        [sys.executable, "-m", "containerpilot_tpu",
         "-catalog-server", f"127.0.0.1:{catalog_port}"],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    supervisors = []
    logs = []
    timeline = []  # (monotonic_t, crashing trainer present in catalog)
    stop_poll = threading.Event()
    try:
        _wait_http(
            f"http://127.0.0.1:{catalog_port}/v1/health/service/none"
        )
        for idx in range(n_procs):
            cfg_path = _supervisor_config(
                tmp_path, idx, catalog_port, coord_port,
                job_ports[idx], crash_idx, n_procs=n_procs, tp=tp,
            )
            log_fh = open(tmp_path / f"sup{idx}.log", "w")
            logs.append(log_fh)
            supervisors.append(
                subprocess.Popen(
                    [sys.executable, "-m", "containerpilot_tpu",
                     "-config", cfg_path],
                    cwd=REPO, env=env,
                    stdout=log_fh, stderr=subprocess.STDOUT,
                )
            )

        backend = ConsulBackend(address=f"127.0.0.1:{catalog_port}")

        def poll_catalog() -> None:
            while not stop_poll.is_set():
                try:
                    present = bool(
                        backend.instances(f"trainer{crash_idx}")
                    )
                    timeline.append((time.monotonic(), present))
                except Exception:
                    pass
                stop_poll.wait(0.25)

        poller = threading.Thread(target=poll_catalog, daemon=True)
        poller.start()

        deadline = time.monotonic() + 480 * max(1, n_procs // 2)
        for proc in supervisors:
            remaining = max(5.0, deadline - time.monotonic())
            try:
                proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                pytest.fail(
                    "supervisor did not exit; logs:\n"
                    + "\n".join(
                        (tmp_path / f"sup{i}.log").read_text()[-3000:]
                        for i in range(n_procs)
                    )
                )
        stop_poll.set()
        poller.join(timeout=5)

        for i, proc in enumerate(supervisors):
            assert proc.returncode == 0, (
                f"supervisor {i} rc={proc.returncode}:\n"
                + (tmp_path / f"sup{i}.log").read_text()[-3000:]
            )

        # the fault actually fired
        assert (tmp_path / "crash-sentinel").exists()

        outs = []
        for idx in range(n_procs):
            out_path = tmp_path / f"out{idx}.json"
            assert out_path.exists(), (
                f"worker {idx} never finished:\n"
                + (tmp_path / f"sup{idx}.log").read_text()[-3000:]
            )
            outs.append(json.loads(out_path.read_text()))

        # every worker completed the SAME run and resumed mid-stream
        # (a from-scratch restart would report resumed_from == 0)
        for out in outs:
            assert out["resumed_from"] > 0, out
            assert out["final_loss"] == pytest.approx(
                outs[0]["final_loss"], abs=1e-5
            )

        # loss parity with a single-process run over the identical
        # global batch schedule
        base_out = tmp_path / "baseline.json"
        baseline = subprocess.run(
            [sys.executable, WORKER,
             "--process-id", "0", "--num-processes", "1",
             "--steps", str(STEPS),
             "--global-batch", str(GLOBAL_BATCH),
             "--checkpoint-dir", str(tmp_path / "ckpt-base"),
             "--out", str(base_out)]
            + (["--tp", "1"] if tp else []),  # same code path as pod
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=240,
        )
        assert baseline.returncode == 0, baseline.stderr[-2000:]
        base = json.loads(base_out.read_text())
        assert outs[0]["final_loss"] == pytest.approx(
            base["final_loss"], abs=1e-4
        )
        assert outs[0]["params_digest"] == pytest.approx(
            base["params_digest"], rel=1e-5
        )

        # the crash was catalog-visible: the crashing trainer was in
        # the passing set, fell out (stale heartbeat -> failing health
        # exec -> TTL lapse -> critical), and returned once the
        # reincarnated pod resumed stepping
        saw_present = saw_gap_after_present = saw_return = False
        for _, present in timeline:
            if present and not saw_present:
                saw_present = True
            elif saw_present and not present:
                saw_gap_after_present = True
            elif saw_gap_after_present and present:
                saw_return = True
        assert saw_present and saw_gap_after_present and saw_return, (
            f"catalog timeline never showed a restart gap: "
            f"{[(round(t, 1), p) for t, p in timeline]}"
        )
    finally:
        stop_poll.set()
        for proc in supervisors:
            if proc.poll() is None:
                proc.terminate()
        for proc in supervisors:
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
        catalog.terminate()
        catalog.wait(timeout=10)
        for fh in logs:
            fh.close()
