"""Watch tests: poll-driven change events (reference: watches/watches_test.go)."""
import asyncio

import pytest

from containerpilot_tpu.discovery import (
    FileCatalogBackend,
    NoopBackend,
    ServiceRegistration,
)
from containerpilot_tpu.events import Event, EventBus, EventCode
from containerpilot_tpu.watches import Watch, WatchConfig, WatchConfigError


def test_watch_config_prefixes_name():
    cfg = WatchConfig({"name": "backend", "interval": 5}).validate(NoopBackend())
    assert cfg.name == "watch.backend"
    assert cfg.service_name == "backend"


def test_watch_config_requires_interval():
    with pytest.raises(WatchConfigError):
        WatchConfig({"name": "backend"}).validate(NoopBackend())


def test_watch_config_rejects_unknown_keys():
    with pytest.raises(WatchConfigError):
        WatchConfig({"name": "b", "interval": 1, "poll": 2})


def test_watch_publishes_on_change(run):
    async def scenario():
        disc = NoopBackend()
        bus = EventBus()
        cfg = WatchConfig({"name": "backend", "interval": 1}).validate(disc)
        watch = Watch(cfg)
        watch.poll = 0.03  # speed up
        watch.run(bus)
        await asyncio.sleep(0.1)  # several polls, no change
        quiet = list(bus.debug_events())
        disc.val = True  # upstream becomes healthy
        await asyncio.sleep(0.1)
        after_up = list(bus.debug_events())
        disc.val = False  # upstream goes away
        await asyncio.sleep(0.1)
        after_down = list(bus.debug_events())
        watch.stop()
        await bus.wait()
        return quiet, after_up, after_down

    quiet, after_up, after_down = run(scenario())
    assert quiet == []  # no change -> no events
    assert Event(EventCode.STATUS_CHANGED, "watch.backend") in after_up
    assert Event(EventCode.STATUS_HEALTHY, "watch.backend") in after_up
    assert Event(EventCode.STATUS_UNHEALTHY, "watch.backend") in after_down


def test_watch_against_file_catalog(run, tmp_path):
    """A watch sees another host's registration appear in the shared
    file catalog — the TPU-pod cross-host discovery path."""

    async def scenario():
        catalog = FileCatalogBackend(str(tmp_path))
        other_host = FileCatalogBackend(str(tmp_path))  # same shared dir
        bus = EventBus()
        cfg = WatchConfig({"name": "trainer", "interval": 1}).validate(catalog)
        watch = Watch(cfg)
        watch.poll = 0.03
        watch.run(bus)
        await asyncio.sleep(0.08)
        # "another host" registers + heartbeats its trainer
        reg = ServiceRegistration(
            id="trainer-host7", name="trainer", port=4000,
            address="10.0.0.7", ttl=10,
        )
        other_host.service_register(reg, status="passing")
        await asyncio.sleep(0.1)
        events = list(bus.debug_events())
        watch.stop()
        await bus.wait()
        return events

    events = run(scenario())
    assert Event(EventCode.STATUS_CHANGED, "watch.trainer") in events
    assert Event(EventCode.STATUS_HEALTHY, "watch.trainer") in events
