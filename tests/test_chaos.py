"""Chaos harness tests: trace/SLO units, the gateway's catalog-flap
hold-down and jittered retries, the all-replicas-down path, the fault
injectors, and the quick chaos scenarios against a REAL fleet (the
tier-1 under-fire invariants: SIGKILL with spare capacity, wedged
health check, catalog flap, slow replica + hedging).

Long compound scenarios are ``slow``-marked: tier-1 runs the quick
ones, ``make chaos`` runs everything.
"""
import asyncio
import dataclasses
import json
import urllib.error
import urllib.request

import pytest

from containerpilot_tpu.chaos import (
    SLO,
    ChaosProxy,
    FlakyBackend,
    RequestRecord,
    ScenarioScore,
    SCENARIOS,
    TraceConfig,
    generate_trace,
    trace_summary,
)
from containerpilot_tpu.discovery import (
    FileCatalogBackend,
    NoopBackend,
    ServiceRegistration,
)
from containerpilot_tpu.fleet import FleetGateway, FleetMember
from containerpilot_tpu.fleet.gateway import Replica
from containerpilot_tpu.utils.http import HTTPServer, Response


def _post(port, path, payload, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode(), dict(exc.headers)


def _register(backend, instance_id, port, name="svc"):
    backend.service_register(
        ServiceRegistration(
            id=instance_id, name=name, port=port, ttl=60,
            address="127.0.0.1",
        ),
        status="passing",
    )


# -- trace generator (no JAX, no servers) -------------------------------


def test_trace_is_deterministic_under_a_seed():
    cfg = TraceConfig(seed=11)
    a = generate_trace(cfg)
    b = generate_trace(cfg)
    assert [vars(r) for r in a] == [vars(r) for r in b]
    c = generate_trace(TraceConfig(seed=12))
    assert [vars(r) for r in a] != [vars(r) for r in c]
    # arrivals are ordered and inside the horizon
    times = [r.at_s for r in a]
    assert times == sorted(times)
    assert all(0 <= t < cfg.duration_s for t in times)


def test_trace_has_the_advertised_structure():
    cfg = TraceConfig(seed=3, duration_s=6.0, mean_rps=20.0)
    requests = generate_trace(cfg)
    summary = trace_summary(requests)
    assert summary["requests"] > 50
    assert summary["streams"] > 0 and summary["abandons"] > 0
    assert 0 < summary["burst_requests"] < summary["requests"]
    # multi-tenant sessions share prefixes: two requests of one
    # session open with identical tokens (tenant + session prefix)
    by_session = {}
    for r in requests:
        by_session.setdefault(r.session_id, []).append(r)
    multi = [rs for rs in by_session.values() if len(rs) > 1]
    assert multi, "trace never revisited a session"
    prefix = cfg.tenant_prefix + cfg.session_prefix
    for rs in multi:
        first = rs[0].tokens[:prefix]
        assert all(r.tokens[:prefix] == first for r in rs)
    # prompt lengths are quantized (bounded compile set) but still
    # spread across buckets (the lognormal tail survives)
    lengths = {len(r.tokens) for r in requests}
    assert all(length % cfg.prompt_quantum == 0 for length in lengths)
    assert len(lengths) > 1
    assert max(len(r.tokens) for r in requests) <= cfg.max_prompt
    # per-request seeds are unique (retries must be idempotent, but
    # distinct requests must not share a sampling stream)
    seeds = [r.seed for r in requests]
    assert len(set(seeds)) == len(seeds)


# -- SLO scorer (pure) --------------------------------------------------


def test_slo_scorer_goodput_and_failure_ledger():
    slo = SLO(ttft_s=0.5, tpot_s=0.1)
    records = [
        # good: fast TTFT, fine decode rate
        RequestRecord(0, "s0", 0.0, 1.0, status=200, ttft_s=0.1,
                      tokens_out=10),
        # bad: TTFT blown
        RequestRecord(1, "s1", 0.0, 2.0, status=200, ttft_s=1.0,
                      tokens_out=4),
        # bad: TPOT blown (0.9s residual over 4 tokens -> 0.3/token)
        RequestRecord(2, "s2", 0.0, 1.0, status=200, ttft_s=0.1,
                      tokens_out=4),
        # bad: 5xx
        RequestRecord(3, "s3", 0.0, 0.1, status=503),
        # bad: transport error
        RequestRecord(4, "s4", 0.0, 0.1, error="ConnectionError"),
        # bad: truncated stream
        RequestRecord(5, "s5", 0.0, 0.4, status=200, ttft_s=0.1,
                      tokens_out=3, stream=True, truncated=True),
        # good: abandoned stream that met TTFT — hanging up is the
        # client's choice, and a TPOT over the tiny delivered window
        # (here 0.2/token, over the SLO) is noise, not decode rate
        RequestRecord(6, "s6", 0.0, 0.3, status=200, ttft_s=0.1,
                      tokens_out=2, stream=True, abandoned=True),
    ]
    score = ScenarioScore(records, wall_s=2.0, slo=slo).as_dict()
    assert score["requests"] == 7
    assert score["good"] == 2
    assert score["goodput_rps"] == 1.0  # 2 good / 2s
    assert score["count_5xx"] == 1
    assert score["transport_errors"] == 1
    assert score["truncated_streams"] == 1
    assert score["abandoned_streams"] == 1
    assert score["statuses"]["error"] == 1
    # the triage ledger names the bad requests, abandons excluded
    failed_indices = {f["index"] for f in score["failures"]}
    assert failed_indices == {1, 2, 3, 4, 5}
    json.dumps(score)  # report must be JSON-able


def test_tpot_math():
    r = RequestRecord(0, "s", 0.0, 1.1, status=200, ttft_s=0.1,
                      tokens_out=11)
    assert abs(r.tpot() - 0.1) < 1e-9
    # one token has no inter-token gap
    assert RequestRecord(
        0, "s", 0.0, 1.0, status=200, ttft_s=0.5, tokens_out=1
    ).tpot() is None


# -- gateway hold-down + jitter (no servers) ----------------------------


class _EmptyBackend(NoopBackend):
    """Catalog that always answers empty-but-changed."""

    def check_for_upstream_changes(self, s, tag="", dc=""):
        return True, False

    def instances(self, s, tag=""):
        return []


def _two_replicas():
    return {
        "a": Replica("a", "h", 1),
        "b": Replica("b", "h", 2),
    }


def test_holddown_damps_transient_empty_polls(run):
    gw = FleetGateway(
        _EmptyBackend(), "svc", empty_poll_threshold=3
    )
    gw._replicas = _two_replicas()

    async def scenario():
        await gw._poll_once()
        assert gw.replica_count == 2 and gw.flaps_damped == 1
        await gw._poll_once()
        assert gw.replica_count == 2 and gw.flaps_damped == 2
        # third CONSECUTIVE empty poll: the emptiness is real
        await gw._poll_once()
        assert gw.replica_count == 0 and gw.flaps_damped == 2

    run(scenario(), timeout=30)


def test_holddown_window_resets_on_healthy_poll(run):
    """Two separate two-poll flaps with healthy polls between them
    must BOTH be damped — the consecutive-empties counter resets on
    any healthy poll, including the no-change early return."""
    backend = FlakyBackend(_HealthyStub())
    gw = FleetGateway(backend, "svc", empty_poll_threshold=3)
    gw._replicas = _two_replicas()

    async def scenario():
        backend.flap(2)
        await gw._poll_once()
        await gw._poll_once()
        assert gw.replica_count == 2 and gw.flaps_damped == 2
        # healthy poll (steady state, no change): window closes
        await gw._poll_once()
        assert gw.replica_count == 2
        backend.flap(2)
        await gw._poll_once()
        await gw._poll_once()
        # regression: these used to accumulate to 4 consecutive and
        # wipe the table mid-flap
        assert gw.replica_count == 2 and gw.flaps_damped == 4

    run(scenario(), timeout=30)


class _HealthyStub(NoopBackend):
    """Two healthy instances, steady state (no changes reported)."""

    def check_for_upstream_changes(self, s, tag="", dc=""):
        return False, True

    def instances(self, s, tag=""):
        from containerpilot_tpu.discovery import ServiceInstance

        return [
            ServiceInstance(id="a", name=s, address="h", port=1),
            ServiceInstance(id="b", name=s, address="h", port=2),
        ]


def test_flaky_backend_budget_is_per_poll_cycle():
    backend = FlakyBackend(_HealthyStub())
    backend.flap(2)
    # one poll cycle = check + re-list; exactly two cycles come up empty
    assert backend.check_for_upstream_changes("svc") == (True, False)
    assert backend.instances("svc") == []
    assert backend.check_for_upstream_changes("svc") == (True, False)
    assert backend.instances("svc") == []
    assert backend.check_for_upstream_changes("svc") == (False, True)
    assert len(backend.instances("svc")) == 2
    assert backend.flaps_served == 2


def test_retry_jitter_bounded_and_seeded():
    gw = FleetGateway(NoopBackend(), "svc", jitter_seed=42)
    delays = [gw._jittered(0.2) for _ in range(50)]
    # equal jitter: [backoff/2, backoff] at the default 0.5 fraction
    assert all(0.1 <= d <= 0.2 for d in delays)
    assert len(set(delays)) > 10, "jitter produced no spread"
    # seeded: two gateways draw identical sequences (reproducible runs)
    gw2 = FleetGateway(NoopBackend(), "svc", jitter_seed=42)
    assert [gw2._jittered(0.2) for _ in range(50)] == delays
    # jitter disabled -> the exact deterministic backoff
    plain = FleetGateway(NoopBackend(), "svc", retry_jitter=0.0)
    assert plain._jittered(0.2) == 0.2


# -- all replicas down: fast 503, no leak, full recovery ----------------


def test_all_replicas_down_fast_503_then_recovery(run, tmp_path):
    """Every replica dies: after the hold-down expires the gateway
    answers 503 + Retry-After immediately (no hang, no pooled
    connection left), and the next poll after replicas return
    restores routing."""
    import time

    backend = FileCatalogBackend(str(tmp_path))

    async def scenario():
        replicas = []
        for rid in ("aaa", "bbb"):
            server = HTTPServer()

            async def handler(_req):
                return Response(
                    200, b"{}", content_type="application/json"
                )

            server.route("POST", "/v1/generate", handler)
            await server.start_tcp("127.0.0.1", 0)
            _register(backend, rid, server.bound_port)
            replicas.append(server)
        gw = FleetGateway(
            backend, "svc", "127.0.0.1", 0,
            poll_interval=0.05, hedge=False, retry_backoff=0.01,
            empty_poll_threshold=2,
        )
        await gw.run()
        loop = asyncio.get_event_loop()
        status, _, _ = await loop.run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
        )
        assert status == 200

        # all replicas die at once (catalog records removed + servers
        # gone) — the hold-down damps the first empty poll, then the
        # table empties for real
        for rid in ("aaa", "bbb"):
            backend.service_deregister(rid)
        for server in replicas:
            await server.stop()
        for _ in range(100):
            if gw.replica_count == 0:
                break
            await asyncio.sleep(0.05)
        assert gw.replica_count == 0
        assert gw.flaps_damped >= 1

        # fast-fail: 503 + Retry-After with no upstream to hang on
        t0 = time.perf_counter()
        status, _, headers = await loop.run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
        )
        elapsed = time.perf_counter() - t0
        assert status == 503
        assert {k.lower(): v for k, v in headers.items()}["retry-after"]
        assert elapsed < 5.0, f"all-down 503 took {elapsed:.1f}s"
        # no pooled connections survive the prune
        assert gw._pool.idle_count("aaa") == 0
        assert gw._pool.idle_count("bbb") == 0

        # recovery: a replica comes back; the next polls re-route
        revived = HTTPServer()

        async def handler2(_req):
            return Response(200, b"{}", content_type="application/json")

        revived.route("POST", "/v1/generate", handler2)
        await revived.start_tcp("127.0.0.1", 0)
        _register(backend, "ccc", revived.bound_port)
        for _ in range(100):
            if gw.replica_count == 1:
                break
            await asyncio.sleep(0.05)
        assert gw.replica_count == 1
        status, _, _ = await loop.run_in_executor(
            None, _post, gw.port, "/v1/generate", {"tokens": [[1]]},
        )
        assert status == 200

        await gw.stop()
        await revived.stop()

    run(scenario(), timeout=120)


# -- fault injectors (no JAX) -------------------------------------------


def test_chaos_proxy_resets_mid_response(run):
    """The lossy-transport fault: the proxy forwards the request, then
    RSTs the response after its byte budget."""

    async def scenario():
        async def handle(reader, writer):
            await reader.readuntil(b"\r\n\r\n")
            writer.write(b"HTTP/1.1 200 OK\r\nContent-Length: 1000\r\n"
                         b"\r\n" + b"x" * 1000)
            await writer.drain()
            writer.close()

        upstream = await asyncio.start_server(handle, "127.0.0.1", 0)
        port = upstream.sockets[0].getsockname()[1]
        proxy = ChaosProxy("127.0.0.1", port)
        await proxy.start()

        async def fetch():
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", proxy.port
            )
            writer.write(b"GET / HTTP/1.1\r\n\r\n")
            await writer.drain()
            body = b""
            try:
                while True:
                    chunk = await reader.read(4096)
                    if not chunk:
                        break
                    body += chunk
            except ConnectionError:
                return body, True
            finally:
                writer.close()
            return body, False

        # pass-through first
        body, _reset = await fetch()
        assert body.endswith(b"x" * 100) and len(body) > 1000
        # armed: response cut at the budget
        proxy.reset_after_bytes = 100
        body, reset = await fetch()
        assert len(body) <= 100
        assert reset or len(body) < 1000  # RST or short read
        assert proxy.resets_injected == 1
        await proxy.stop()
        upstream.close()
        await upstream.wait_closed()

    run(scenario(), timeout=60)


def test_member_advertises_override_port(run, tmp_path):
    """The proxy seam: a member can advertise a port other than the
    server's bind (NAT, chaos transport proxies)."""
    backend = FileCatalogBackend(str(tmp_path))

    class _Stub:
        ready = True
        draining = False
        inflight = 0
        port = 7777

    async def scenario():
        member = FleetMember(
            _Stub(), backend, "svc", ttl=2, heartbeat_interval=0.05,
            instance_id="r1", advertise_port=8888,
        )
        await member.start()
        for _ in range(100):
            if backend.instances("svc"):
                break
            await asyncio.sleep(0.02)
        instances = backend.instances("svc")
        await member.stop()
        return instances

    instances = run(scenario(), timeout=30)
    assert [i.port for i in instances] == [8888]


# -- shed accounting + Retry-After honoring -----------------------------


def test_slo_scorer_counts_sheds_apart_from_failures():
    """A 429/504 shed with Retry-After is the overload design working:
    never good, never a 5xx failure, excluded from the triage ledger,
    and goodput-over-admitted ignores it."""
    slo = SLO(ttft_s=0.5, tpot_s=0.1)
    records = [
        RequestRecord(0, "s0", 0.0, 0.3, status=200, ttft_s=0.1,
                      tokens_out=4),
        RequestRecord(1, "s1", 0.0, 0.1, status=429, shed=True,
                      retry_after_quoted=True),
        RequestRecord(2, "s2", 0.0, 0.1, status=504, shed=True,
                      retry_after_quoted=True, client_retries=1),
        # a REAL 5xx still counts as failure
        RequestRecord(3, "s3", 0.0, 0.1, status=503),
        # a 503 politely retried into a 200 was still SEEN: counted
        RequestRecord(4, "s4", 0.0, 0.3, status=200, ttft_s=0.1,
                      tokens_out=4, saw_5xx=True, client_retries=1),
    ]
    score = ScenarioScore(records, wall_s=1.0, slo=slo).as_dict()
    assert score["sheds"] == 2
    assert score["shed_429"] == 1 and score["shed_504"] == 1
    # the 503 and the retried-away 503 — never the shed 504
    assert score["count_5xx"] == 2
    assert score["goodput_fraction"] == 0.4  # 2 good of 5
    # first-contact admissions = the clean 200 and the 503 (no shed,
    # no client retry); the retried record is accounted elsewhere
    assert score["goodput_fraction_admitted"] == 0.5
    assert score["client_retries"] == 2
    assert {f["index"] for f in score["failures"]} == {3}
    # shed answers' millisecond TTFTs stay out of the percentiles
    shedded = ScenarioScore(
        [
            RequestRecord(0, "s", 0.0, 1.0, status=200, ttft_s=0.5,
                          tokens_out=2),
            RequestRecord(1, "s", 0.0, 0.002, status=429, shed=True,
                          retry_after_quoted=True, ttft_s=0.001),
        ],
        wall_s=1.0, slo=slo,
    ).as_dict()
    assert shedded["ttft_ms"]["p50"] == 500.0
    json.dumps(score)


def test_client_honors_retry_after_then_succeeds(run):
    """A shed answer with Retry-After is retried after a jittered
    fraction of the quoted delay (never immediately: retry storms must
    desynchronize), and the eventual 200 is recorded with the retry
    count."""
    import time as time_mod

    from containerpilot_tpu.chaos.client import issue_request
    from containerpilot_tpu.chaos.trace import TraceRequest

    async def scenario():
        hits = []
        server = HTTPServer()

        async def handler(_req):
            hits.append(time_mod.monotonic())
            if len(hits) == 1:
                return Response(
                    429, b"shed\n", headers={"Retry-After": "1"}
                )
            return Response(
                200, b'{"tokens": [[1, 2]]}',
                content_type="application/json",
            )

        server.route("POST", "/v1/generate", handler)
        await server.start_tcp("127.0.0.1", 0)
        req = TraceRequest(
            index=0, at_s=0.0, session_id="s", tenant=0,
            tokens=[1, 2], max_new_tokens=2, seed=123,
        )
        record = await issue_request(
            server.bound_port, req, time_mod.monotonic()
        )
        await server.stop()
        assert record.status == 200 and not record.shed
        assert record.client_retries == 1
        assert record.tokens_out == 2
        # equal jitter on a 1s hint: the re-send waits [0.5, 1.0]s
        assert len(hits) == 2
        assert 0.4 <= hits[1] - hits[0] <= 1.5

    run(scenario(), timeout=60)


def test_client_marks_final_shed_and_never_retries_504(run):
    """A 504 (deadline already blown) is never re-sent; with
    Retry-After quoted it lands as a shed, not a failure."""
    import time as time_mod

    from containerpilot_tpu.chaos.client import issue_request
    from containerpilot_tpu.chaos.trace import TraceRequest

    async def scenario():
        hits = [0]
        server = HTTPServer()

        async def handler(_req):
            hits[0] += 1
            return Response(
                504, b"deadline\n", headers={"Retry-After": "2"}
            )

        server.route("POST", "/v1/generate", handler)
        await server.start_tcp("127.0.0.1", 0)
        req = TraceRequest(
            index=0, at_s=0.0, session_id="s", tenant=0,
            tokens=[1], max_new_tokens=1, seed=7,
        )
        record = await issue_request(
            server.bound_port, req, time_mod.monotonic()
        )
        await server.stop()
        assert record.status == 504
        assert record.shed and record.client_retries == 0
        assert hits[0] == 1

    run(scenario(), timeout=60)


def test_trace_batch_priority_is_seeded_and_optional():
    cfg = TraceConfig(seed=4, batch_fraction=0.4)
    requests = generate_trace(cfg)
    batch = [r for r in requests if r.priority == "batch"]
    assert 0 < len(batch) < len(requests)
    assert trace_summary(requests)["batch"] == len(batch)
    # batch_fraction=0 draws nothing: pre-existing traces replay
    # byte-identically seed-for-seed
    plain = generate_trace(TraceConfig(seed=4))
    assert all(r.priority == "interactive" for r in plain)
    assert [r.tokens for r in plain] == [
        r.tokens for r in generate_trace(TraceConfig(seed=4))
    ]


def test_multiturn_trace_grows_shared_prefixes():
    """Multi-turn mode: each session is a conversation whose turn
    k prompt is a STRICT prefix of turn k+1's (the prefix-reuse
    regime), deterministic under a seed, bounded by the context
    window, with think gaps over the floor."""
    cfg = TraceConfig(
        seed=9, multiturn=True, duration_s=2.0,
        turns_per_session=5, think_time_s=0.3, think_floor_s=0.25,
        max_prompt=56, first_turn_min=16,
    )
    a = generate_trace(cfg)
    assert [vars(r) for r in a] == [
        vars(r) for r in generate_trace(cfg)
    ]
    assert [r.index for r in a] == list(range(len(a)))
    assert [r.at_s for r in a] == sorted(r.at_s for r in a)
    by_session = {}
    for r in a:
        by_session.setdefault(r.session_id, []).append(r)
    multi = [rs for rs in by_session.values() if len(rs) > 1]
    assert multi, "no session got a second turn"
    for rs in multi:
        for prev, cur in zip(rs, rs[1:]):
            assert len(prev.tokens) < len(cur.tokens) <= cfg.max_prompt
            assert cur.tokens[: len(prev.tokens)] == prev.tokens
            assert cur.at_s - prev.at_s >= cfg.think_floor_s
        assert len(rs[0].tokens) >= cfg.first_turn_min
    # turns count toward the cap but stop at the context window
    assert all(len(rs) <= cfg.turns_per_session for rs in by_session.values())
    # multiturn=False draws nothing new from the rng: pre-existing
    # traces replay byte-identically
    assert [r.tokens for r in generate_trace(TraceConfig(seed=4))] == [
        r.tokens for r in generate_trace(TraceConfig(seed=4))
    ]


# -- the quick scenarios: a real fleet under fire (tier-1) --------------


def _run_scenario_checked(name, tmp_path, seed=5):
    from containerpilot_tpu.chaos import run_scenario

    report = run_scenario(name, str(tmp_path), seed=seed)
    assert report["passed"], json.dumps(report["checks"], indent=2)
    assert report["score"]["count_5xx"] == 0
    assert report["score"]["transport_errors"] == 0
    # loopcheck rode along: the lag bound was asserted as a check,
    # the schema carries the gated number, and no task died unseen
    check_names = {c["name"] for c in report["checks"]}
    assert "loop_lag" in check_names
    assert report["loop_lag_max_ms"] == report["loop"]["lag_max_ms"]
    assert report["loop"]["heartbeats"] > 0
    assert report["loop"]["task_exceptions"] == []
    # the device-time ledger rode along (telemetry/goodput.py):
    # schema-stable stages, internally consistent sums, per-replica
    # breakdown present for every replica the scenario ever booted
    ledger = report["goodput_ledger"]
    assert set(ledger["stages_s"]) == {
        "boot", "compile_warmup", "idle", "prefill", "decode",
        "kv_readmit", "drain",
    }
    assert ledger["device_seconds"] == pytest.approx(
        sum(ledger["stages_s"].values()), abs=0.05
    )
    assert ledger["per_replica"]
    for entry in ledger["per_replica"].values():
        assert set(entry) == {
            "departed", "productive_fraction", "stages_s",
        }
    json.dumps(report)  # the whole report is JSON-able
    return report


def test_scenario_kill_with_spare_capacity(tmp_path):
    """SIGKILL one of three replicas mid-trace: zero client-visible
    5xx, and the corpse TTL-expires out of catalog + routing."""
    report = _run_scenario_checked("kill_spare", tmp_path)
    assert report["gateway"]["replicas_at_end"] == 2
    # the run is the seeded trace, reproducibly
    spec = SCENARIOS["kill_spare"]
    expected = trace_summary(
        generate_trace(dataclasses.replace(spec.trace, seed=5))
    )
    assert report["trace"] == expected


def test_scenario_wedged_health_check(tmp_path):
    """A replica stops heartbeating (wedged health): its record goes
    catalog-critical by TTL and traffic routes around it."""
    report = _run_scenario_checked("wedged_health", tmp_path)
    assert report["gateway"]["replicas_at_end"] == 1


def test_scenario_catalog_flap_and_cli(tmp_path):
    """Catalog flaps mid-trace: the hold-down damps them with zero
    5xx — driven through the CLI so its report plumbing is covered."""
    from containerpilot_tpu.chaos.__main__ import main

    out = tmp_path / "report.json"
    rc = main([
        "--scenario", "catalog_flap", "--seed", "5",
        "--json", str(out),
    ])
    assert rc == 0
    payload = json.loads(out.read_text())
    assert payload["passed"] is True
    report = payload["scenarios"][0]
    assert report["score"]["count_5xx"] == 0
    assert report["gateway"]["catalog_flaps_damped"] >= 2
    assert report["gateway"]["replicas_at_end"] == 2
    assert {"goodput_rps", "ttft_ms", "tpot_ms"} <= set(
        report["score"]
    )


def test_scenario_slow_replica_hedging_bounds_p99(tmp_path):
    """One replica browns out: hedging fires (hedged > 0) and keeps
    scenario p99 TTFT bounded, goodput above its floor."""
    report = _run_scenario_checked("slow_replica", tmp_path)
    assert report["gateway"]["hedged"] >= 1
    spec = SCENARIOS["slow_replica"]
    assert (
        report["score"]["goodput_fraction"]
        >= spec.min_goodput_fraction
    )
    assert report["score"]["ttft_ms"]["p99"] <= spec.max_ttft_p99_ms


def test_scenario_abandoned_streams_mux(tmp_path):
    """Abandoned SSE clients under the mux transport: every abandon
    becomes a CANCEL frame (stream id freed, shared connection kept),
    co-resident streams see zero 5xx, and the run records connection
    teardowns avoided."""
    report = _run_scenario_checked("abandoned_streams_mux", tmp_path)
    gw = report["gateway"]
    assert gw["mux_streams"] >= 1  # the trace actually rode mux
    assert gw["mux_cancels"] >= 1  # abandons became CANCEL frames
    assert gw["conns_saved_by_mux"] >= 3
    assert report["score"]["abandoned_streams"] >= 1
    # abandons retried nothing: a CANCEL is not a failure
    assert report["score"]["count_5xx"] == 0


def test_scenario_burst_10x_sheds_honestly(tmp_path):
    """The overload invariant: a 10x burst over a browned-out fleet
    yields ZERO client-visible 5xx — every refusal is a 429/504 shed
    carrying a drain-rate-derived Retry-After — and the work the
    fleet admitted still meets its SLOs."""
    report = _run_scenario_checked("burst_10x", tmp_path)
    score = report["score"]
    assert score["sheds"] >= 1
    assert score["goodput_fraction_admitted"] >= 0.8
    admission = report["gateway"]["admission"]
    assert admission["shed_overload"] + admission["deadline_expired"] >= 1
    # clients honored Retry-After instead of hammering
    assert score["client_retries"] >= 1


def test_scenario_burst_10x_standby_outruns_part_of_the_burst(tmp_path):
    """The cold-start collapse under the SAME burst: a warm standby
    is promoted into the sustained pressure (capacity grows in ~a
    poll interval instead of a full boot), admitted work keeps its
    SLOs, zero client-visible 5xx — and the shed count against
    burst_10x's in the same suite report is the release-over-release
    yardstick (105 -> 53 at the suite seed; a light seed may shed
    zero, which is the point)."""
    report = _run_scenario_checked("burst_10x_standby", tmp_path)
    scaler = report["autoscaler"]
    assert scaler["standby"]["standby_count"] == 1
    assert scaler["standby"]["promotions"] >= 1
    promoted = [
        e for e in report["goodput_ledger"]["scale_events"]
        if e["direction"] == "up" and e.get("mode") == "promoted"
    ]
    assert promoted


def test_scenario_kill_under_burst_autoscaled(tmp_path):
    """The capacity loop under fire: a replica dies inside the burst
    (autoscaler repairs the min), pressure launches a replica that
    registers AND takes traffic, the idle tail drains back to min,
    and injected catalog flaps cause no scale thrash."""
    report = _run_scenario_checked(
        "kill_under_burst_autoscaled", tmp_path
    )
    scaler = report["autoscaler"]
    assert scaler["scale_ups"] >= 1
    assert scaler["scale_downs"] >= 1
    assert scaler["replicas"] == scaler["min_replicas"] == 2
    assert scaler["scale_ups"] + scaler["scale_downs"] <= 8
    # a launched replica (index past the boot set) was routed to
    routed = report["gateway"]["routed"]
    assert any(
        count > 0
        for rid, count in routed.items()
        if int(rid.rsplit("-", 1)[1]) >= 2
    )
    assert report["gateway"]["catalog_flaps_damped"] >= 1
    # the cold-start yardstick: every scale decision is stamped into
    # the ledger, and at least one launch carries a finite
    # time-to-first-routed-token (the expect_scale_up_ttfrt check
    # gated it; assert the schema here too)
    events = report["goodput_ledger"]["scale_events"]
    ups = [e for e in events if e["direction"] == "up"]
    assert len(ups) >= 1
    assert any(e.get("ttfrt_s") is not None for e in ups)


def test_scenario_kill_under_burst_promoted(tmp_path):
    """The cold-start collapse proof: with slow_boot armed (+2s on
    every NEW launch), a kill inside the burst is repaired by
    PROMOTING the warm standby — the promoted scale-up's TTFRT clears
    the stated 2.0s bound a slow-booted cold launch could not, the
    background refill absorbs the slow boot off the critical path,
    and the run stays at zero client-visible 5xx."""
    report = _run_scenario_checked(
        "kill_under_burst_promoted", tmp_path
    )
    scaler = report["autoscaler"]
    assert scaler["standby"]["promotions"] >= 1
    assert scaler["replicas"] == scaler["min_replicas"] == 2
    # the tightened yardstick: every promoted launch that served has
    # a finite TTFRT at or under the bound (the spec check gated it;
    # pin the schema + split here)
    events = report["goodput_ledger"]["scale_events"]
    promoted = [
        e for e in events
        if e["direction"] == "up" and e.get("mode") == "promoted"
    ]
    assert promoted
    finite = [
        e["ttfrt_s"] for e in promoted
        if e.get("ttfrt_s") is not None
    ]
    assert finite and max(finite) <= 2.0
    check_names = {c["name"] for c in report["checks"]}
    assert "promoted_ttfrt_bound" in check_names
    assert "standby_promotions" in check_names
    # the slow_boot fault actually fired (it is in the ledger)
    assert report["fault_counts"].get("slow_boot") == 1


def test_slow_boot_fault_is_armed_for_future_launches(run, tmp_path):
    """The slow_boot verb arms harness state for replicas launched
    AFTER it — existing replicas are untouched (their warmup already
    happened), which is exactly the production cold-start shape."""
    from containerpilot_tpu.chaos.scenarios import FleetHarness
    from containerpilot_tpu.chaos.faults import Fault

    async def scenario():
        harness = FleetHarness(str(tmp_path / "catalog"), replicas=1)
        await harness.start()
        try:
            await harness.apply(
                Fault(at_s=0.0, kind="slow_boot", value=0.5)
            )
            assert harness.slow_boot_s == 0.5
            import time as time_mod

            t0 = time_mod.monotonic()
            rid = await harness.spawn_replica()
            boot_s = time_mod.monotonic() - t0
            assert boot_s >= 0.5
            index = int(rid.rsplit("-", 1)[1])
            ledger = harness.servers[index].ledger.totals()
            assert ledger["compile_warmup"] >= 0.5
            # disarm: the next launch is fast again (no hook)
            await harness.apply(
                Fault(at_s=0.0, kind="slow_boot", value=0.0)
            )
            rid2 = await harness.spawn_replica()
            index2 = int(rid2.rsplit("-", 1)[1])
            assert harness.servers[index2].chaos_hook is None
        finally:
            await harness.stop()

    run(scenario(), timeout=120)


def test_scenario_multiturn_rebalance(tmp_path):
    """The KV-reuse proof: multi-turn conversations against a bounded
    sticky table while a replica drains mid-conversation. Cache-aware
    routing lands re-pinned sessions on digest-warm survivors (hint
    hits), the host-RAM spill tier readmits what the 2-entry device
    LRU evicted between turns, and the fleet reuses prefix tokens —
    all with zero client-visible 5xx."""
    report = _run_scenario_checked("multiturn_rebalance", tmp_path)
    kv = report["kv"]
    assert kv["cache_hint_hits"] >= 1
    assert kv["readmitted"] >= 1
    assert kv["spilled"] >= 1
    assert kv["tokens_reused"] >= 100
    assert kv["tokens_reused_per_prompt_token"] > 0
    # the sticky bound did its job under 9 sessions / capacity 2
    sticky = report["gateway"]["sticky"]
    assert sticky["capacity"] == 2 and sticky["size"] <= 2
    assert sticky["evicted"] >= 1
    # the drained replica's absence from catalog + routing and the
    # zero-5xx bar are covered by the spec checks (report["passed"])


# -- the compound marathons (make chaos) --------------------------------


@pytest.mark.slow
def test_scenario_lossy_transport(tmp_path):
    report = _run_scenario_checked("lossy_transport", tmp_path)
    assert report["gateway"]["proxy_resets"] >= 1


@pytest.mark.slow
def test_scenario_kill_under_burst(tmp_path):
    report = _run_scenario_checked("kill_under_burst", tmp_path)
    assert report["gateway"]["replicas_at_end"] == 2
    assert report["gateway"]["catalog_flaps_damped"] >= 1


@pytest.mark.slow
def test_scenario_rolling_chaos(tmp_path):
    report = _run_scenario_checked("rolling_chaos", tmp_path)
    assert report["gateway"]["replicas_at_end"] == 2
