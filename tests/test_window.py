"""Sliding-window attention: masks, flash kernels, ring KV cache.

Mistral-style local attention (TransformerConfig.window): position i
attends j iff i - window < j <= i. The decode cache becomes a ring of
`window` slots, so KV memory is bounded by the window, not the
generation length. No reference analog (the reference is a supervisor,
SURVEY.md §2); this is workload-half model-family coverage.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from containerpilot_tpu.models.decode import (
    decode_chunk,
    decode_step,
    generate,
    init_cache,
    prefill,
)
from containerpilot_tpu.models.transformer import (
    TransformerConfig,
    forward,
    init_params,
)
from containerpilot_tpu.ops.attention import causal_attention
from containerpilot_tpu.ops.flash import flash_attention


def _cfg(window, **kw):
    base = dict(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=256, dtype=jnp.float32, flash_min_seq=0,
        window=window,
    )
    base.update(kw)
    return TransformerConfig(**base)


def test_windowed_mask_matches_bruteforce():
    """causal_attention(window=W) == explicit mask reference."""
    rng = jax.random.PRNGKey(0)
    q, k, v = (
        jax.random.normal(kk, (2, 48, 4, 16), jnp.float32)
        for kk in jax.random.split(rng, 3)
    )
    W = 16
    got = causal_attention(q, k, v, window=W)
    s = q.shape[1]
    idx = np.arange(s)
    mask = (idx[None, :] <= idx[:, None]) & (idx[None, :] > idx[:, None] - W)
    scores = np.einsum("bqhk,bshk->bhqs", np.asarray(q), np.asarray(k))
    scores = scores * (16 ** -0.5)
    scores = np.where(mask[None, None], scores, -1e30)
    w = np.exp(scores - scores.max(-1, keepdims=True))
    w /= w.sum(-1, keepdims=True)
    ref = np.einsum("bhqs,bshk->bqhk", w, np.asarray(v))
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-4, atol=1e-5)


def test_window_geq_seq_equals_full():
    rng = jax.random.PRNGKey(1)
    q, k, v = (
        jax.random.normal(kk, (1, 32, 2, 16), jnp.float32)
        for kk in jax.random.split(rng, 3)
    )
    full = causal_attention(q, k, v)
    win = causal_attention(q, k, v, window=32)
    np.testing.assert_allclose(
        np.asarray(full), np.asarray(win), rtol=0, atol=0
    )


def test_windowed_flash_matches_xla_fwd_and_grads():
    """The pallas kernels' block-skip + in-block window mask agree with
    the einsum path for value and all three gradients, including
    mismatched block sizes and a window that skips whole blocks."""
    rng = jax.random.PRNGKey(2)
    q, k, v = (
        jax.random.normal(kk, (2, 512, 4, 64), jnp.float32)
        for kk in jax.random.split(rng, 3)
    )
    W = 128
    ref = causal_attention(q, k, v, window=W)
    got = flash_attention(q, k, v, block_q=128, block_k=64, window=W)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(got), rtol=1e-4, atol=1e-5
    )
    for argi in range(3):
        def lf(x, fn, argi=argi):
            args = [q, k, v]
            args[argi] = x
            return (fn(*args) ** 2).sum()

        ga = jax.grad(
            lambda x: lf(x, lambda *a: causal_attention(*a, window=W))
        )([q, k, v][argi])
        gb = jax.grad(
            lambda x: lf(x, lambda *a: flash_attention(*a, window=W))
        )([q, k, v][argi])
        np.testing.assert_allclose(
            np.asarray(ga), np.asarray(gb), rtol=2e-4, atol=2e-4
        )


def test_windowed_forward_trains():
    """Training through the windowed model: finite loss, finite grads,
    and the windowed forward differs from full attention once seq >
    window (the mask is actually live)."""
    from containerpilot_tpu.models.transformer import loss_fn

    cfg = _cfg(window=8)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 33), 0, cfg.vocab_size, jnp.int32
    )
    loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
    assert np.isfinite(float(loss))
    for g in jax.tree_util.tree_leaves(grads):
        assert np.isfinite(np.asarray(g)).all()
    full = forward(params, tokens[:, :-1], _cfg(window=0))
    win = forward(params, tokens[:, :-1], cfg)
    assert not np.allclose(np.asarray(full), np.asarray(win))


@pytest.mark.parametrize("prompt_len", [4, 24])
def test_windowed_incremental_decode_matches_forward(prompt_len):
    """Ring-cache decode == windowed full forward at every position,
    with the prompt shorter AND longer than the window, decoding far
    enough that the ring wraps several times."""
    cfg = _cfg(window=8)
    params = init_params(jax.random.PRNGKey(0), cfg)
    b, total = 2, 40
    tokens = jax.random.randint(
        jax.random.PRNGKey(3), (b, total), 0, cfg.vocab_size, jnp.int32
    )
    ref_logits = forward(params, tokens, cfg)  # [b, total, vocab]

    logits, cache = prefill(params, tokens[:, :prompt_len], cfg, total)
    assert cache["k"].shape[2] == 8  # ring, not max_len
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits[:, prompt_len - 1]),
        rtol=2e-3, atol=2e-3,
    )
    for i in range(prompt_len, total):
        logits, cache = decode_step(params, cache, tokens[:, i], cfg)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(ref_logits[:, i]),
            rtol=2e-3, atol=2e-3,
            err_msg=f"position {i}",
        )


def test_windowed_decode_chunk_matches_steps():
    """Multi-token chunks through the ring equal single steps."""
    cfg = _cfg(window=8)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(4), (2, 30), 0, cfg.vocab_size, jnp.int32
    )
    _, cache_a = prefill(params, tokens[:, :6], cfg, 64)
    _, cache_b = prefill(params, tokens[:, :6], cfg, 64)
    # chunk of 5 (crosses the ring boundary at pos 6+5 > 8)
    chunk = tokens[:, 6:11]
    logits_a, cache_a = decode_chunk(params, cache_a, chunk, cfg)
    for i in range(5):
        logits_b, cache_b = decode_step(
            params, cache_b, chunk[:, i], cfg
        )
        np.testing.assert_allclose(
            np.asarray(logits_a[:, i]), np.asarray(logits_b),
            rtol=2e-3, atol=2e-3, err_msg=f"chunk index {i}",
        )
    np.testing.assert_allclose(
        np.asarray(cache_a["k"]), np.asarray(cache_b["k"]),
        rtol=1e-5, atol=1e-6,
    )
    with pytest.raises(ValueError, match="window ring"):
        decode_chunk(params, cache_a, tokens[:, :9], cfg)


def test_windowed_generate_greedy_matches_bruteforce():
    """End-to-end generate with a window: greedy tokens equal the
    brute-force argmax loop over the windowed full forward."""
    cfg = _cfg(window=8)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(
        jax.random.PRNGKey(5), (2, 12), 0, cfg.vocab_size, jnp.int32
    )
    out = generate(params, prompt, cfg, max_new_tokens=10, max_len=64)
    seq = np.asarray(prompt)
    for _ in range(10):
        logits = forward(params, jnp.asarray(seq), cfg)
        nxt = np.argmax(np.asarray(logits[:, -1]), axis=-1)
        seq = np.concatenate([seq, nxt[:, None].astype(np.int32)], axis=1)
    np.testing.assert_array_equal(np.asarray(out), seq[:, 12:])


def test_windowed_gqa_and_cache_shape():
    """GQA + window: the ring holds only kv heads x window slots."""
    cfg = _cfg(window=8, n_kv_heads=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(6), (1, 20), 0, cfg.vocab_size, jnp.int32
    )
    ref = forward(params, tokens, cfg)
    logits, cache = prefill(params, tokens[:, :10], cfg, 64)
    assert cache["k"].shape == (2, 1, 8, 2, 16)
    for i in range(10, 20):
        logits, cache = decode_step(params, cache, tokens[:, i], cfg)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(ref[:, i]),
            rtol=2e-3, atol=2e-3, err_msg=f"position {i}",
        )


def test_window_rejects_speculative_and_ring_contexts():
    """Destructive ring writes can't be rolled back, so speculative
    decoding (and ring attention) refuse windowed configs."""
    from containerpilot_tpu.models.speculative import (
        layer_prefix_draft,
        speculative_generate,
    )

    cfg = _cfg(window=8)
    params = init_params(jax.random.PRNGKey(0), cfg)
    draft_params, draft_cfg = layer_prefix_draft(params, cfg, 1)
    prompt = jnp.ones((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="sliding-window"):
        speculative_generate(
            params, draft_params, prompt, cfg, draft_cfg,
            max_new_tokens=4, max_len=32,
        )


@pytest.mark.parametrize("bq,bk,W", [(64, 128, 300), (128, 64, 300)])
def test_windowed_flash_mismatched_blocks_span_coverage(bq, bk, W):
    """Unequal block sizes with a window that is not block-aligned:
    the visited-block span must still cover every contributing block
    (regression: the original span formulas undercounted here,
    silently dropping in-window kv blocks)."""
    rng = jax.random.PRNGKey(7)
    q, k, v = (
        jax.random.normal(kk, (1, 1024, 2, 64), jnp.float32)
        for kk in jax.random.split(rng, 3)
    )
    ref = causal_attention(q, k, v, window=W)
    got = flash_attention(q, k, v, block_q=bq, block_k=bk, window=W)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(got), rtol=1e-4, atol=1e-5
    )
    ga = jax.grad(
        lambda k_: (causal_attention(q, k_, v, window=W) ** 2).sum()
    )(k)
    gb = jax.grad(
        lambda k_: (
            flash_attention(q, k_, v, block_q=bq, block_k=bk, window=W)
            ** 2
        ).sum()
    )(k)
    np.testing.assert_allclose(
        np.asarray(ga), np.asarray(gb), rtol=2e-4, atol=2e-4
    )


def test_truncated_ring_overflow_rejected():
    """window > max_len truncates the ring to max_len slots; wrapping
    such a ring would overwrite keys still inside the attention
    window, so generate_from_cache must apply the linear-cache
    overflow guard instead of the full-ring wrap exemption."""
    from containerpilot_tpu.models.decode import generate_from_cache

    cfg = _cfg(window=128)  # window wider than the serving max_len
    params = init_params(jax.random.PRNGKey(0), cfg)
    max_len = 16  # ring truncated: length = min(window, max_len) = 16
    prompt = jnp.ones((1, 8), jnp.int32)
    logits, cache = prefill(params, prompt, cfg, max_len)
    assert cache["k"].shape[2] == max_len  # truncated ring
    with pytest.raises(ValueError, match="exceeds cache length"):
        generate_from_cache(
            params, cache, logits, cfg, max_new_tokens=12, pos=8
        )
    # in-bounds decode still works
    out = generate_from_cache(
        params, cache, logits, cfg, max_new_tokens=4, pos=8
    )
    assert out.shape == (1, 4)


def test_full_ring_decodes_past_length():
    """A FULL ring (length == window) legally wraps: every overwritten
    slot is already outside the window."""
    from containerpilot_tpu.models.decode import generate_from_cache

    cfg = _cfg(window=8)
    params = init_params(jax.random.PRNGKey(0), cfg)
    max_len = 32  # ring length = window = 8 (full ring)
    prompt = jnp.ones((1, 4), jnp.int32)
    logits, cache = prefill(params, prompt, cfg, max_len)
    assert cache["k"].shape[2] == 8
    out = generate_from_cache(
        params, cache, logits, cfg, max_new_tokens=16, pos=4
    )
    assert out.shape == (1, 16)
