"""MFU denominator accounting: the attention span must be the mean
number of keys a query ACTUALLY attends to — billing the skipped
causal half would flatter MFU ~2x on exactly the configs where the
flash kernels skip it."""
import types

from containerpilot_tpu.workload.flops import (
    peak_flops,
    train_flops_per_token,
)


def _cfg(window=0, moe_experts=0):
    return types.SimpleNamespace(
        n_layers=4, d_model=256, d_ff=1024, window=window,
        moe_experts=moe_experts,
    )


def test_full_causal_attention_span_is_halved():
    cfg = _cfg()
    seq, n_params = 2048, 10_000_000
    got = train_flops_per_token(cfg, n_params, seq)
    # exact mean span over positions: (seq + 1) / 2
    expected = (
        6.0 * n_params
        + 12.0 * cfg.n_layers * cfg.d_model * (seq + 1) / 2.0
    )
    assert abs(got - expected) < 1.0


def test_windowed_attention_span_tracks_window():
    cfg = _cfg(window=256)
    seq, n_params = 4096, 10_000_000
    got = train_flops_per_token(cfg, n_params, seq)
    w = 256.0
    span = w - w * (w - 1.0) / (2.0 * seq)
    expected = 6.0 * n_params + 12.0 * cfg.n_layers * cfg.d_model * span
    assert abs(got - expected) < 1.0
    # windowed span ~= window, far below the full-causal span
    full = train_flops_per_token(_cfg(), n_params, seq)
    assert got < full


def test_window_wider_than_seq_equals_full_causal():
    assert train_flops_per_token(
        _cfg(window=8192), 1_000_000, 1024
    ) == train_flops_per_token(_cfg(), 1_000_000, 1024)


def test_frozen_params_bill_4_flops():
    cfg = _cfg()
    n = 1_000_000
    all_trained = train_flops_per_token(cfg, n, 128)
    all_frozen = train_flops_per_token(cfg, n, 128, n_frozen=n)
    assert abs((all_trained - all_frozen) - 2.0 * n) < 1.0


def test_peak_flops_known_generations():
    assert peak_flops("TPU v5 lite") == 197e12
    assert peak_flops("TPU v4") == 275e12
    assert peak_flops("weird-device") == 197e12
