"""Multi-host pod serving (workload/serve_dist.py): real OS processes
rendezvous through a live catalog server, shard the model over a
global mesh — pure TP at 2 processes, a 2x2 dp x tp mesh at 4 — and
answer HTTP byte-identically to a single-host server of the same
config. Failure detection: a wedged follower trips every process's
decode-progress watchdog (exit 86), and under supervision the pod
restarts, re-rendezvouses, and serves again."""
import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MODEL_FLAGS = [
    "--max-len", "48", "--d-model", "64", "--n-layers", "1",
    "--n-heads", "2", "--vocab", "128",
]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _sub_env() -> dict:
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # exactly 1 CPU device per process
    # conftest's in-process cache env must not leak: subprocess cache
    # behavior is controlled ONLY by CONTAINERPILOT_COMPILE_CACHE
    # (enable_compile_cache), so dedicated-cache tests stay cold
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env.pop("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", None)
    # pod boots across this suite recompile the same tiny-model
    # program sets; the workload CLIs' opt-in persistent compile
    # cache (modelcfg.enable_compile_cache) turns every boot after
    # the first into cache re-warms — exactly the crash->restart
    # path it exists for, and minutes off the suite on one core.
    # Shares conftest's per-user default dir (JAX_COMPILATION_CACHE_DIR
    # was set from it at session start) so one suite run warms both.
    env.setdefault(
        "CONTAINERPILOT_COMPILE_CACHE",
        os.environ.get(
            "JAX_COMPILATION_CACHE_DIR", "/tmp/cp_test_compile_cache"
        ),
    )
    return env


def _default_cfg():
    """The config MODEL_FLAGS describes — the ONE copy every parity
    check derives from."""
    from containerpilot_tpu.models.transformer import TransformerConfig
    from containerpilot_tpu.workload.modelcfg import derive_d_ff

    return TransformerConfig(
        vocab_size=128, d_model=64, n_heads=2, n_layers=1,
        d_ff=derive_d_ff(64), max_seq_len=48,
    )


def _reference(tokens, max_new, cfg=None, params=None, row=0, **kw):
    """Single-device generate with the server key convention — the
    ONE copy of the fold_in(PRNGKey(seed), row) + _trim parity recipe
    every pod test compares against (row i of an n-sample request
    draws from fold_in(seed, i))."""
    from containerpilot_tpu.models.decode import generate
    from containerpilot_tpu.models.transformer import init_params

    if cfg is None:
        cfg = _default_cfg()
    if params is None:
        params = init_params(jax.random.PRNGKey(0), cfg)
    seed = kw.pop("seed", 0)
    eos = kw.pop("eos_id", -1)
    out = generate(
        params, jnp.asarray([tokens], jnp.int32), cfg, max_new,
        cfg.max_seq_len,
        rng=jnp.stack(
            [jax.random.fold_in(jax.random.PRNGKey(seed), row)]
        ),
        eos_id=eos, **kw,
    )
    from containerpilot_tpu.workload.serve import InferenceServer

    out_row = [int(t) for t in np.asarray(out)[0]]
    return InferenceServer._trim([out_row], max_new, eos)[0]


def _write_cpu_wrapper(tmp_path):
    # the image's sitecustomize pins jax to the tunneled TPU in
    # every interpreter; the pod processes must pin CPU first
    wrapper = tmp_path / "serve_dist_cpu.py"
    wrapper.write_text(
        "import sys\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from containerpilot_tpu.workload.serve_dist import main\n"
        "sys.exit(main())\n"
    )
    return wrapper


def _wait_catalog(catalog_port):
    deadline = time.monotonic() + 30
    while True:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{catalog_port}/v1/health/service/x",
                timeout=1,
            )
            return
        except Exception:
            if time.monotonic() > deadline:
                pytest.fail("catalog never became ready")
            time.sleep(0.2)


def _wait_pod_healthy(base, procs, tmp_path, n_procs, deadline_s,
                      log_prefix="pod"):
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            urllib.request.urlopen(f"{base}/health", timeout=2)
            return
        except Exception:
            for i, proc in enumerate(procs):
                assert proc.poll() is None, (
                    tmp_path / f"{log_prefix}{i}.log"
                ).read_text()[-3000:]
            if time.monotonic() > deadline:
                pytest.fail(
                    "pod never became healthy:\n" + "\n".join(
                        (tmp_path / f"{log_prefix}{i}.log")
                        .read_text()[-2000:]
                        for i in range(n_procs)
                    )
                )
            time.sleep(0.5)


@pytest.mark.parametrize(
    "n_procs,dp", [(2, 1), (4, 2)], ids=["tp2", "dp2xtp2"]
)
def test_pod_serves_http(tmp_path, n_procs, dp):
    catalog_port, coord_port, http_port = (
        _free_port(), _free_port(), _free_port()
    )
    env = _sub_env()
    catalog = subprocess.Popen(
        [sys.executable, "-m", "containerpilot_tpu",
         "-catalog-server", f"127.0.0.1:{catalog_port}"],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    procs = []
    logs = []
    try:
        _wait_catalog(catalog_port)
        wrapper = _write_cpu_wrapper(tmp_path)
        for pid in range(n_procs):
            fh = open(tmp_path / f"pod{pid}.log", "w")
            logs.append(fh)
            procs.append(subprocess.Popen(
                [sys.executable, "-u", str(wrapper),
                 "--process-id", str(pid),
                 "--num-processes", str(n_procs),
                 "--catalog", f"127.0.0.1:{catalog_port}",
                 "--coordinator-port", str(coord_port),
                 "--advertise-address", "127.0.0.1",
                 "--host", "127.0.0.1", "--port", str(http_port),
                 "--dp", str(dp)]
                # tp2 also proves pod prefix reuse (lockstep LRU on
                # every process) AND chunked admission (the 20-token
                # history cold-prefills in 4-token pieces; the turn-2
                # hit's bucketed suffix takes extend_pieces under the
                # same bound); dp2xtp2 stays on one-shot admission
                + (["--prefix-cache", "2", "--prefill-chunk", "4"]
                   if n_procs == 2 else [])
                + MODEL_FLAGS,
                cwd=REPO, env=env, stdout=fh, stderr=subprocess.STDOUT,
            ))

        base = f"http://127.0.0.1:{http_port}"
        # the single-core box serializes n_procs startup compiles
        _wait_pod_healthy(
            base, procs, tmp_path, n_procs, 240 * max(1, n_procs // 2)
        )

        def post(body):
            req = urllib.request.Request(
                f"{base}/v1/generate",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=240) as resp:
                return json.loads(resp.read().decode())

        greedy = post({"tokens": [[1, 2, 3]], "max_new_tokens": 6})
        assert greedy["tokens"][0] == _reference([1, 2, 3], 6)

        sampled = post({
            "tokens": [[5, 6]], "max_new_tokens": 5,
            "temperature": 0.8, "top_k": 20, "seed": 9,
        })
        assert sampled["tokens"][0] == _reference(
            [5, 6], 5, temperature=0.8, top_k=20, seed=9
        )

        # the newer sampling knobs ride the broadcast payload too
        knobs = post({
            "tokens": [[7, 8, 9]], "max_new_tokens": 6,
            "min_new_tokens": 3, "frequency_penalty": 30.0,
            "logit_bias": {"11": -100},
        })
        assert knobs["tokens"][0] == _reference(
            [7, 8, 9], 6, min_new_tokens=3, frequency_penalty=30.0,
            logit_bias={11: -100.0},
        )
        assert 11 not in knobs["tokens"][0]

        # the per-knob parity matrix (n/stop/bias/logprobs/beam) is
        # topology-independent — prove it once at tp2; the dp2xtp2
        # boot proves what IS topology-bound (lockstep parity,
        # co-batching, streams, score) without re-paying ~6 request
        # rounds of 4-process collectives on this one-core box
        knob_matrix = n_procs == 2
        from containerpilot_tpu.workload.serve import InferenceServer

        ref = _reference([1, 2, 3], 6)
        if knob_matrix:
            # n > 1: one prompt, n samples as n pool slots — row i
            # draws from fold_in(seed, i), the batcher's convention
            two = post({"tokens": [[1, 2, 3]], "max_new_tokens": 6,
                        "n": 2})
            assert two["tokens"][0] == ref
            assert two["tokens"][1] == two["tokens"][0]  # greedy twins
            sampled2 = post({
                "tokens": [[5, 6]], "max_new_tokens": 5,
                "temperature": 0.8, "top_k": 20, "seed": 9, "n": 2,
            })
            assert sampled2["tokens"][0] == sampled["tokens"][0]
            assert sampled2["tokens"][1] == _reference(
                [5, 6], 5, temperature=0.8, top_k=20, seed=9, row=1,
            )

            # stop sequences: OpenAI exclusive trim, identical to the
            # single-host server's whole-row trim of the same output
            stop_seq = ref[2:4]
            stopped = post({"tokens": [[1, 2, 3]],
                            "max_new_tokens": 6,
                            "stop": [stop_seq]})
            assert stopped["tokens"][0] == \
                InferenceServer._trim_stops(
                    [list(ref)], [stop_seq]
                )[0]
            assert len(stopped["tokens"][0]) < len(ref)

            # logit_bias beyond the 16-slot fast path (the OpenAI-300
            # wide table): 20 bans hold, byte-parity with generate
            wb = post({
                "tokens": [[1, 2, 3]], "max_new_tokens": 6,
                "logit_bias": {str(i): -100.0 for i in range(20)},
            })
            assert wb["tokens"][0] == _reference(
                [1, 2, 3], 6,
                logit_bias={i: -100.0 for i in range(20)},
            )
            assert all(t >= 20 for t in wb["tokens"][0])

            # pod prefix reuse: turn 1 (>= MIN_REUSE) misses and
            # seeds every process's identical LRU; turn 2 extends the
            # shared history through the cached rows — byte parity
            # with the single-host reference either way, and the
            # frontend's stats show exactly one miss + one hit
            history = [(i * 5 + 2) % 128 for i in range(20)]
            t1 = post({"tokens": [history], "max_new_tokens": 5})
            assert t1["tokens"][0] == _reference(history, 5)
            turn2 = history + [7, 3]
            t2 = post({"tokens": [turn2], "max_new_tokens": 5,
                       "temperature": 0.6, "seed": 5})
            assert t2["tokens"][0] == _reference(
                turn2, 5, temperature=0.6, seed=5
            )
            with urllib.request.urlopen(
                f"{base}/v1/model", timeout=30
            ) as resp:
                pc_info = json.loads(resp.read().decode())
            assert pc_info["prefix_cache"]["entries"] == 2
            assert pc_info["prefix_cache"]["misses"] == 1
            assert pc_info["prefix_cache"]["hits"] == 1
            assert pc_info["prefix_cache"]["tokens_reused"] > 0

        # /v1/score rides the broadcast too: teacher-forced logprobs
        # match the single-host formula bit-for-bit
        req = urllib.request.Request(
            f"{base}/v1/score",
            data=json.dumps({"tokens": [[1, 2, 3, 4]]}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=240) as resp:
            scored = json.loads(resp.read().decode())
        from containerpilot_tpu.models.transformer import init_params
        from containerpilot_tpu.workload.modelcfg import (
            score_logprobs_fn,
        )

        s_cfg = _default_cfg()
        s_params = init_params(jax.random.PRNGKey(0), s_cfg)
        # pad to the pod's 16-multiple width convention, slice back —
        # the same function the endpoint jits
        toks = jnp.asarray([[1, 2, 3, 4] + [0] * 12], jnp.int32)
        want = [
            round(float(x), 6)
            for x in np.asarray(
                score_logprobs_fn(s_cfg)(s_params, toks)
            )[0][:3]
        ]
        assert scored["logprobs"][0] == want

        if knob_matrix:
            # logprobs echo: per-token logprobs of the trimmed output
            # via lockstep score rounds — the single-host echo numbers
            lp = post({"tokens": [[1, 2, 3]], "max_new_tokens": 6,
                       "logprobs": True})
            assert lp["tokens"][0] == ref
            echo_row = [1, 2, 3] + ref
            width = -(-len(echo_row) // 16) * 16
            picked = np.asarray(score_logprobs_fn(s_cfg)(
                s_params,
                jnp.asarray(
                    [echo_row + [0] * (width - len(echo_row))],
                    jnp.int32,
                ),
            ))[0]
            assert lp["logprobs"][0] == [
                round(float(x), 6) for x in picked[2:2 + len(ref)]
            ]

            # beam search: a one-shot lockstep round, byte-identical
            # to the single-host deterministic beam program
            from containerpilot_tpu.models.beam import beam_search

            beam = post({"tokens": [[1, 2, 3]], "max_new_tokens": 6,
                         "beam_width": 2})
            bt, _sc = beam_search(
                s_params, jnp.asarray([[1, 2, 3]], jnp.int32), s_cfg,
                max_new_tokens=6, max_len=48, beam_width=2,
            )
            assert beam["tokens"][0] == [
                int(t) for t in np.asarray(bt)
            ]

        # SSE streaming over the chunked lockstep rounds: deltas
        # concatenate to the non-streamed answer for the same request
        import http.client

        conn = http.client.HTTPConnection(
            "127.0.0.1", http_port, timeout=240
        )
        conn.request(
            "POST", "/v1/generate",
            json.dumps({"tokens": [[1, 2, 3]], "max_new_tokens": 6,
                        "stream": True}),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Content-Type") == "text/event-stream"
        events, buf = [], b""
        while True:
            data = resp.read1(65536)
            if not data:
                break
            buf += data
            while b"\n\n" in buf:
                raw, buf = buf.split(b"\n\n", 1)
                events.append(json.loads(raw[len(b"data: "):]))
        conn.close()
        assert events[-1]["done"] is True
        streamed = sum(
            (e["tokens"] for e in events if "tokens" in e), []
        )
        assert streamed == greedy["tokens"][0]
        assert events[-1]["count"] == len(streamed)

        # CONTINUOUS BATCHING across the pod: a non-streamed request
        # lands mid-flight next to a running stream (it joins the
        # pool at a chunk boundary instead of queueing behind the
        # whole generation), and BOTH outputs stay byte-identical to
        # their solo references
        conn2 = http.client.HTTPConnection(
            "127.0.0.1", http_port, timeout=240
        )
        conn2.request(
            "POST", "/v1/generate",
            json.dumps({"tokens": [[5, 6]], "max_new_tokens": 40,
                        "temperature": 0.8, "top_k": 20, "seed": 9,
                        "stream": True}),
            {"Content-Type": "application/json"},
        )
        resp2 = conn2.getresponse()
        assert resp2.status == 200
        buf2 = b""
        while b"\n\n" not in buf2:  # the stream is live
            buf2 += resp2.read1(65536)
        mid = post({"tokens": [[1, 2, 3]], "max_new_tokens": 6})
        assert mid["tokens"][0] == greedy["tokens"][0]
        while True:  # drain the co-batched stream to its end
            data = resp2.read1(65536)
            if not data:
                break
            buf2 += data
        conn2.close()
        events2 = [
            json.loads(raw[len(b"data: "):])
            for raw in buf2.split(b"\n\n")
            if raw.startswith(b"data: ")
        ]
        assert events2[-1]["done"] is True
        streamed2 = sum(
            (e["tokens"] for e in events2 if "tokens" in e), []
        )
        assert streamed2 == _reference(
            [5, 6], 40, temperature=0.8, top_k=20, seed=9
        )

        # disconnect mid-stream: the frontend evicts the slot at the
        # next round, the pool keeps serving everyone else
        conn = http.client.HTTPConnection(
            "127.0.0.1", http_port, timeout=240
        )
        conn.request(
            "POST", "/v1/generate",
            json.dumps({"tokens": [[5, 6]], "max_new_tokens": 40,
                        "stream": True}),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        buf = b""
        while b"\n\n" not in buf:
            buf += resp.read1(65536)
        resp.close()
        conn.close()
        again = post({"tokens": [[1, 2, 3]], "max_new_tokens": 6})
        assert again["tokens"][0] == greedy["tokens"][0]

        # observability parity: /v1/model reports the pod topology
        # and pool shape, /metrics carries the request/token counters
        info = json.loads(urllib.request.urlopen(
            f"{base}/v1/model", timeout=30
        ).read().decode())
        assert info["pod"]["num_processes"] == n_procs
        assert info["pod"]["mesh"] == {
            "data": dp, "seq": 1, "model": n_procs // dp,
        }
        assert info["slot_engine"]["slots"] == 4
        time.sleep(1)  # let the disconnected stream's close land
        metrics = urllib.request.urlopen(
            f"{base}/metrics", timeout=30
        ).read().decode()
        # plain 200s + 3 streamed 200s (the disconnected stream
        # still counts its 200); the knob matrix adds 6 at tp2 and
        # the prefix-reuse pair adds 2 more
        n_200 = 16.0 if knob_matrix else 8.0
        assert (
            'containerpilot_pod_requests_total'
            '{endpoint="generate",status="200"} %s' % n_200
        ) in metrics
        n_model = 2.0 if knob_matrix else 1.0
        assert (
            'containerpilot_pod_requests_total'
            '{endpoint="model",status="200"} %s' % n_model
        ) in metrics
        assert "containerpilot_pod_generated_tokens_total" in metrics


        # graceful pod shutdown: TERM on the frontend broadcasts the
        # stop; ALL processes exit 0
        procs[0].send_signal(15)
        for i, proc in enumerate(procs):
            assert proc.wait(timeout=60 * max(1, n_procs // 2)) == 0, (
                tmp_path / f"pod{i}.log"
            ).read_text()[-3000:]
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        catalog.terminate()
        catalog.wait(timeout=10)
        for fh in logs:
            fh.close()


def test_pod_frontend_parse_never_leaks_exceptions():
    """Adversarial bodies against the frontend's parse layer: every
    malformed request must raise the ValueError family the handlers
    turn into 422s — anything else would reach the broadcast loop,
    where an exception is deliberately pod-fatal."""
    import random

    from containerpilot_tpu.workload.serve_dist import _Frontend

    f = _Frontend("127.0.0.1", 0, max_len=48, vocab=512)
    rng = random.Random(0)
    atoms = [
        None, True, False, 0, 1, -1, 2**40, -2**40, 1.5, float("nan"),
        float("inf"), "x", "", [], {}, [None], [[]], [[1]], [[-1]],
        [[1, "a"]], [[True]], [[2**40]], {"1": 1}, [[1], [2]],
        [[1, 2, 3]],
    ]
    keys = [
        "tokens", "max_new_tokens", "temperature", "top_k", "top_p",
        "eos_id", "seed", "min_new_tokens", "presence_penalty",
        "frequency_penalty", "logit_bias", "n", "stop", "stream",
        "logprobs", "beam_width",
    ]
    ok = 0
    for _ in range(300):
        body = {
            k: rng.choice(atoms)
            for k in rng.sample(keys, rng.randrange(1, 6))
        }
        try:
            tokens = f._parse_single_row(body)
            f._parse_work(body, tokens)
            ok += 1
        except (ValueError, KeyError, TypeError, OverflowError):
            pass  # the 422 family the handlers catch
    # some random bodies are legal; the point is nothing ELSE raised
    assert ok >= 0


def test_pod_warmup_covers_serve_path():
    """The pod's no-post-grace-compiles invariant, in-process: after
    ``warm_pod``, serving a request at the warmed shapes — a plen-4
    admission with ARBITRARY sampling knobs (they are operands, not
    compile keys), the (slots, chunk) chunk program, the width-16
    scorer — compiles NOTHING. Post-grace compiles are what eat a
    production pod's watchdog deadline, so a regression that adds an
    un-warmed shape to the serve path must fail a test instead of
    wedging a pod. The detector is proven non-vacuous by an unwarmed
    prompt length compiling."""
    import logging

    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload import serve_dist as sd

    cfg = _default_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    mirror = sd._SlotMirror(cfg, params, 48, 4, 8)
    sd.warm_pod(mirror)

    records = []
    handler = logging.Handler()
    handler.emit = records.append
    jax_logger = logging.getLogger("jax")
    old_level = jax_logger.level
    jax.config.update("jax_log_compiles", True)
    jax_logger.addHandler(handler)
    jax_logger.setLevel(logging.DEBUG)

    def compiles():
        return [
            r.getMessage() for r in records
            if "ompil" in r.getMessage()
        ]

    def admit_round(tokens, slot, **knobs):
        work = {
            "tokens": tokens, "max_new": 9, "temperature": 0.0,
            "top_k": 0, "top_p": 0.0, "eos_id": -1, "seed": 0,
            "min_new": 0, "presence": 0.0, "frequency": 0.0,
            "logit_bias": {},
        }
        work.update(knobs)
        p = sd._payload_zeros(48, 4)
        p["op"] = np.asarray(sd.OP_ROUND, np.int32)
        sd._fill_admission(p, work, row_idx=0, slot=slot)
        p["run_chunk"] = np.asarray(1, np.int32)
        p["done"][slot] = 0
        sd._apply_round(mirror, p)

    try:
        # warmed shapes + aggressively different KNOB VALUES: zero
        # compiles (temperature/top_k/bias/penalties are operands of
        # the one chunk program, not compile keys)
        admit_round(
            [1, 2, 3, 4], slot=1, temperature=0.7, top_k=5, seed=3,
            min_new=2, presence=0.5, frequency=0.25,
            logit_bias={7: -5.0},
        )
        sc = sd._payload_zeros(48, 4)
        sc["prompt"][:9] = 1
        sc["plen"] = np.asarray(9, np.int32)
        np.asarray(jax.device_get(sd._score_pod(params, cfg, sc, 48)))
        assert not compiles(), compiles()
        # non-vacuous: an UNwarmed prompt length (11 — no other
        # in-process test prefills it) does compile and IS caught
        records.clear()
        admit_round(list(range(1, 12)), slot=2)
        assert compiles()
    finally:
        jax.config.update("jax_log_compiles", False)
        jax_logger.removeHandler(handler)
        jax_logger.setLevel(old_level)


def test_mirror_rounds_match_generate():
    """In-process parity for the device-resident slot mirror: the
    exact per-round device ops every pod process replays — admission
    row-writes into the state dict, chunk rounds under a churning
    broadcast done mask, retirement, and slot REUSE — byte-match solo
    generate. This is the single-process half of the 2-process
    co-batch parity story, and it pins the refactor that removed the
    per-round knob uploads and the torn-state barriers: a request
    admitted mid-flight must change nothing for the row already
    decoding, and a reused slot must carry nothing of its previous
    occupant."""
    from containerpilot_tpu.models.slots import append_chunk
    from containerpilot_tpu.models.transformer import init_params
    from containerpilot_tpu.workload import serve_dist as sd

    cfg = _default_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    S, chunk = 4, 8
    mirror = sd._SlotMirror(cfg, params, 48, S, chunk)
    sd.warm_pod(mirror)

    def round_payload(mask, admit_work=None, slot=0, row_idx=0):
        p = sd._payload_zeros(48, S)
        p["op"] = np.asarray(sd.OP_ROUND, np.int32)
        if admit_work is not None:
            sd._fill_admission(p, admit_work, row_idx=row_idx,
                               slot=slot)
        p["run_chunk"] = np.asarray(1, np.int32)
        p["done"] = np.asarray(mask, np.int32)
        return p

    def work(tokens, max_new, **kw):
        w = {
            "tokens": tokens, "max_new": max_new, "temperature": 0.0,
            "top_k": 0, "top_p": 0.0, "eos_id": -1, "seed": 0,
            "min_new": 0, "presence": 0.0, "frequency": 0.0,
            "logit_bias": {},
        }
        w.update(kw)
        return w

    # A (slot 0, greedy, 20 new) decodes alone for one round...
    a_work = work([1, 2, 3, 4], 20)
    em_a: list = []
    first, toks = sd._apply_round(
        mirror, round_payload([0, 1, 1, 1], a_work, slot=0)
    )
    em_a.append(first)
    append_chunk(em_a, toks[0], 20, -1)
    # ...then B (slot 1, SAMPLED — different knobs mid-flight) joins
    b_work = work([5, 6, 7, 8], 12, temperature=0.8, top_k=20, seed=9)
    em_b: list = []
    first, toks = sd._apply_round(
        mirror, round_payload([0, 0, 1, 1], b_work, slot=1)
    )
    em_b.append(first)
    append_chunk(em_a, toks[0], 20, -1)
    append_chunk(em_b, toks[1], 12, -1)
    # third co-batched round finishes A (20 = 1 + 8 + 8 + 3)
    _f, toks = sd._apply_round(mirror, round_payload([0, 0, 1, 1]))
    append_chunk(em_a, toks[0], 20, -1)
    append_chunk(em_b, toks[1], 12, -1)
    assert len(em_a) == 20
    # A retired (mask flips its slot dead); B finishes alone
    _f, toks = sd._apply_round(mirror, round_payload([1, 0, 1, 1]))
    append_chunk(em_b, toks[1], 12, -1)
    assert len(em_b) == 12
    assert em_a == _reference([1, 2, 3, 4], 20)
    assert em_b == _reference(
        [5, 6, 7, 8], 12, temperature=0.8, top_k=20, seed=9
    )
    # slot 0 REUSED: the admission row-write + pool insert must leave
    # nothing of A (and the sampled knobs of B must not leak into a
    # greedy neighbor)
    c_work = work([9, 8, 7, 6], 9, seed=3)
    em_c: list = []
    first, toks = sd._apply_round(
        mirror, round_payload([0, 0, 1, 1], c_work, slot=0)
    )
    em_c.append(first)
    append_chunk(em_c, toks[0], 9, -1)
    _f, toks = sd._apply_round(mirror, round_payload([0, 1, 1, 1]))
    append_chunk(em_c, toks[0], 9, -1)
    assert len(em_c) == 9
    assert em_c == _reference([9, 8, 7, 6], 9, seed=3)


def test_pod_model_prefix_schema_stable_across_boot(run):
    """/v1/model's prefix_cache block must carry the SAME keys during
    the boot window (before warm_pod hands the mirror's live cache to
    the frontend) as after it — a client polling at startup must not
    see the schema change shape."""
    from containerpilot_tpu.workload.serve_dist import _Frontend
    from containerpilot_tpu.workload.serve_prefix import PrefixCache

    f = _Frontend(
        "127.0.0.1", 0, max_len=48, vocab=128,
        pod_info={"prefix_cache": {"entries": 2}}, prefix_entries=2,
    )
    before = json.loads(run(f._model(None)).body.decode())
    assert before["prefix_cache"] == {
        "entries": 2, "hits": 0, "misses": 0, "tokens_reused": 0,
    }
    # after warm: the live cache (with counted traffic) — same keys
    pc = PrefixCache(2)
    pc.stats["misses"] = 1
    f.prefix_cache = pc
    after = json.loads(run(f._model(None)).body.decode())
    assert set(after["prefix_cache"]) == set(before["prefix_cache"])
    assert after["prefix_cache"]["misses"] == 1
    # unconfigured cache: no block at all, before or after (the
    # single-host server's contract)
    bare = _Frontend("127.0.0.1", 0, max_len=48, vocab=128)
    none = json.loads(run(bare._model(None)).body.decode())
    assert "prefix_cache" not in none


def test_pod_text_completions(tmp_path):
    """--text on the pod: /v1/completions encodes through the byte
    tokenizer, rides the broadcast decode, and byte-matches the
    single-host text contract — streamed (UTF-8 holdback) and not,
    with stop strings plumbed through the shared parser. The pod also
    runs --draft-layers here: the greedy non-streamed completion
    routes through the one-shot lockstep SPECULATIVE round (idle
    pool), and the streamed one through the slot chunks — both must
    byte-match the same reference, proving spec output identity on
    the pod."""
    catalog_port, coord_port, http_port = (
        _free_port(), _free_port(), _free_port()
    )
    env = _sub_env()
    # spec output is byte-identical to plain greedy BY DESIGN, so
    # parity alone can't prove the route; the debug round log pins it
    env["CONTAINERPILOT_POD_DEBUG"] = "1"
    catalog = subprocess.Popen(
        [sys.executable, "-m", "containerpilot_tpu",
         "-catalog-server", f"127.0.0.1:{catalog_port}"],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    procs = []
    logs = []
    try:
        _wait_catalog(catalog_port)
        wrapper = _write_cpu_wrapper(tmp_path)
        for pid in (0, 1):
            fh = open(tmp_path / f"pod{pid}.log", "w")
            logs.append(fh)
            procs.append(subprocess.Popen(
                [sys.executable, "-u", str(wrapper),
                 "--process-id", str(pid), "--num-processes", "2",
                 "--catalog", f"127.0.0.1:{catalog_port}",
                 "--coordinator-port", str(coord_port),
                 "--advertise-address", "127.0.0.1",
                 "--host", "127.0.0.1", "--port", str(http_port),
                 "--text", "--vocab", "512", "--max-len", "48",
                 "--d-model", "64", "--n-layers", "2",
                 "--n-heads", "2",
                 "--draft-layers", "1", "--speculate", "2"],
                cwd=REPO, env=env, stdout=fh, stderr=subprocess.STDOUT,
            ))
        base = f"http://127.0.0.1:{http_port}"
        _wait_pod_healthy(base, procs, tmp_path, 2, 240)

        def post(path, body):
            req = urllib.request.Request(
                f"{base}{path}",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=240) as resp:
                    return resp.status, json.loads(resp.read().decode())
            except urllib.error.HTTPError as exc:
                return exc.code, exc.read().decode()

        status, comp = post(
            "/v1/completions", {"prompt": "hi", "max_new_tokens": 6}
        )
        assert status == 200

        # single-host reference: same encode, eos default, decode
        from containerpilot_tpu.models.transformer import (
            TransformerConfig,
        )
        from containerpilot_tpu.workload.modelcfg import derive_d_ff
        from containerpilot_tpu.workload.text import ByteTokenizer

        t_cfg = TransformerConfig(
            vocab_size=512, d_model=64, n_heads=2, n_layers=2,
            d_ff=derive_d_ff(64), max_seq_len=48,
        )
        tok = ByteTokenizer(512)
        want = _reference(
            tok.encode("hi"), 6, cfg=t_cfg, eos_id=tok.EOS
        )
        assert comp["tokens"] == want
        assert comp["text"] == tok.decode(comp["tokens"])
        # that greedy request ran the speculative path (idle pool,
        # no sampling knobs): BOTH processes log the SPEC round —
        # parity alone couldn't distinguish spec from the slot pool,
        # since their outputs are identical by design
        time.sleep(0.5)
        for pid in (0, 1):
            assert "SPEC plen=" in (
                tmp_path / f"pod{pid}.log"
            ).read_text(), f"pod{pid} never ran the spec round"
        info = json.loads(urllib.request.urlopen(
            f"{base}/v1/model", timeout=30
        ).read().decode())
        assert info["speculative"] == {
            "draft_layers": 1, "speculate": 2,
        }

        # stop strings plumb through the shared parser: a never-
        # matching stop leaves the completion untouched (200, not the
        # round-4 422 carve-out)
        s1, with_stop = post(
            "/v1/completions",
            {"prompt": "hi", "max_new_tokens": 6, "stop": ["\x00zz"]},
        )
        assert s1 == 200 and with_stop["tokens"] == want

        # streamed text: UTF-8-holdback deltas concatenate to the
        # non-streamed text AND ids (the single-host contract,
        # pod-shaped)
        import http.client

        conn = http.client.HTTPConnection(
            "127.0.0.1", http_port, timeout=240
        )
        conn.request(
            "POST", "/v1/completions",
            json.dumps({"prompt": "hi", "max_new_tokens": 6,
                        "stream": True}),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Content-Type") == "text/event-stream"
        buf = b""
        while True:
            data = resp.read1(65536)
            if not data:
                break
            buf += data
        conn.close()
        events = [
            json.loads(raw[len(b"data: "):])
            for raw in buf.split(b"\n\n")
            if raw.startswith(b"data: ")
        ]
        assert events[-1]["done"] is True
        streamed_ids = sum(
            (e["tokens"] for e in events if "tokens" in e), []
        )
        streamed_text = "".join(
            e["text"] for e in events if "text" in e
        )
        assert streamed_ids == comp["tokens"]
        assert streamed_text == comp["text"]

        procs[0].send_signal(15)
        for i, proc in enumerate(procs):
            assert proc.wait(timeout=60) == 0, (
                tmp_path / f"pod{i}.log"
            ).read_text()[-3000:]
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        catalog.terminate()
        catalog.wait(timeout=10)
        for fh in logs:
            fh.close()


def test_pod_restores_checkpoint_in_lockstep(tmp_path):
    """--checkpoint-dir on the pod: every process restores the SAME
    trained weights through orbax's global barriers onto the pod
    mesh (saved on a DIFFERENT, single-process topology — the
    restore re-shards), and answers change accordingly: byte-parity
    with a single-device restore of the same checkpoint. The pod
    also serves ``--kv-int8`` here: every process quantizes the KV
    cache identically, so the lockstep answer byte-matches a
    single-device kv-int8 decode of the same weights (the int8-KV
    serving accelerator composed with the pod)."""
    import numpy as np

    # train a couple of steps single-process to produce the artifact
    ck = tmp_path / "ck"
    worker = os.path.join(REPO, "tests", "capstone_worker.py")
    env = _sub_env()
    trained = subprocess.run(
        [sys.executable, worker, "--process-id", "0",
         "--num-processes", "1", "--tp", "1", "--steps", "2",
         "--global-batch", "4", "--checkpoint-dir", str(ck),
         "--out", str(tmp_path / "t.json")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert trained.returncode == 0, trained.stderr[-2000:]

    # the capstone worker's model config, serving-shaped
    model_flags = [
        "--max-len", "48", "--d-model", "32", "--n-layers", "1",
        "--n-heads", "2", "--vocab", "64",
    ]
    catalog_port, coord_port, http_port = (
        _free_port(), _free_port(), _free_port()
    )
    catalog = subprocess.Popen(
        [sys.executable, "-m", "containerpilot_tpu",
         "-catalog-server", f"127.0.0.1:{catalog_port}"],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    procs = []
    logs = []
    try:
        _wait_catalog(catalog_port)
        wrapper = _write_cpu_wrapper(tmp_path)
        for pid in (0, 1):
            fh = open(tmp_path / f"pod{pid}.log", "w")
            logs.append(fh)
            procs.append(subprocess.Popen(
                [sys.executable, "-u", str(wrapper),
                 "--process-id", str(pid), "--num-processes", "2",
                 "--catalog", f"127.0.0.1:{catalog_port}",
                 "--coordinator-port", str(coord_port),
                 "--advertise-address", "127.0.0.1",
                 "--host", "127.0.0.1", "--port", str(http_port),
                 "--checkpoint-dir", str(ck), "--kv-int8"]
                + model_flags,
                cwd=REPO, env=env, stdout=fh, stderr=subprocess.STDOUT,
            ))
        base = f"http://127.0.0.1:{http_port}"
        _wait_pod_healthy(base, procs, tmp_path, 2, 240)

        req = urllib.request.Request(
            f"{base}/v1/generate",
            data=json.dumps(
                {"tokens": [[1, 2, 3]], "max_new_tokens": 6}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=240) as resp:
            got = json.loads(resp.read().decode())["tokens"][0]
        assert "pod serving checkpoint step 2" in (
            tmp_path / "pod0.log"
        ).read_text()

        # reference: single-device restore of the same checkpoint,
        # through the module's ONE parity recipe
        from containerpilot_tpu.models.transformer import (
            TransformerConfig,
        )
        from containerpilot_tpu.parallel import MeshPlan, make_mesh
        from containerpilot_tpu.workload.modelcfg import (
            derive_d_ff,
            restore_params_only,
        )

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=2, n_layers=1,
            d_ff=derive_d_ff(32), max_seq_len=48, kv_int8=True,
        )
        one_dev = make_mesh(
            jax.devices()[:1], plan=MeshPlan(data=1, model=1)
        )
        params, step = restore_params_only(cfg, one_dev, str(ck))
        assert int(step) == 2
        assert got == _reference([1, 2, 3], 6, cfg=cfg, params=params)
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        catalog.terminate()
        catalog.wait(timeout=10)
        for fh in logs:
            fh.close()


def test_pod_serves_moe_int8_lora(tmp_path):
    """The load-time model knobs compose on the pod in ONE boot:
    ``--moe-experts`` (experts shard over the model axis, all-to-alls
    in lockstep), ``--lora-dir`` (adapter restored through orbax's
    global barriers and merged before quantization), ``--int8``
    (weight-only; every process quantizes its shards identically),
    and ``--window`` (sliding-window attention: the pod's slot pool
    runs per-slot ring caches). The greedy request below decodes past
    the window boundary (3 prompt + 6 new > window 8), so the ring
    actually wraps. Byte parity against a single-device reference
    that applies the SAME transforms in the same order to the same
    PRNGKey(0) init."""
    from containerpilot_tpu.models.transformer import (
        TransformerConfig, init_params,
    )
    from containerpilot_tpu.parallel import (
        MeshPlan,
        make_lora_train_step,
        make_mesh,
        restore_params,
        save_checkpoint,
    )
    from containerpilot_tpu.workload.modelcfg import derive_d_ff

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1,
        d_ff=derive_d_ff(32), max_seq_len=48, moe_experts=2,
        window=8,
    )
    one_dev = make_mesh(jax.devices()[:1], plan=MeshPlan(1, 1))

    # train a tiny adapter so the merge provably changes the weights
    lora_dir = tmp_path / "lora"
    init_fn, step_fn, abstract = make_lora_train_step(
        cfg, one_dev, rank=4, learning_rate=1e-2
    )
    state = init_fn(jax.random.PRNGKey(3))
    base = init_params(jax.random.PRNGKey(0), cfg)  # the pod's init
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 17), 0, cfg.vocab_size, jnp.int32
    )
    for _ in range(3):
        state, _loss = step_fn(state, base, tokens)
    save_checkpoint(str(lora_dir), 3, state)

    model_flags = [
        "--max-len", "48", "--d-model", "32", "--n-layers", "1",
        "--n-heads", "2", "--vocab", "64", "--moe-experts", "2",
        "--int8", "--lora-dir", str(lora_dir), "--lora-rank", "4",
        "--window", "8",
    ]
    catalog_port, coord_port, http_port = (
        _free_port(), _free_port(), _free_port()
    )
    env = _sub_env()
    catalog = subprocess.Popen(
        [sys.executable, "-m", "containerpilot_tpu",
         "-catalog-server", f"127.0.0.1:{catalog_port}"],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    procs = []
    logs = []
    try:
        _wait_catalog(catalog_port)
        wrapper = _write_cpu_wrapper(tmp_path)
        for pid in (0, 1):
            fh = open(tmp_path / f"pod{pid}.log", "w")
            logs.append(fh)
            procs.append(subprocess.Popen(
                [sys.executable, "-u", str(wrapper),
                 "--process-id", str(pid), "--num-processes", "2",
                 "--catalog", f"127.0.0.1:{catalog_port}",
                 "--coordinator-port", str(coord_port),
                 "--advertise-address", "127.0.0.1",
                 "--host", "127.0.0.1", "--port", str(http_port)]
                + model_flags,
                cwd=REPO, env=env, stdout=fh, stderr=subprocess.STDOUT,
            ))
        base_url = f"http://127.0.0.1:{http_port}"
        _wait_pod_healthy(base_url, procs, tmp_path, 2, 240)

        log0 = (tmp_path / "pod0.log").read_text()
        assert "pod merged lora adapter (rank 4, step 3)" in log0
        assert "pod int8 weight-only params" in log0

        with urllib.request.urlopen(
            f"{base_url}/v1/model", timeout=30
        ) as resp:
            info = json.loads(resp.read().decode())
        assert info["moe_experts"] == 2 and info["int8"] is True
        assert info["lora"] == {"rank": 4}
        assert info["window"] == 8

        def post(body):
            req = urllib.request.Request(
                f"{base_url}/v1/generate",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=240) as resp:
                return json.loads(resp.read().decode())

        # reference: same init -> same adapter merge -> same int8
        from containerpilot_tpu.models.lora import apply_lora
        from containerpilot_tpu.models.quantized import (
            quantize_model_params,
        )

        adapter, step_n = restore_params(str(lora_dir), abstract)
        assert int(step_n) == 3
        ref_params = quantize_model_params(
            apply_lora(base, adapter, cfg)
        )

        greedy = post({"tokens": [[1, 2, 3]], "max_new_tokens": 6})
        assert greedy["tokens"][0] == _reference(
            [1, 2, 3], 6, cfg=cfg, params=ref_params
        )
        sampled = post({
            "tokens": [[5, 6]], "max_new_tokens": 5,
            "temperature": 0.7, "top_k": 12, "seed": 4,
        })
        assert sampled["tokens"][0] == _reference(
            [5, 6], 5, cfg=cfg, params=ref_params,
            temperature=0.7, top_k=12, seed=4,
        )
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        catalog.terminate()
        catalog.wait(timeout=10)
        for fh in logs:
            fh.close()


def test_pod_serves_cp_long_prompt(tmp_path):
    """``--sp``: context-parallel admission on the pod. Long prompts
    ring their prefill over a 2-process seq axis (each process holds
    half the prompt's activations) and then decode on the replicated
    slot pool; short prompts take the plain path. The reference for
    the cp path is ``cp_generate`` on an IN-PROCESS seq=2 mesh — ring
    numerics against ring numerics, so parity is exact (plain-prefill
    references would differ by the ring's softmax reassociation under
    bf16). Also covered: the non-axis-divisible remainder (one extend
    chunk), /v1/model topology, and the --sp composition rejections."""
    from containerpilot_tpu.models.decode import generate_from_cache
    from containerpilot_tpu.models.transformer import (
        TransformerConfig, init_params,
    )
    from containerpilot_tpu.parallel import MeshPlan, make_mesh
    from containerpilot_tpu.parallel.context import (
        cp_head_buckets,
        cp_prefill_with_remainder,
        pick_cp_head,
    )
    from containerpilot_tpu.workload.modelcfg import derive_d_ff
    from containerpilot_tpu.workload.serve import InferenceServer

    max_len = 96
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1,
        d_ff=derive_d_ff(32), max_seq_len=max_len,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    ref_mesh = make_mesh(
        jax.devices()[:2], plan=MeshPlan(data=1, model=1, seq=2)
    )
    # the pod's exact recipe: startup-bucketed ring head + local
    # remainder extend + decode from the gathered cache (ring numerics
    # against ring numerics — a plain-prefill reference would differ
    # by the ring's softmax reassociation under bf16)
    buckets = cp_head_buckets(24, max_len, 2)
    assert buckets == [24, 48]

    def cp_ref(tokens, max_new, seed=0, **kw):
        head = pick_cp_head(len(tokens), buckets)
        assert head > 0
        logits, cache = cp_prefill_with_remainder(
            params, np.asarray([tokens], np.int32), cfg, ref_mesh,
            max_len, head=head,
        )
        out = generate_from_cache(
            params, cache, logits, cfg, max_new, pos=len(tokens),
            rng=jnp.stack(
                [jax.random.fold_in(jax.random.PRNGKey(seed), 0)]
            ),
            **kw,
        )
        rows = [[int(t) for t in np.asarray(out)[0]]]
        return InferenceServer._trim(rows, max_new, -1)[0]

    model_flags = [
        "--max-len", str(max_len), "--d-model", "32",
        "--n-layers", "1", "--n-heads", "2", "--vocab", "64",
        "--sp", "2", "--cp-min-len", "24",
    ]
    catalog_port, coord_port, http_port = (
        _free_port(), _free_port(), _free_port()
    )
    env = _sub_env()
    catalog = subprocess.Popen(
        [sys.executable, "-m", "containerpilot_tpu",
         "-catalog-server", f"127.0.0.1:{catalog_port}"],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    procs = []
    logs = []
    try:
        _wait_catalog(catalog_port)
        wrapper = _write_cpu_wrapper(tmp_path)
        for pid in (0, 1):
            fh = open(tmp_path / f"pod{pid}.log", "w")
            logs.append(fh)
            procs.append(subprocess.Popen(
                [sys.executable, "-u", str(wrapper),
                 "--process-id", str(pid), "--num-processes", "2",
                 "--catalog", f"127.0.0.1:{catalog_port}",
                 "--coordinator-port", str(coord_port),
                 "--advertise-address", "127.0.0.1",
                 "--host", "127.0.0.1", "--port", str(http_port)]
                + model_flags,
                cwd=REPO, env=env, stdout=fh, stderr=subprocess.STDOUT,
            ))
        base_url = f"http://127.0.0.1:{http_port}"
        _wait_pod_healthy(base_url, procs, tmp_path, 2, 240)

        with urllib.request.urlopen(
            f"{base_url}/v1/model", timeout=30
        ) as resp:
            info = json.loads(resp.read().decode())
        assert info["cp"] == {"seq": 2, "min_len": 24}
        assert info["pod"]["mesh"] == {"data": 1, "seq": 2, "model": 1}

        def post(body):
            req = urllib.request.Request(
                f"{base_url}/v1/generate",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=240) as resp:
                return json.loads(resp.read().decode())

        # 40 tokens with buckets [24, 48]: head 24 rings, the
        # 16-token remainder extends locally in one 16-chunk
        long_even = [(i * 7 + 3) % 64 for i in range(40)]
        got = post({"tokens": [long_even], "max_new_tokens": 8})
        assert got["tokens"][0] == cp_ref(long_even, 8)

        # 41 tokens: head 24 rings, remainder 17 extends as 16 + 1
        # (the power-of-two decomposition's < axis tail)
        long_odd = long_even + [11]
        got = post({"tokens": [long_odd], "max_new_tokens": 8})
        assert got["tokens"][0] == cp_ref(long_odd, 8)

        # the sampling contract rides the cp admission unchanged
        sampled = post({
            "tokens": [long_even], "max_new_tokens": 6,
            "temperature": 0.8, "top_k": 12, "seed": 9,
        })
        assert sampled["tokens"][0] == cp_ref(
            long_even, 6, seed=9, temperature=0.8, top_k=12,
        )

        # short prompts stay on the plain replicated path
        short = post({"tokens": [[1, 2, 3]], "max_new_tokens": 6})
        assert short["tokens"][0] == _reference(
            [1, 2, 3], 6, cfg=cfg, params=params
        )
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        catalog.terminate()
        catalog.wait(timeout=10)
        for fh in logs:
            fh.close()

    # composition rejections fail fast, before any rendezvous
    for extra, msg in (
        (["--window", "8"], b"--sp does not compose with --window"),
        (["--draft-layers", "1"],
         b"--sp does not compose with --draft-layers"),
    ):
        res = subprocess.run(
            [sys.executable, str(_write_cpu_wrapper(tmp_path)),
             "--process-id", "0", "--num-processes", "2",
             "--catalog", "127.0.0.1:1", "--sp", "2"] + extra
            + ["--max-len", "96", "--d-model", "32", "--n-layers",
               "2", "--n-heads", "2", "--vocab", "64"],
            cwd=REPO, env=_sub_env(), capture_output=True, timeout=120,
        )
        assert res.returncode != 0
        assert msg in res.stderr + res.stdout


def test_pod_watchdog_turns_wedged_follower_into_exit(tmp_path):
    """A follower that stops making progress WITHOUT dying used to
    hang the frontend's collectives forever (the serve_dist docstring
    conceded as much in round 3). With --watchdog, the idle-heartbeat
    broadcast bounds every process's cycle time, so the wedge trips
    EVERY pod member's decode-progress deadline: all processes
    hard-exit 86 for a supervisor to restart."""
    catalog_port, coord_port, http_port = (
        _free_port(), _free_port(), _free_port()
    )
    wedge = tmp_path / "wedge"
    env = _sub_env()
    catalog = subprocess.Popen(
        [sys.executable, "-m", "containerpilot_tpu",
         "-catalog-server", f"127.0.0.1:{catalog_port}"],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    procs = []
    logs = []
    try:
        _wait_catalog(catalog_port)
        wrapper = _write_cpu_wrapper(tmp_path)
        for pid in (0, 1):
            fh = open(tmp_path / f"pod{pid}.log", "w")
            logs.append(fh)
            procs.append(subprocess.Popen(
                [sys.executable, "-u", str(wrapper),
                 "--process-id", str(pid), "--num-processes", "2",
                 "--catalog", f"127.0.0.1:{catalog_port}",
                 "--coordinator-port", str(coord_port),
                 "--advertise-address", "127.0.0.1",
                 "--host", "127.0.0.1", "--port", str(http_port),
                 "--watchdog", "6", "--startup-grace", "240",
                 "--wedge-file", str(wedge)]
                + MODEL_FLAGS,
                cwd=REPO, env=env, stdout=fh, stderr=subprocess.STDOUT,
            ))
        base = f"http://127.0.0.1:{http_port}"
        _wait_pod_healthy(base, procs, tmp_path, 2, 240)

        wedge.write_text("1")  # the follower consumes this and wedges
        for i, proc in enumerate(procs):
            rc = proc.wait(timeout=120)
            assert rc == 86, (
                f"pod{i} rc={rc}:\n"
                + (tmp_path / f"pod{i}.log").read_text()[-3000:]
            )
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        catalog.terminate()
        catalog.wait(timeout=10)
        for fh in logs:
            fh.close()


def _pod_supervisor_config(
    tmp_path, idx, n_procs, catalog_port, coord_port, http_port,
    wrapper, wedge,
):
    exec_argv = [
        sys.executable, "-u", str(wrapper),
        "--process-id", str(idx), "--num-processes", str(n_procs),
        "--catalog", f"127.0.0.1:{catalog_port}",
        "--coordinator-port", str(coord_port),
        "--advertise-address", "127.0.0.1",
        "--host", "127.0.0.1", "--port", str(http_port),
        "--dp", "2",
        # the deadline must exceed the slowest LEGITIMATE cycle; the
        # test's requests reuse the warmed (plen 4, bucket 16) shape
        # so no cycle carries a compile, but 4 processes share one
        # core here — keep slack
        "--watchdog", "20", "--startup-grace", "420",
    ] + MODEL_FLAGS
    if idx == 1:  # exactly one follower carries the fault injector
        exec_argv += ["--wedge-file", str(wedge)]
    config = {
        "stopTimeout": "15s",
        # four supervisors on one box: the default control-socket
        # path would collide
        "control": {"socket": str(tmp_path / f"cp{idx}.socket")},
        "logging": {"level": "INFO", "format": "default",
                    "output": "stdout"},
        "jobs": [
            {
                "name": f"pod{idx}",
                "exec": exec_argv,
                # absorbs: the watchdog exit plus rendezvous races
                # while the pod re-forms
                "restarts": 6,
            }
        ],
    }
    path = tmp_path / f"pod{idx}.json5"
    path.write_text(json.dumps(config))
    return str(path)


def test_supervised_pod_recovers_from_wedged_follower(tmp_path):
    """The serving capstone at n=4 on a 2x2 dp x tp mesh: a follower
    wedges mid-flight; every pod member's watchdog exits 86; the four
    supervisors apply restart budgets; the reincarnated pod
    re-rendezvouses through the catalog (process 0 re-registers the
    coordinator) and serves byte-identical answers again."""
    n_procs = 4
    catalog_port, coord_port, http_port = (
        _free_port(), _free_port(), _free_port()
    )
    wedge = tmp_path / "wedge"
    env = _sub_env()
    # restart speed is the point of the shared compile cache
    # (serve_dist calls enable_compile_cache): the reincarnated pod
    # re-warms from cached executables, shrinking exactly the window
    # this test measures
    env["CONTAINERPILOT_COMPILE_CACHE"] = str(tmp_path / "xla-cache")
    catalog = subprocess.Popen(
        [sys.executable, "-m", "containerpilot_tpu",
         "-catalog-server", f"127.0.0.1:{catalog_port}"],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    sups = []
    logs = []
    try:
        _wait_catalog(catalog_port)
        wrapper = _write_cpu_wrapper(tmp_path)
        for idx in range(n_procs):
            cfg = _pod_supervisor_config(
                tmp_path, idx, n_procs, catalog_port, coord_port,
                http_port, wrapper, wedge,
            )
            fh = open(tmp_path / f"sup{idx}.log", "w")
            logs.append(fh)
            sups.append(subprocess.Popen(
                [sys.executable, "-m", "containerpilot_tpu",
                 "-config", cfg],
                cwd=REPO, env=env, stdout=fh, stderr=subprocess.STDOUT,
            ))
        base = f"http://127.0.0.1:{http_port}"
        _wait_pod_healthy(base, sups, tmp_path, n_procs, 600,
                          log_prefix="sup")

        def post(body, timeout=240):
            req = urllib.request.Request(
                f"{base}/v1/generate",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read().decode())

        # 4-token prompts ride the warmed (plen 4, bucket 16) decode
        # program: no request-triggered compile can outlast the
        # watchdog deadline on this single-core box
        before = post({"tokens": [[1, 2, 3, 4]], "max_new_tokens": 6})
        assert before["tokens"][0] == _reference([1, 2, 3, 4], 6)

        # inject the wedge; the pod must go DOWN (health unreachable
        # or 503) as the watchdogs fire...
        wedge.write_text("1")
        deadline = time.monotonic() + 180
        while True:
            try:
                urllib.request.urlopen(f"{base}/health", timeout=2)
                if time.monotonic() > deadline:
                    pytest.fail("pod never went unhealthy after wedge")
                time.sleep(0.5)
            except Exception:
                break

        # ...and come BACK: supervisors restart the members, the pod
        # re-rendezvouses, warms, and serves the same answer
        _wait_pod_healthy(base, sups, tmp_path, n_procs, 600,
                          log_prefix="sup")
        # greedy again: the sampled-path compile belongs to the
        # non-watchdog pod tests; here every cycle must stay far
        # under the deadline
        after = post({"tokens": [[5, 6, 7, 8]], "max_new_tokens": 5})
        assert after["tokens"][0] == _reference([5, 6, 7, 8], 5)

        # graceful teardown: stop every supervisor; each stops its pod
        # member (the frontend broadcasts shutdown) without burning a
        # restart, and exits 0
        for proc in sups:
            proc.send_signal(15)
        for i, proc in enumerate(sups):
            rc = proc.wait(timeout=120)
            assert rc == 0, (
                f"sup{i} rc={rc}:\n"
                + (tmp_path / f"sup{i}.log").read_text()[-3000:]
            )
    finally:
        for proc in sups:
            if proc.poll() is None:
                proc.terminate()
        for proc in sups:
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
        catalog.terminate()
        catalog.wait(timeout=10)
        for fh in logs:
            fh.close()
